"""Multiprocess DataLoader workers (ref: python/paddle/fluid/dataloader/
dataloader_iter.py:162 _DataLoaderIterSingleProcess / :370
_DataLoaderIterMultiProcess + worker.py _worker_loop: subprocess workers fed
an index queue, returning batches through a result queue, large arrays moved
via shared memory).

TPU-native framing: workers do the GIL-bound numpy work (decode, augment);
the PARENT does collate (which may build jax Arrays — children never touch
jax, so forked children cannot deadlock XLA runtime state). Arrays over a
size threshold cross the process boundary through
``multiprocessing.shared_memory`` instead of being pickled through the pipe.

Order semantics match the reference: batch k of the sampler is yielded k-th
(an out-of-order reorder buffer holds early arrivals); for IterableDataset
each worker iterates its own replica (shard with ``get_worker_info()``) and
completed batches are yielded round-robin by worker for determinism.
"""
from __future__ import annotations

import itertools
import os
import queue
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

_SHM_MIN_BYTES = 1 << 16  # arrays >= 64KB go through shared memory


@dataclass
class WorkerInfo:
    id: int
    num_workers: int
    seed: int
    dataset: Any


_worker_info: Optional[WorkerInfo] = None


def get_worker_info() -> Optional[WorkerInfo]:
    """Inside a worker process: (id, num_workers, seed, dataset); None in the
    main process. Ref fluid/dataloader/worker.py get_worker_info."""
    return _worker_info


# --------------------------------------------------------------------------
# shared-memory transport
# --------------------------------------------------------------------------


class _ShmRef:
    """Pickled placeholder for a large ndarray living in a SharedMemory
    segment; the parent reconstructs and unlinks."""

    __slots__ = ("name", "shape", "dtype")

    def __init__(self, name, shape, dtype):
        self.name, self.shape, self.dtype = name, shape, str(dtype)


def _rebuild_seq(obj, items):
    """Rebuild a list/tuple preserving namedtuple types (their constructors
    take positional fields, not one iterable)."""
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):
        return type(obj)(*items)
    return type(obj)(items)


def _encode(obj, use_shm: bool):
    """Recursively swap big ndarrays for _ShmRefs."""
    if not use_shm:
        return obj
    if isinstance(obj, np.ndarray) and obj.nbytes >= _SHM_MIN_BYTES:
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(create=True, size=obj.nbytes)
        dst = np.ndarray(obj.shape, obj.dtype, buffer=shm.buf)
        dst[...] = obj
        ref = _ShmRef(shm.name, obj.shape, obj.dtype)
        shm.close()  # parent unlinks after copy-out
        try:
            # ownership transfers to the parent (it unlinks in _decode);
            # unregister here so this process's resource_tracker doesn't
            # warn about the already-unlinked segment at exit
            from multiprocessing import resource_tracker

            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:
            pass
        return ref
    if isinstance(obj, dict):
        return {k: _encode(v, use_shm) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return _rebuild_seq(obj, [_encode(v, use_shm) for v in obj])
    return obj


def _decode(obj):
    if isinstance(obj, _ShmRef):
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(name=obj.name)
        try:
            arr = np.array(
                np.ndarray(obj.shape, obj.dtype, buffer=shm.buf))  # copy out
        finally:
            shm.close()
            try:
                shm.unlink()
            except FileNotFoundError:
                pass
        return arr
    if isinstance(obj, dict):
        return {k: _decode(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return _rebuild_seq(obj, [_decode(v) for v in obj])
    return obj


def _to_plain(sample):
    """Make samples picklable/shm-able: Tensors -> numpy before crossing the
    process boundary (children must not ship device arrays)."""
    from ..framework.core import Tensor

    if isinstance(sample, Tensor):
        return np.asarray(sample.value)
    if isinstance(sample, dict):
        return {k: _to_plain(v) for k, v in sample.items()}
    if isinstance(sample, (list, tuple)):
        return _rebuild_seq(sample, [_to_plain(v) for v in sample])
    return sample


def _safe_exc(e):
    """An exception that is guaranteed to survive pickling through the
    result queue (unpicklable exceptions would be dropped by the queue's
    feeder thread, hanging the parent)."""
    import pickle
    import traceback

    try:
        pickle.loads(pickle.dumps(e))
        return e
    except Exception:
        return RuntimeError(
            f"{type(e).__name__}: {e}\n" + "".join(traceback.format_exc()))


def _dumps(payload):
    """Pickle in the worker's main thread so serialization errors are caught
    synchronously and shipped as errors instead of hanging the parent."""
    import pickle

    return pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)


def _loads(buf):
    import pickle

    return pickle.loads(buf)


# --------------------------------------------------------------------------
# worker loops
# --------------------------------------------------------------------------


def _map_worker_loop(dataset, index_q, result_q, worker_id, num_workers,
                     seed, worker_init_fn, use_shm):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, seed + worker_id, dataset)
    np.random.seed((seed + worker_id) % (2 ** 31))
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    while True:
        job = index_q.get()
        if job is None:
            return
        key, idxs = job  # key = (epoch, batch_id)
        try:
            samples = [_to_plain(dataset[i]) for i in idxs]
            result_q.put((key, _dumps(_encode(samples, use_shm)), None))
        except BaseException as e:  # ship the error to the parent
            result_q.put((key, None, _safe_exc(e)))


def _iterable_worker_loop(dataset, result_q, worker_id, num_workers, seed,
                          worker_init_fn, batch_size, drop_last, use_shm,
                          stop_ev):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, seed + worker_id, dataset)
    np.random.seed((seed + worker_id) % (2 ** 31))
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    def _put(item):
        # bounded queue: block in short slices so stop_ev can interrupt
        while not stop_ev.is_set():
            try:
                result_q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    try:
        it = iter(dataset)
        while not stop_ev.is_set():
            if batch_size is None:
                try:
                    sample = next(it)
                except StopIteration:
                    break
                if not _put((worker_id,
                             _dumps(_encode([_to_plain(sample)], use_shm)),
                             None)):
                    return
                continue
            batch = list(itertools.islice(it, batch_size))
            if not batch or (len(batch) < batch_size and drop_last):
                break
            if not _put((worker_id,
                         _dumps(_encode([_to_plain(s) for s in batch],
                                        use_shm)), None)):
                return
        _put((worker_id, None, None))  # this worker is done
    except BaseException as e:
        _put((worker_id, None, _safe_exc(e)))


# --------------------------------------------------------------------------
# parent-side iterators
# --------------------------------------------------------------------------


def _mp_context():
    """Start-method policy: ``fork`` is the fast path but is unsafe once the
    parent is multi-threaded (JAX/XLA runtime threads, the elastic heartbeat
    — CPython itself deprecates fork-after-threads and children can deadlock
    on locks held by threads that don't survive the fork), so default to
    ``forkserver`` in that case.  ``PADDLE_TPU_MP_START`` overrides either
    way."""
    import multiprocessing as mp
    import threading

    def _xla_backend_up() -> bool:
        # XLA's runtime threads are C++ threads invisible to
        # threading.active_count(); an initialized backend is the signal.
        # Merely importing jax starts nothing, so light scripts keep fork.
        try:
            from jax._src import xla_bridge

            return bool(xla_bridge._backends)
        except Exception:
            return False

    method = os.environ.get("PADDLE_TPU_MP_START")
    if method is None:
        threaded = threading.active_count() > 1 or _xla_backend_up()
        method = "forkserver" if threaded else "fork"
    return mp.get_context(method)


class MapWorkerPool:
    """Index-fed worker pool for map-style datasets; supports
    persistent_workers reuse across epochs."""

    def __init__(self, dataset, num_workers, worker_init_fn=None, seed=None,
                 use_shm=True, timeout=0):
        self._alive = False  # before anything that can raise: __del__ safety
        ctx = _mp_context()
        self.num_workers = num_workers
        self.timeout = timeout
        self.index_q = ctx.Queue()
        self.result_q = ctx.Queue()
        # fresh base seed per pool (ref dataloader_iter.py base_seed): epochs
        # with non-persistent workers get different augmentation randomness
        if seed is None:
            seed = int(np.random.randint(0, 2 ** 31))
        self._epoch = 0
        self.procs = [
            ctx.Process(target=_map_worker_loop,
                        args=(dataset, self.index_q, self.result_q, w,
                              num_workers, seed, worker_init_fn, use_shm),
                        daemon=True)
            for w in range(num_workers)
        ]
        started = []
        try:
            for p in self.procs:
                p.start()
                started.append(p)
        except BaseException:
            for p in started:
                p.terminate()
                p.join(timeout=2)
            raise
        self._alive = True

    def run_epoch(self, batches, collate_fn, prefetch_factor=2):
        """batches: list of index lists. Yields collated batches IN ORDER.
        Jobs/results are epoch-tagged so results abandoned mid-epoch (early
        break with persistent workers) are discarded, not replayed."""
        self._epoch += 1
        epoch = self._epoch
        inflight = 0
        next_submit = 0
        next_yield = 0
        hold = {}
        max_inflight = max(2, prefetch_factor) * self.num_workers
        n = len(batches)
        while next_yield < n:
            while next_submit < n and inflight < max_inflight:
                self.index_q.put(((epoch, next_submit), batches[next_submit]))
                next_submit += 1
                inflight += 1
            while next_yield in hold:
                yield collate_fn(hold.pop(next_yield))
                next_yield += 1
            if next_yield >= n:
                break
            try:
                (r_epoch, batch_id), data, err = self.result_q.get(
                    timeout=self.timeout or None)
            except queue.Empty:
                self.shutdown()
                raise RuntimeError(
                    f"DataLoader worker timed out after {self.timeout}s "
                    f"(batch {next_yield})")
            if r_epoch != epoch:  # stale result from an abandoned epoch
                if data is not None:
                    _decode(_loads(data))  # free its shm segments
                continue
            inflight -= 1
            if err is not None:
                self.shutdown()
                raise err
            hold[batch_id] = _decode(_loads(data))

    def shutdown(self):
        if not self._alive:
            return
        self._alive = False
        for _ in self.procs:
            try:
                self.index_q.put(None)
            except Exception:
                pass
        # drain while joining (frees shm of in-flight results), with a final
        # drain AFTER all workers are dead so late puts can't leak segments
        deadline = 5.0
        for p in self.procs:
            while p.is_alive() and deadline > 0:
                self._drain_results()
                p.join(timeout=0.2)
                deadline -= 0.2
            if p.is_alive():
                p.terminate()
                p.join(timeout=2)
        self._drain_results()

    def _drain_results(self):
        try:
            while True:
                _, data, _ = self.result_q.get_nowait()
                if data is not None:
                    _decode(_loads(data))
        except queue.Empty:
            pass

    def __del__(self):
        self.shutdown()


class IterableWorkerIter:
    """One-shot iterator over an IterableDataset with worker replicas."""

    def __init__(self, dataset, num_workers, batch_size, drop_last,
                 collate_fn, convert_fn, worker_init_fn=None, seed=None,
                 use_shm=True, timeout=0, prefetch_factor=2):
        ctx = _mp_context()
        self.collate_fn = collate_fn
        self.convert_fn = convert_fn
        self.batch_size = batch_size
        self.timeout = timeout
        if seed is None:
            seed = int(np.random.randint(0, 2 ** 31))
        # bounded: backpressure so workers can't buffer the whole dataset
        self.result_q = ctx.Queue(
            maxsize=max(2, prefetch_factor) * num_workers)
        self.stop_ev = ctx.Event()
        self.procs = [
            ctx.Process(target=_iterable_worker_loop,
                        args=(dataset, self.result_q, w, num_workers, seed,
                              worker_init_fn, batch_size, drop_last, use_shm,
                              self.stop_ev),
                        daemon=True)
            for w in range(num_workers)
        ]
        for p in self.procs:
            p.start()
        self._done = set()
        self._buffers = {w: [] for w in range(num_workers)}
        self._rr = 0  # round-robin pointer for deterministic yield order

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            n_workers = len(self.procs)
            if len(self._done) == n_workers and all(
                    not b for b in self._buffers.values()):
                self.shutdown()
                raise StopIteration
            # yield strictly round-robin over workers still producing
            for _ in range(n_workers):
                w = self._rr
                if self._buffers[w]:
                    self._rr = (w + 1) % n_workers
                    return self._emit(self._buffers[w].pop(0))
                if w in self._done:
                    self._rr = (w + 1) % n_workers
                    continue
                break  # need more data from worker self._rr
            try:
                w, data, err = self.result_q.get(timeout=self.timeout or None)
            except queue.Empty:
                self.shutdown()
                raise RuntimeError(
                    f"DataLoader worker timed out after {self.timeout}s")
            if err is not None:
                self.shutdown()
                raise err
            if data is None:
                self._done.add(w)
            else:
                self._buffers[w].append(_decode(_loads(data)))

    def _emit(self, samples):
        if self.batch_size is None:
            return self.convert_fn(samples[0])
        return self.collate_fn(samples)

    def shutdown(self):
        self.stop_ev.set()
        # drain while joining (workers may be blocked on the bounded queue),
        # and once more after death so late puts can't leak shm segments
        deadline = 5.0
        for p in self.procs:
            while p.is_alive() and deadline > 0:
                self._drain_results()
                p.join(timeout=0.2)
                deadline -= 0.2
            if p.is_alive():
                p.terminate()
                p.join(timeout=2)
        self._drain_results()

    def _drain_results(self):
        try:
            while True:
                _, data, _ = self.result_q.get_nowait()
                if data is not None:
                    _decode(_loads(data))
        except queue.Empty:
            pass

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass
