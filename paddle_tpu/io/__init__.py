"""Data loading (ref: python/paddle/io/ + fluid/reader.py:311 DataLoader,
fluid/dataloader/ worker machinery).

TPU-native: the loader produces host numpy batches; device transfer happens
at first tensor use (XLA manages staging). ``num_workers>0`` spawns real
SUBPROCESS workers (worker_pool.py — index queue in, shared-memory arrays
out, collate in the parent so children never touch jax), matching the
reference's multiprocess design for GIL-bound numpy augmentation
(dataloader_iter.py:162,370). Set PADDLE_TPU_DATALOADER_THREAD=1 to force
the lighter single-thread prefetch path instead.
"""
from __future__ import annotations

import itertools
import math
import queue
import threading
from typing import Any, Callable, Iterable, List, Optional, Sequence

import numpy as np

from ..framework.core import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            if isinstance(item, tuple):
                out.extend(item)
            else:
                out.append(item)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumsizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumsizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        d_idx = int(np.searchsorted(self.cumsizes, idx, side="right"))
        prev = 0 if d_idx == 0 else self.cumsizes[d_idx - 1]
        return self.datasets[d_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(math.floor(total * l)) for l in lengths]
        lengths[-1] = total - sum(lengths[:-1])
    perm = np.random.permutation(total).tolist()
    out = []
    offset = 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l]))
        offset += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples if self._num_samples is not None else len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """Ref python/paddle/io/batch_sampler.py."""

    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Ref fluid/dataloader/batch_sampler.py DistributedBatchSampler — shards
    the index space across data-parallel ranks."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None, shuffle=False,
                 drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_rank, get_world_size

            num_replicas = num_replicas if num_replicas is not None else get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            indices = rng.permutation(n).tolist()
        else:
            indices = list(range(n))
        indices += indices[: (self.total_size - n)]
        indices = indices[self.local_rank: self.total_size: self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic)):
        return Tensor(np.stack(batch))
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s.value) for s in batch]))
    if isinstance(sample, (int, float)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        return [default_collate_fn(list(items)) for items in zip(*batch)]
    try:
        return Tensor(np.stack([np.asarray(s) for s in batch]))
    except Exception:
        return batch


def default_convert_fn(batch):
    if isinstance(batch, (np.ndarray,)):
        return Tensor(batch)
    if isinstance(batch, (list, tuple)):
        return [default_convert_fn(b) for b in batch]
    return batch


class _PrefetchIter:
    def __init__(self, gen_fn, num_workers, prefetch_factor):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(2, prefetch_factor))
        self._done = object()
        self._exc = None

        def producer():
            try:
                for item in gen_fn():
                    self._q.put(item)
            except BaseException as e:  # propagate to consumer
                self._exc = e
            finally:
                self._q.put(self._done)

        self._thread = threading.Thread(target=producer, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._done:
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        return item


class DataLoader:
    """Ref fluid/reader.py:311 DataLoader."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers
        self._pool = None  # persistent MapWorkerPool
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif batch_size is None:
            self.batch_sampler = None
            self.batch_size = None
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size, drop_last=drop_last)

    def _gen(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            if self.batch_size is None:
                for sample in it:
                    yield default_convert_fn(sample)
                return
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        elif self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield default_convert_fn(self.dataset[i])
        else:
            for idxs in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idxs])

    def _use_threads(self):
        import os

        return os.environ.get("PADDLE_TPU_DATALOADER_THREAD") == "1"

    def _mp_iter(self):
        from .worker_pool import IterableWorkerIter, MapWorkerPool

        if self._iterable_mode:
            return IterableWorkerIter(
                self.dataset, self.num_workers, self.batch_size,
                self.drop_last, self.collate_fn, default_convert_fn,
                worker_init_fn=self.worker_init_fn,
                use_shm=self.use_shared_memory, timeout=self.timeout,
                prefetch_factor=self.prefetch_factor)
        if self.batch_sampler is not None:
            batches = list(self.batch_sampler)
        else:
            batches = [[i] for i in range(len(self.dataset))]
            # single-sample mode converts, not collates
        collate = (self.collate_fn if self.batch_sampler is not None
                   else lambda samples: default_convert_fn(samples[0]))
        if self._pool is None:
            self._pool = MapWorkerPool(
                self.dataset, self.num_workers,
                worker_init_fn=self.worker_init_fn,
                use_shm=self.use_shared_memory, timeout=self.timeout)
        pool = self._pool

        def run():
            try:
                yield from pool.run_epoch(batches, collate,
                                          self.prefetch_factor)
            finally:
                if not self.persistent_workers:
                    pool.shutdown()
                    self._pool = None

        return run()

    def __iter__(self):
        if self.num_workers and self.num_workers > 0:
            if self._use_threads():
                return _PrefetchIter(self._gen, self.num_workers,
                                     self.prefetch_factor)
            try:
                return self._mp_iter()
            except Exception as e:  # unpicklable dataset etc.
                import os
                import warnings

                if (os.environ.get("PADDLE_TPU_MP_START") is None
                        and isinstance(e, (AttributeError, TypeError))):
                    # implicit forkserver rejects closure-local datasets /
                    # lambdas that the old fork default accepted: retry with
                    # fork once (the user's risk trade-off, warned)
                    warnings.warn(
                        f"dataset not picklable for the forkserver workers "
                        f"({e!r}); retrying with fork — set "
                        f"PADDLE_TPU_MP_START to silence", RuntimeWarning)
                    os.environ["PADDLE_TPU_MP_START"] = "fork"
                    try:
                        return self._mp_iter()
                    except Exception as e2:
                        e = e2
                    finally:
                        del os.environ["PADDLE_TPU_MP_START"]
                warnings.warn(
                    f"multiprocess DataLoader workers unavailable ({e!r}); "
                    f"falling back to single-thread prefetch", RuntimeWarning)
                return _PrefetchIter(self._gen, self.num_workers,
                                     self.prefetch_factor)
        return self._gen()

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("DataLoader over IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)


def get_worker_info():
    """Worker metadata inside a DataLoader worker process; None in the main
    process (ref fluid/dataloader/worker.py get_worker_info)."""
    from .worker_pool import get_worker_info as _impl

    return _impl()


from .bucketing import (LengthBucketBatchSampler, bucket_boundaries,  # noqa: E402,F401
                        pad_to_bucket)
