"""Native data-loader bindings (csrc/ptio.cpp via ctypes).

TokenDataset/TokenDataLoader: the pretraining input pipeline — mmap token
file, C++ threaded prefetch, fixed (B, S) int32 blocks (inputs + next-token
labels). Falls back to a numpy implementation when the .so can't be built.
Ref: paddle/fluid/framework/data_feed.cc + fluid/dataloader worker stack.
"""
from __future__ import annotations

import ctypes
import threading
from typing import Optional, Tuple

import numpy as np

_LIB = None
_LIB_LOCK = threading.Lock()


def _build_lib() -> Optional[str]:
    from ..utils.native_build import ensure_lib

    return ensure_lib("ptio")


def get_lib():
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        path = _build_lib()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        lib.ptio_create_reader.restype = ctypes.c_void_p
        lib.ptio_create_reader.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
            ctypes.c_int]
        lib.ptio_next_batch.restype = ctypes.c_int
        lib.ptio_next_batch.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_int32)]
        lib.ptio_samples_per_shard.restype = ctypes.c_long
        lib.ptio_samples_per_shard.argtypes = [ctypes.c_void_p]
        lib.ptio_destroy_reader.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return _LIB


def write_token_file(tokens: np.ndarray, path: str, dtype=np.int32) -> str:
    """Serialize a 1-D token stream to the binary format the reader mmaps."""
    arr = np.ascontiguousarray(tokens, dtype=dtype)
    with open(path, "wb") as f:
        f.write(arr.tobytes())
    return path


class TokenDataLoader:
    """Pretraining loader: yields (input_ids (B,S) int32, labels (B,S) int64).

    Uses the C++ prefetch core when available; numpy fallback otherwise.
    shard_id/num_shards give DistributedBatchSampler-style dataset sharding.
    """

    def __init__(self, path: str, seq_len: int, batch_size: int, dtype_size: int = 4,
                 num_threads: int = 2, capacity: int = 8, seed: int = 0,
                 shard_id: int = 0, num_shards: int = 1):
        self.path = path
        self.seq_len = seq_len
        self.batch_size = batch_size
        self.dtype_size = dtype_size
        self._handle = None
        self._lib = get_lib()
        self._seed = seed
        self._shard = (shard_id, num_shards)
        if self._lib is not None:
            self._handle = self._lib.ptio_create_reader(
                path.encode(), dtype_size, seq_len, batch_size, num_threads,
                capacity, seed, shard_id, num_shards)
            if not self._handle:
                self._lib = None
        if self._lib is None:
            dt = {2: np.uint16, 4: np.int32, 8: np.int64}[dtype_size]
            self._tokens = np.fromfile(path, dtype=dt)
            self._rng = np.random.RandomState(seed)

    @property
    def native(self) -> bool:
        return self._handle is not None

    def samples_per_shard(self) -> int:
        if self._handle:
            return int(self._lib.ptio_samples_per_shard(self._handle))
        stride = self.seq_len + 1
        return (len(self._tokens) // stride) // self._shard[1]

    def next(self) -> Tuple[np.ndarray, np.ndarray]:
        stride = self.seq_len + 1
        buf = np.empty((self.batch_size, stride), np.int32)
        if self._handle:
            ok = self._lib.ptio_next_batch(
                self._handle, buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            if not ok:
                raise StopIteration
        else:
            n = self.samples_per_shard()
            shard_id, _ = self._shard
            for i in range(self.batch_size):
                s = shard_id * n + self._rng.randint(n)
                buf[i] = self._tokens[s * stride:(s + 1) * stride].astype(np.int32)
        return buf[:, :-1].copy(), buf[:, 1:].astype(np.int64)

    def __iter__(self):
        while True:
            yield self.next()

    def close(self):
        if self._handle:
            self._lib.ptio_destroy_reader(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
