"""Dynamic companion to graftlint: a jit-cache regression guard.

Static analysis catches tracer-unsafe *code*; this guard catches
tracer-unsafe *behavior* — silent recompilations in a steady-state loop.
A serving decode tick or a train step must compile exactly once; a shape
or dtype that wobbles per step (a Python int that sometimes arrives as
np.int64, a donated buffer whose sharding changed, a weak_type flip)
recompiles every step and turns a μs dispatch into a multi-second stall,
visible only as mysterious slowness on the TPU.

Implementation: ``jax.monitoring`` emits a
``/jax/compilation_cache/compile_requests_use_cache`` event for every
backend compile (cache miss). One process-wide listener feeds a counter;
the guard snapshots it around a block and fails if it moved more than
``allowed`` (default 0).

Usage::

    from paddle_tpu.analysis import jit_cache_guard

    # warm up: run one step of every program the loop uses
    server.step()
    with jit_cache_guard("paged decode steady state"):
        for _ in range(8):
            server.step()          # any recompile here raises

As a pytest fixture::

    @pytest.fixture
    def no_recompiles():
        with jit_cache_guard("steady state") as g:
            yield g
"""
from __future__ import annotations

import threading
from typing import List, Optional

__all__ = ["RecompileError", "JitCacheGuard", "jit_cache_guard",
           "compile_count"]


class RecompileError(AssertionError):
    """A guarded block triggered jit cache misses (recompilation)."""


_lock = threading.Lock()
_installed = False
_compiles = 0
_recent: List[str] = []          # last few event names, for diagnostics
_RECENT_MAX = 16

# every backend compile (jit cache miss) records one of these, whether or
# not the persistent compilation cache is enabled
_COMPILE_EVENT_PREFIX = "/jax/compilation_cache/compile_requests"


def _on_event(name: str, **kwargs) -> None:
    global _compiles
    if name.startswith(_COMPILE_EVENT_PREFIX):
        with _lock:
            _compiles += 1
            _recent.append(name)
            del _recent[:-_RECENT_MAX]


def _ensure_listener() -> None:
    global _installed
    with _lock:
        if _installed:
            return
        import jax

        jax.monitoring.register_event_listener(_on_event)
        _installed = True


def compile_count() -> int:
    """Process-wide backend-compile (cache-miss) count since the listener
    was installed. Monotonic; meaningful as deltas."""
    _ensure_listener()
    with _lock:
        return _compiles


class JitCacheGuard:
    """Context manager asserting jit cache-miss counts stay flat.

    ``allowed`` tolerates a known number of one-off compiles inside the
    block (e.g. a first-use epilogue); steady-state loops should keep the
    default 0. The count is process-wide — don't run unrelated jax work
    concurrently with a guarded block.
    """

    def __init__(self, label: str = "", allowed: int = 0):
        self.label = label
        self.allowed = int(allowed)
        self.start: Optional[int] = None
        self.compiles: Optional[int] = None

    def __enter__(self) -> "JitCacheGuard":
        self.start = compile_count()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.compiles = compile_count() - self.start
        if exc_type is None and self.compiles > self.allowed:
            with _lock:
                recent = ", ".join(_recent[-min(self.compiles, 4):])
            where = f" [{self.label}]" if self.label else ""
            raise RecompileError(
                f"jit cache regression{where}: {self.compiles} backend "
                f"compile(s) inside a steady-state block (allowed "
                f"{self.allowed}). Something retraces per step — check for "
                f"wobbling shapes/dtypes/static args or un-donated buffers. "
                f"Recent events: {recent}")
        return False


def jit_cache_guard(label: str = "", allowed: int = 0) -> JitCacheGuard:
    """Factory matching the class (reads better at call sites)."""
    return JitCacheGuard(label=label, allowed=allowed)
