"""Telemetry hygiene: no metric/span/flight calls inside jitted bodies.

The telemetry contract (``paddle_tpu/telemetry.py``, shared by serving
AND training) is host-only: registry counters, span tracers, and the
flight recorders run BETWEEN device programs, never inside them. A
telemetry call inside a jitted function is doubly wrong — it executes
once at trace time (so the metric records the trace, not the steady
state) and it tempts a ``.item()``/host sync to read the value being
recorded, breaking the async dispatch pipeline both the serving loop
and the train step depend on. The rule is path-unscoped on purpose: a
traced ``train_step`` in ``parallel/`` is held to the same contract as
a serving decode body — the engine records AROUND its compiled call.
"""
from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..engine import Finding, ModuleContext, Rule, register


@register
class TelemetryInJitRule(Rule):
    """GL010: metrics/span/flight-recorder mutation inside a function this
    module jit-compiles. Telemetry is host-side observability; inside a
    traced body the call fires once at trace time and never again, so the
    instrument silently reports trace-time state forever."""

    id = "GL010"
    name = "telemetry-in-jit"
    description = ("counter/histogram/span/flight-recorder calls inside a "
                   "jitted function run at trace time only — record around "
                   "the compiled call on the host side "
                   "(paddle_tpu/telemetry.py is host-only by contract, "
                   "serving and training alike)")

    # receiver components that name a telemetry object outright
    _RECV_EXACT = frozenset({
        "telemetry", "tracer", "registry", "metrics", "flight",
        "recorder", "tel",
    })
    # receiver components that name one by convention
    _RECV_SUBSTR = ("telemetry", "metric", "tracer", "flight", "span",
                    "counter", "gauge", "histogram")
    # mutating methods of the telemetry API surface
    _METHODS = frozenset({
        "inc", "add", "observe", "set", "begin", "end", "record",
        "instant", "complete", "close", "span",
    })

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        if not ctx.jitted_names:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name not in ctx.jitted_names:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                hit = self._telemetry_call(sub)
                if hit is not None:
                    recv, meth = hit
                    yield self.finding(
                        ctx, sub,
                        f"{recv}.{meth}() inside jitted '{node.name}' — "
                        f"telemetry is host-only: it fires at trace time, "
                        f"not per step; move the call outside the compiled "
                        f"function and record around the dispatch")

    @classmethod
    def _telemetry_call(cls, call: ast.Call) -> Optional[tuple]:
        func = call.func
        if not isinstance(func, ast.Attribute):
            return None
        meth = func.attr
        if meth not in cls._METHODS:
            return None
        # walk the receiver, peeling intermediate get-or-create calls
        # (reg.histogram("h").observe(...)); a subscript root (.at[].set)
        # yields no components and stays clean
        parts = []
        node = func.value
        while True:
            if isinstance(node, ast.Call):
                node = node.func
            elif isinstance(node, ast.Attribute):
                parts.append(node.attr)
                node = node.value
            elif isinstance(node, ast.Name):
                parts.append(node.id)
                break
            else:
                break
        for part in parts:
            low = part.lstrip("_").lower()
            if low in cls._RECV_EXACT or any(
                    s in low for s in cls._RECV_SUBSTR):
                return ".".join(reversed(parts)), meth
        return None
