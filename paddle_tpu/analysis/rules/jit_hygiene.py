"""jax.jit call-site hygiene: parameters that should be static.

A Python scalar argument traced as a device value costs an abstract
0-d array where the function actually needs a CONCRETE value — shapes
(``jnp.zeros(n)``), trip counts (``range(n)``), flags (``if mode:``).
Passing it dynamically either fails to trace or, when it happens to
trace, retraces/recompiles on every distinct value anyway — without the
cache key making that intent explicit. Declaring ``static_argnums`` is
both the fix and the documentation.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List

from ..engine import Finding, ModuleContext, Rule, register
from . import attr_chain

_SHAPE_FNS = (
    "jnp.zeros", "jnp.ones", "jnp.full", "jnp.empty", "jnp.arange",
    "jnp.linspace", "jnp.eye", "jax.numpy.zeros", "jax.numpy.ones",
)


def _static_positions(fn: ast.FunctionDef) -> Dict[str, str]:
    """Param name → why it must be concrete, for params used in
    static-only positions inside ``fn``'s own body (nested defs excluded:
    their params are the nested function's problem)."""
    params = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
    params.discard("self")
    out: Dict[str, str] = {}
    nested = {n for sub in ast.walk(fn)
              if isinstance(sub, (ast.FunctionDef, ast.Lambda)) and sub is not fn
              for n in ast.walk(sub)}
    for node in ast.walk(fn):
        if node in nested:
            continue
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if isinstance(node.func, ast.Name) and node.func.id == "range":
                for a in node.args:
                    for n in ast.walk(a):
                        if isinstance(n, ast.Name) and n.id in params:
                            out.setdefault(n.id, "loop trip count (range)")
            elif chain in _SHAPE_FNS and node.args:
                for n in ast.walk(node.args[0]):
                    if isinstance(n, ast.Name) and n.id in params:
                        out.setdefault(n.id, f"array shape ({chain})")
        elif isinstance(node, (ast.If, ast.While)):
            t = node.test
            if isinstance(t, ast.Name) and t.id in params:
                out.setdefault(t.id, "Python branch condition")
            elif isinstance(t, ast.Compare) and isinstance(t.left, ast.Name) \
                    and t.left.id in params \
                    and all(isinstance(c, ast.Constant) for c in t.comparators):
                out.setdefault(t.left.id, "Python branch condition")
    return out


@register
class StaticArgnumsRule(Rule):
    """GL007: ``jax.jit(fn)`` without static_argnums/static_argnames where
    ``fn`` (resolvable in the same module) uses a parameter in a position
    that requires a concrete Python value."""

    id = "GL007"
    name = "static-argnums"
    description = ("jitted function uses a parameter as shape/trip-count/"
                   "branch — declare it in static_argnums so the intent "
                   "(recompile per value) is explicit and tracing succeeds")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        defs: Dict[str, ast.FunctionDef] = {
            n.name: n for n in ast.walk(ctx.tree)
            if isinstance(n, ast.FunctionDef)}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if chain not in ("jax.jit", "jit", "pjit", "jax.pjit"):
                continue
            if any(kw.arg in ("static_argnums", "static_argnames")
                   for kw in node.keywords):
                continue
            if not node.args or not isinstance(node.args[0], ast.Name):
                continue
            fn = defs.get(node.args[0].id)
            if fn is None:
                continue
            hits = _static_positions(fn)
            if hits:
                detail = "; ".join(f"'{p}' used as {why}"
                                   for p, why in sorted(hits.items()))
                yield self.finding(
                    ctx, node,
                    f"jax.jit({fn.name}) without static_argnums, but "
                    f"{detail} — these need concrete values at trace time")
