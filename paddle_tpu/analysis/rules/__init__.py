"""graftlint rule set. Importing this package registers every rule with
the engine registry (engine.all_rules imports it for that side effect).

Shared AST helpers live here; the rules themselves are grouped by hazard
family: host_sync (device→host syncs), control_flow (traced-value
branching, effects inside jit), purity (RNG/default/except hygiene),
jit_hygiene (jax.jit call-site quality).
"""
from __future__ import annotations

import ast
from typing import Optional

__all__ = ["attr_chain", "contains_jnp_call", "contains_value_attr"]


def attr_chain(node: ast.AST) -> Optional[str]:
    """Dotted name for Name/Attribute chains: ``np.random.rand`` → that
    string; anything rooted in a non-Name (call result, subscript)
    returns None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_JNP_ROOTS = ("jnp.", "jax.numpy.", "jax.nn.", "jax.lax.", "lax.")


def _is_jnp_chain(chain: Optional[str]) -> bool:
    return chain is not None and chain.startswith(_JNP_ROOTS)


def contains_jnp_call(node: ast.AST) -> bool:
    """True if the expression contains a call into jnp/jax.numpy/jax.nn/
    jax.lax — i.e. its value is (or derives from) a traced/device array."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _is_jnp_chain(attr_chain(sub.func)):
            return True
    return False


# reading these off a device array is free host-side metadata, not data
_METADATA_ATTRS = frozenset({
    "shape", "dtype", "ndim", "size", "sharding", "aval", "weak_type",
})


def contains_value_attr(node: ast.AST) -> bool:
    """True if the expression touches DEVICE DATA via a ``.value``/
    ``._value`` attribute (the Tensor-unwrap idiom). Metadata projections
    of it (``x.value.shape``, ``._value.dtype``) are pruned — they're
    host-resident and free."""

    def visit(n: ast.AST) -> bool:
        if isinstance(n, ast.Attribute):
            if n.attr in _METADATA_ATTRS:
                return False
            if n.attr in ("value", "_value"):
                return True
            return visit(n.value)
        return any(visit(c) for c in ast.iter_child_nodes(n))

    return visit(node)


# registration side effects
from . import (atomic_io, control_flow, faults, host_sync,  # noqa: E402,F401
               jit_hygiene, purity, telemetry, timing, transfers)
