"""Fault-injection hygiene: no injector/recovery hooks inside jitted bodies.

The fault-injection contract (``inference/faults.py``) is host-only, like
telemetry (GL010): ``fire()`` sites run BETWEEN device programs so an
injected exception raises before compiled dispatch — donated buffers are
still intact and the trip can be retried verbatim. A hook inside a jitted
function is doubly wrong: it fires once at trace time (so the scripted
plan's ordinals never advance in steady state and the fault never lands
where scheduled), and an exception escaping a traced body after dispatch
may have consumed donated pool buffers, turning a recoverable injected
fault into real corruption.
"""
from __future__ import annotations

import ast
from typing import Iterable, Optional

from ..engine import Finding, ModuleContext, Rule, register


@register
class FaultHookInJitRule(Rule):
    """GL011: fault-injection or recovery hooks inside a function this
    module jit-compiles. Injection is a host-side protocol; inside a
    traced body the hook fires at trace time only, and a fault raised
    mid-program lands after donation — unretryable by construction."""

    id = "GL011"
    name = "fault-hook-in-jit"
    description = ("fault-injection/recovery hooks (fire/corrupt/"
                   "wrap_clock) inside a jitted function run at trace "
                   "time only and can raise after buffer donation — "
                   "hooks belong on the host side, before compiled "
                   "dispatch (inference/faults.py is host-only by "
                   "contract)")

    # receiver components that name an injector outright
    _RECV_EXACT = frozenset({
        "faults", "injector", "fault_injector", "chaos",
    })
    # receiver components that name one by convention
    _RECV_SUBSTR = ("fault", "inject", "chaos")
    # the injector API surface
    _METHODS = frozenset({
        "fire", "corrupt", "wrap_clock",
    })

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        if not ctx.jitted_names:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name not in ctx.jitted_names:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                hit = self._fault_call(sub)
                if hit is not None:
                    recv, meth = hit
                    yield self.finding(
                        ctx, sub,
                        f"{recv}.{meth}() inside jitted '{node.name}' — "
                        f"fault hooks are host-only: at trace time the "
                        f"plan's ordinals freeze, and a fault raised "
                        f"inside the program lands after donation; hook "
                        f"before the compiled call on the host side")

    @classmethod
    def _fault_call(cls, call: ast.Call) -> Optional[tuple]:
        func = call.func
        if not isinstance(func, ast.Attribute):
            return None
        meth = func.attr
        if meth not in cls._METHODS:
            return None
        # walk the receiver, peeling intermediate get-or-create calls
        # (server.faults.fire(...)); a subscript root yields no
        # components and stays clean
        parts = []
        node = func.value
        while True:
            if isinstance(node, ast.Call):
                node = node.func
            elif isinstance(node, ast.Attribute):
                parts.append(node.attr)
                node = node.value
            elif isinstance(node, ast.Name):
                parts.append(node.id)
                break
            else:
                break
        for part in parts:
            low = part.lstrip("_").lower()
            if low in cls._RECV_EXACT or any(
                    s in low for s in cls._RECV_SUBSTR):
                return ".".join(reversed(parts)), meth
        return None
