"""Timing hygiene: no direct wall-clock reads inside the serving stack.

Everything time-dependent in ``paddle_tpu/inference/`` — scheduler TTLs,
telemetry spans, heartbeat liveness, fault-plan clock attacks — flows
through an injectable ``clock`` callable precisely so a chaos run
replays bit-identically and a snapshot restores with deterministic
timing. One stray ``time.time()`` inside that package re-introduces
nondeterminism the whole fault-injection contract was built to remove:
the same seeded plan stops producing the same run, and the token-identity
assertions the chaos tests lean on become flaky instead of load-bearing.

``paddle_tpu/autotune/`` is in scope for the same reason with a harder
payoff: the tuner's determinism contract is byte-equality of the whole
winning profile per seed (tests byte-compare two independent runs), so
even a timestamp stamped mid-search breaks the artifact — profiles take
their timestamp from the CALLER (``TunedProfile.save(now=...)``), and
trial measurement threads the same injectable clock the servers use.

Passing a clock *reference* (``clock=time.monotonic`` as a default) is
the sanctioned pattern and stays clean — only direct *calls* are flagged.
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..engine import Finding, ModuleContext, Rule, register
from . import attr_chain


@register
class WallClockInServingRule(Rule):
    """GL012: direct wall-clock reads inside ``paddle_tpu/inference/``.
    The serving stack's time base is an injectable clock — scheduler,
    telemetry, fault injector and fleet router all accept ``clock=`` —
    so deterministic chaos replay survives. A direct read bypasses the
    injection seam."""

    id = "GL012"
    name = "wall-clock-in-serving"
    description = ("direct time.time()/time.monotonic()/datetime.now() "
                   "calls inside paddle_tpu/inference/ or "
                   "paddle_tpu/autotune/ bypass the injectable-clock "
                   "seam (clock= parameters) that keeps seeded chaos "
                   "runs, snapshot/restore timing, and per-seed "
                   "byte-identical tuned profiles deterministic; take a "
                   "clock callable instead (passing a reference like "
                   "clock=time.monotonic stays clean — only calls are "
                   "flagged)")

    _SCOPE = ("paddle_tpu/inference/", "paddle_tpu/autotune/")

    # the wall-clock read surface: direct calls to any of these are a
    # hidden time dependency (references to them are fine — that's how
    # the default clock is threaded)
    _CLOCK_CALLS = frozenset({
        "time.time", "time.time_ns",
        "time.monotonic", "time.monotonic_ns",
        "time.perf_counter", "time.perf_counter_ns",
        "time.process_time", "time.process_time_ns",
        "datetime.now", "datetime.utcnow", "datetime.today",
        "datetime.datetime.now", "datetime.datetime.utcnow",
        "datetime.datetime.today",
        "datetime.date.today", "date.today",
    })

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        if not ctx.path.startswith(self._SCOPE):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if chain in self._CLOCK_CALLS:
                yield self.finding(
                    ctx, node,
                    f"{chain}() is a direct wall-clock read inside a "
                    f"clock-injected package (inference/, autotune/) — "
                    f"thread the injectable clock (a clock= parameter "
                    f"defaulting to time.monotonic) instead, so seeded "
                    f"chaos plans, restore timing, and tuned-profile "
                    f"byte-determinism replay exactly")


@register
class BlockingWallTimeInFleetsimRule(Rule):
    """GL015: blocking waits and wall-clock reads inside the fast-time
    simulation surface. ``paddle_tpu/fleetsim/`` runs a simulated day in
    CI minutes by advancing a virtual clock; ``inference/transport.py``
    and ``replica_worker.py`` synchronize on socket frames, never on
    sleeps. One ``time.sleep()`` turns virtual seconds back into wall
    seconds — a million-session day stops fitting in CI — and one
    wall-clock read couples the byte-identical report to the machine it
    ran on."""

    id = "GL015"
    name = "blocking-wall-time-in-fleetsim"
    description = ("time.sleep() or wall-clock reads inside "
                   "paddle_tpu/fleetsim/ or the replica transport "
                   "(inference/transport.py, inference/replica_worker.py) "
                   "re-couple fast-time simulation to wall time: the "
                   "discrete-event loop owns ALL time via the virtual "
                   "clock, and transport blocking is bounded by socket "
                   "timeouts, not sleeps — a single sleep makes a "
                   "simulated day take a real day and breaks "
                   "byte-identical seeded reports")

    #: fleetsim is wholly in scope; the transport pair is listed
    #: file-by-file because the rest of inference/ may legitimately
    #: sleep in user-facing CLIs layered above it
    _SCOPE = ("paddle_tpu/fleetsim/",
              "paddle_tpu/inference/transport.py",
              "paddle_tpu/inference/replica_worker.py")

    #: sleep in every spelling, plus the GL012 wall-clock read surface —
    #: fleetsim has no sanctioned wall-time at all (GL012 already covers
    #: the transport files for reads; sleep is the new ban there)
    _BLOCKING_CALLS = frozenset(
        {"time.sleep", "sleep", "asyncio.sleep"}
        | WallClockInServingRule._CLOCK_CALLS)

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        if not ctx.path.startswith(self._SCOPE):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if chain in self._BLOCKING_CALLS:
                yield self.finding(
                    ctx, node,
                    f"{chain}() blocks on wall time inside the fast-time "
                    f"simulation surface — fleetsim time belongs to the "
                    f"virtual clock (advance_to/advance) and transport "
                    f"waits are socket-timeout-bounded; a sleep or "
                    f"wall-clock read here makes the simulated day run "
                    f"at wall speed and breaks byte-identical reports")
