"""Purity/hygiene rules: global-state RNG, mutable defaults, bare except.

``np.random`` global-state draws in library code break two contracts at
once: determinism (any other consumer advances the stream — a model's
init changes because a dataloader shuffled first) and traceability (the
draw happens at trace time under jit; see GL008). Library randomness
must route through paddle_tpu.framework.random so `paddle.seed` governs
one reproducible stream.
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..engine import Finding, ModuleContext, Rule, register
from . import attr_chain

# global-stream draws: order-dependent on every other np.random consumer
_GLOBAL_DRAWS = frozenset({
    "seed", "rand", "randn", "randint", "random", "random_sample",
    "permutation", "shuffle", "choice", "uniform", "normal", "binomial",
    "beta", "poisson", "exponential", "standard_normal", "bytes",
})


@register
class NpRandomRule(Rule):
    """GL003: ``np.random.*`` in library modules. Global-stream draws are
    flagged everywhere; even seeded local generators
    (``RandomState``/``default_rng``) are flagged outside data modules —
    library randomness must come from framework.random so ``paddle.seed``
    controls it (and TP-aware RNG can partition it)."""

    id = "GL003"
    name = "np-random"
    description = ("np.random in library code breaks determinism and "
                   "tracing — route through paddle_tpu.framework.random "
                   "(derived_rng/next_key)")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            chain = None
            if isinstance(node, ast.Attribute):
                chain = attr_chain(node)
            if chain is None or not chain.startswith(("np.random.",
                                                      "numpy.random.")):
                continue
            tail = chain.split("random.", 1)[1]
            if "." in tail:  # only the direct member, not sub-attrs
                continue
            if tail in _GLOBAL_DRAWS:
                yield self.finding(
                    ctx, node,
                    f"{chain} uses the GLOBAL numpy stream — any other "
                    f"consumer reorders it; use framework.random.derived_rng")
            elif (not ctx.is_data_module
                    and tail in ("RandomState", "default_rng")):
                yield self.finding(
                    ctx, node,
                    f"{chain} creates an ad-hoc generator outside "
                    f"framework.random — paddle.seed cannot govern it; use "
                    f"framework.random.derived_rng")


@register
class MutableDefaultRule(Rule):
    """GL004: mutable default argument — shared across calls, a classic
    aliasing bug that state-carrying server/engine classes cannot afford."""

    id = "GL004"
    name = "mutable-default"
    description = "list/dict/set/call default arguments are shared across calls"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                continue
            args = node.args
            for d in list(args.defaults) + [d for d in args.kw_defaults if d]:
                if isinstance(d, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                                  ast.DictComp, ast.SetComp)):
                    name = getattr(node, "name", "<lambda>")
                    yield self.finding(
                        ctx, d,
                        f"mutable default in '{name}' is evaluated once and "
                        f"shared by every call — default to None and build "
                        f"inside")


@register
class BareExceptRule(Rule):
    """GL005: bare ``except:`` — swallows KeyboardInterrupt/SystemExit and
    masks tracer leaks (jax errors surface as plain Exceptions)."""

    id = "GL005"
    name = "bare-except"
    description = "bare except: catches SystemExit/KeyboardInterrupt too"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield self.finding(
                    ctx, node,
                    "bare except: catches everything incl. SystemExit — "
                    "name the exception (at minimum `except Exception`)")
