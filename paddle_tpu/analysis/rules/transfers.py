"""Transfer hygiene: no bare device transfers inside the serving stack.

The multi-chip serving engine keeps device placement in exactly three
sanctioned seams: construction-time sharding (``parallel/serving_mesh.py``
places params/pools/LoRA pages with mesh-aware ``NamedSharding``), the
fixed-width host-gather path (``kv_offload.py``'s pinned payload capture),
and the CRC-verified migration admit. A bare ``jax.device_put`` inside
``paddle_tpu/inference/`` silently REPLACES a tensor's sharding with
single-device placement — on a tp mesh that un-shards a pool (tripling
HBM and breaking the per-shard capacity math) without any error; a bare
``jax.device_get`` is an unaccounted full-width D2H sync that dodges the
offload engine's pinning/byte accounting. Both belong behind the seams,
not inline.
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..engine import Finding, ModuleContext, Rule, register
from . import attr_chain


@register
class BareTransferInServingRule(Rule):
    """GL014: bare ``jax.device_put``/``jax.device_get`` inside
    ``paddle_tpu/inference/``. Placement belongs to the mesh-aware
    helpers in ``parallel/serving_mesh.py`` (which carry the tp
    ``NamedSharding``) and host transfers to the offload engine's
    accounted gather path; an inline transfer un-shards pools or dodges
    byte accounting silently."""

    id = "GL014"
    name = "bare-transfer-in-serving"
    description = ("bare jax.device_put()/jax.device_get() calls inside "
                   "paddle_tpu/inference/ bypass the mesh-aware placement "
                   "seam (parallel/serving_mesh.py) and the offload "
                   "engine's accounted host-gather path; on a tp mesh a "
                   "bare device_put silently un-shards the tensor it "
                   "places — route transfers through the sanctioned "
                   "helpers instead")

    _SCOPE = "paddle_tpu/inference/"

    _TRANSFER_CALLS = frozenset({
        "jax.device_put", "jax.device_get",
        "jax.device_put_sharded", "jax.device_put_replicated",
    })

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        if not ctx.path.startswith(self._SCOPE):
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if chain in self._TRANSFER_CALLS:
                yield self.finding(
                    ctx, node,
                    f"{chain}() is a bare device transfer inside "
                    f"inference/ — place through the mesh-aware helpers "
                    f"in parallel/serving_mesh.py (sharding-preserving) "
                    f"or the kv_offload gather path (byte-accounted) "
                    f"instead")
