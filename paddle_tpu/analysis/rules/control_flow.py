"""Control-flow rules: Python branching on traced values and host side
effects inside jit-compiled functions.

``if``/``while`` on a traced array forces concretization: under jit it
raises; in eager mode it blocks on the device AND guarantees the code can
never move under ``jax.jit`` without a rewrite to ``lax.cond``/``select``.
Side effects (wall-clock reads, prints, global RNG) inside a jitted
function run once at trace time and then never again — a classic silent
staleness bug (the traced value is baked into the executable).
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..engine import Finding, ModuleContext, Rule, register
from . import attr_chain, contains_jnp_call, contains_value_attr


@register
class TracedBranchRule(Rule):
    """GL002: Python ``if``/``while``/ternary/assert whose test is a jnp
    expression — data-dependent host control flow."""

    id = "GL002"
    name = "traced-branch"
    description = ("Python control flow on a jnp value concretizes the "
                   "array (host sync; ConcretizationTypeError under jit) — "
                   "use lax.cond/jnp.where or branch on static metadata")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.If, ast.While, ast.IfExp)):
                test, kind = node.test, type(node).__name__.lower()
            elif isinstance(node, ast.Assert):
                test, kind = node.test, "assert"
            else:
                continue
            if contains_jnp_call(test) or self._compares_device(test):
                yield self.finding(
                    ctx, node,
                    f"{kind} condition evaluates a traced/device value — "
                    f"rewrite with jnp.where/lax.cond or hoist the decision "
                    f"to static metadata")

    @staticmethod
    def _compares_device(test: ast.AST) -> bool:
        """Comparison where one side unwraps a Tensor (`x.value > 0`)."""
        for sub in ast.walk(test):
            if isinstance(sub, ast.Compare):
                sides = [sub.left] + list(sub.comparators)
                if any(contains_value_attr(s) for s in sides):
                    return True
        return False


_EFFECT_CALLS = {
    "time.time": "wall-clock read",
    "time.perf_counter": "wall-clock read",
    "time.monotonic": "wall-clock read",
    "datetime.now": "wall-clock read",
    "print": "stdout write",
    "input": "stdin read",
    "open": "file I/O",
}


@register
class EffectInJitRule(Rule):
    """GL008: host side effects inside a function this module jit-compiles.
    They execute at trace time only — the compiled executable replays the
    traced constant forever after."""

    id = "GL008"
    name = "effect-in-jit"
    description = ("time.time()/print()/np.random/file I/O inside a jitted "
                   "function runs once at trace time and never again — "
                   "hoist it out or pass the value as an argument")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        if not ctx.jitted_names:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name not in ctx.jitted_names:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                chain = attr_chain(sub.func)
                if chain in _EFFECT_CALLS:
                    yield self.finding(
                        ctx, sub,
                        f"{chain}() inside jitted '{node.name}' is a "
                        f"{_EFFECT_CALLS[chain]}: it happens at trace time "
                        f"only, then the compiled value is frozen")
                elif chain is not None and chain.startswith(
                        ("np.random.", "numpy.random.", "random.")):
                    yield self.finding(
                        ctx, sub,
                        f"{chain}() inside jitted '{node.name}' draws ONE "
                        f"value at trace time — every compiled call replays "
                        f"it; thread a jax.random key instead")


@register
class AdapterBranchInJitRule(Rule):
    """GL009: Python branching on adapter ids inside a jitted function.
    The multi-adapter serving contract (inference/lora.py) is that
    adapter selection happens by GATHER — per-slot indices into the
    stacked pool tensors — so adapter churn never changes compiled
    shapes. A Python ``if``/``while``/ternary on an adapter id either
    concretizes a traced index (error under jit) or, if the id arrives
    as a static arg, forks the jit cache per adapter — the per-adapter
    recompile storm the pool exists to prevent."""

    id = "GL009"
    name = "adapter-branch-in-jit"
    description = ("Python control flow on an adapter id inside a jitted "
                   "function — adapter selection must be a static-shape "
                   "gather from the pooled factors (inference/lora.py), "
                   "never a data-dependent branch: traced ids raise, "
                   "static ids recompile once per adapter")

    # identifiers that carry adapter identity through the serving stack
    _EXACT = ("aidx",)
    _SUBSTR = ("adapter",)

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        if not ctx.jitted_names:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name not in ctx.jitted_names:
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, (ast.If, ast.While, ast.IfExp)):
                    continue
                name = self._adapter_name(sub.test)
                if name is not None:
                    kind = type(sub).__name__.lower()
                    yield self.finding(
                        ctx, sub,
                        f"{kind} on adapter id '{name}' inside jitted "
                        f"'{node.name}' — gather the slot's factors from "
                        f"the pool by index (static shapes) instead of "
                        f"branching on which adapter is active")

    @classmethod
    def _adapter_name(cls, test: ast.AST):
        """First identifier in the test that names an adapter id."""
        for sub in ast.walk(test):
            ident = None
            if isinstance(sub, ast.Name):
                ident = sub.id
            elif isinstance(sub, ast.Attribute):
                ident = sub.attr
            if ident is None:
                continue
            low = ident.lower()
            if low in cls._EXACT or any(s in low for s in cls._SUBSTR):
                return ident
        return None
