"""Atomic-write hygiene: no bare write-mode ``open()`` under checkpoint
paths.

The crash-safety contract of the checkpoint stack
(``distributed/checkpoint.py``, ``distributed/train_checkpoint.py``,
``incubate/checkpoint/``) is stage → manifest → ``os.replace``: a file
written in place can be torn by a kill at any byte boundary, and a torn
file that keeps its final name is the one failure mode the CRC32
manifest cannot always catch (the manifest itself, or a file written
after it, may be the torn one). Every durable write must therefore land
in a staging location and be renamed into place — the rename is the
commit point the whole degradation ladder (and the ``ckpt_write`` fault
site that tests it) is built around.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from ..engine import Finding, ModuleContext, Rule, register
from . import attr_chain

# any of these in the enclosing scope marks the write as staged-then-
# committed (or explicitly torn on purpose by the fault injector's
# truncate path, which still lives inside a committing function)
_COMMIT_CALLS = frozenset({
    "os.replace", "os.rename", "replace_dir", "write_manifest",
})

_WRITE_MODE_CHARS = ("w", "a", "x", "+")


@register
class NonAtomicCheckpointWriteRule(Rule):
    """GL013: write-mode ``open()`` in a checkpoint module with no
    rename-commit in the enclosing scope. A kill mid-write leaves a torn
    file under its FINAL name — exactly the corruption the manifest +
    ``os.replace`` protocol exists to make impossible."""

    id = "GL013"
    name = "non-atomic-ckpt-write"
    description = ("bare open(..., 'wb')-style writes under checkpoint "
                   "paths tear on kill; stage the file and commit it "
                   "with os.replace/os.rename (or route through "
                   "replace_dir/write_manifest) so the rename is the "
                   "atomic commit point — a write-mode open whose "
                   "enclosing function never renames is a torn-file "
                   "hazard")

    _SCOPE_PART = "checkpoint"

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        if self._SCOPE_PART not in ctx.path:
            return
        yield from self._scan(ctx, ctx.tree, scope_commits=False)

    def _scan(self, ctx: ModuleContext, scope: ast.AST,
              scope_commits: bool) -> Iterable[Finding]:
        """Walk one scope (module or function body). Nested functions
        recurse with their OWN commit verdict — an os.replace in an outer
        function doesn't bless a torn write in a closure that may run on
        another thread or never reach the rename."""
        commits = scope_commits or self._has_commit_call(scope)
        for node in self._scope_body(scope):
            for sub in self._walk_scope(node):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield from self._scan(ctx, sub, scope_commits=False)
                    continue
                if not commits and isinstance(sub, ast.Call) and \
                        self._write_mode(sub) is not None:
                    yield self.finding(
                        ctx, sub,
                        f"open(..., {self._write_mode(sub)!r}) in a "
                        f"checkpoint module without os.replace/os.rename "
                        f"in the enclosing scope — a kill mid-write "
                        f"leaves a torn file under its final name; "
                        f"stage and rename (the commit point), or route "
                        f"through replace_dir/write_manifest")

    @staticmethod
    def _scope_body(scope: ast.AST) -> List[ast.AST]:
        return list(getattr(scope, "body", []))

    @classmethod
    def _walk_scope(cls, node: ast.AST):
        """Yield nodes of this scope only; nested function defs are
        yielded (for recursion) but not descended into here."""
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        for child in ast.iter_child_nodes(node):
            yield from cls._walk_scope(child)

    @classmethod
    def _has_commit_call(cls, scope: ast.AST) -> bool:
        for node in cls._iter_scope_nodes(scope):
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if chain is None:
                    continue
                if chain in _COMMIT_CALLS or \
                        chain.rsplit(".", 1)[-1] in _COMMIT_CALLS:
                    return True
        return False

    @classmethod
    def _iter_scope_nodes(cls, scope: ast.AST):
        for node in getattr(scope, "body", []):
            yield from cls._walk_scope(node)

    @classmethod
    def _write_mode(cls, call: ast.Call) -> Optional[str]:
        """The literal write mode of an ``open()``/``io.open()`` call, or
        None for reads / non-open calls / non-literal modes (can't
        tell statically — stay quiet rather than cry wolf)."""
        chain = attr_chain(call.func)
        if chain not in ("open", "io.open"):
            return None
        mode_node: Optional[ast.AST] = None
        if len(call.args) >= 2:
            mode_node = call.args[1]
        for kw in call.keywords:
            if kw.arg == "mode":
                mode_node = kw.value
        if mode_node is None:
            return None  # default "r"
        if not (isinstance(mode_node, ast.Constant)
                and isinstance(mode_node.value, str)):
            return None
        mode = mode_node.value
        if any(c in mode for c in _WRITE_MODE_CHARS):
            return mode
        return None
