"""Host-sync rules: code that silently pulls a device array to the host.

On TPU every such pull is a blocking device→host round trip that also
fences the XLA dispatch queue; one per gradient per step (the pattern
this rule was written against — nn/clip.py's old global-norm loop) turns
a fused reduction into a serial sync storm. Under ``jax.jit`` tracing the
same code raises ``ConcretizationTypeError`` instead, so these sites are
latent jit-compatibility bugs too.
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..engine import Finding, ModuleContext, Rule, register
from . import attr_chain, contains_jnp_call, contains_value_attr


def _derives_from_device(node: ast.AST) -> bool:
    return contains_jnp_call(node) or contains_value_attr(node)


@register
class HostSyncRule(Rule):
    """GL001: ``float()``/``int()``/``bool()`` over a jnp expression,
    ``.item()``/``.tolist()`` calls, and ``np.asarray()`` of a device
    value — each one a blocking device→host sync."""

    id = "GL001"
    name = "host-sync"
    description = ("float()/int()/bool()/.item()/.tolist()/np.asarray() on "
                   "a device value blocks on a device->host transfer (and "
                   "fails to trace under jit)")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        if ctx.is_data_module:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            # float(jnp.sum(...)) / int(x.value.max()) / bool(jnp.any(...))
            if (isinstance(fn, ast.Name) and fn.id in ("float", "int", "bool")
                    and node.args
                    and _derives_from_device(node.args[0])):
                yield self.finding(
                    ctx, node,
                    f"{fn.id}() on a device value is a blocking host sync — "
                    f"keep the computation in jnp (traced) instead")
            # x.item() / x.tolist() — Tensor/jax.Array host pulls
            elif (isinstance(fn, ast.Attribute)
                    and fn.attr in ("item", "tolist")
                    and not node.keywords):
                chain = attr_chain(fn.value)
                # dict.items() is different; .item with args is ndarray
                # indexing — still a pull, still flagged
                yield self.finding(
                    ctx, node,
                    f".{fn.attr}() pulls the array to the host; in library "
                    f"code prefer traced jnp ops (chain: "
                    f"{chain or '<expr>'})")
            # np.asarray(t.value) / np.array(jnp....)
            elif (isinstance(fn, ast.Attribute)
                    and fn.attr in ("asarray", "array")
                    and attr_chain(fn) in ("np.asarray", "np.array",
                                           "numpy.asarray", "numpy.array")
                    and node.args
                    and _derives_from_device(node.args[0])):
                yield self.finding(
                    ctx, node,
                    f"np.{fn.attr}() of a device value forces a host copy — "
                    f"stay in jnp, or sync once at a deliberate boundary")


_NP_MATH = frozenset({
    "sum", "mean", "dot", "matmul", "einsum", "exp", "log", "sqrt",
    "square", "abs", "maximum", "minimum", "max", "min", "prod", "tanh",
    "power", "clip", "argmax", "argmin", "linalg.norm", "cumsum", "where",
})


@register
class NumpyOnTensorRule(Rule):
    """GL006: numpy math applied to a Tensor's device value. The result
    is a HOST ndarray: the transfer is implicit, gradients are severed,
    and the op runs on CPU instead of the MXU."""

    id = "GL006"
    name = "np-on-tensor"
    description = ("np.<math>(x.value) silently computes on host — use the "
                   "jnp equivalent so XLA fuses it on device")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        if ctx.is_data_module:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if chain is None or not chain.startswith(("np.", "numpy.")):
                continue
            tail = chain.split(".", 1)[1]
            if tail not in _NP_MATH:
                continue
            if any(contains_value_attr(a) for a in node.args):
                yield self.finding(
                    ctx, node,
                    f"{chain}() over a Tensor value runs on host and severs "
                    f"the autograd tape — use jnp.{tail}")
