"""paddle_tpu.analysis — tracing-safety static analysis (graftlint) and
the dynamic jit-cache regression guard.

The reference Paddle bakes correctness tooling into the framework
(nan/inf sanitizer wiring, op checkers); the TPU-native analogue guards
the hazards of a traced stack: host syncs, traced-value control flow,
impure RNG, silent recompilation. See docs/static_analysis.md.
"""
from .baseline import (build_baseline, filter_new, load_baseline,
                       save_baseline)
from .engine import (Finding, ModuleContext, Rule, all_rules, analyze_paths,
                     analyze_source, parse_suppressions, register)
from .recompile_guard import (JitCacheGuard, RecompileError, compile_count,
                              jit_cache_guard)

__all__ = [
    "Finding", "ModuleContext", "Rule", "register", "all_rules",
    "analyze_source", "analyze_paths", "parse_suppressions",
    "load_baseline", "save_baseline", "build_baseline", "filter_new",
    "JitCacheGuard", "RecompileError", "jit_cache_guard", "compile_count",
]
