"""graftlint baseline: committed ledger of pre-existing violations.

Existing debt must not block the gate (the analyzer lands on a codebase
with live findings), but NEW violations must fail immediately. The
baseline maps finding fingerprints (``path::rule::stripped-source-line``
— line-number-free, so edits elsewhere in a file don't churn it) to
occurrence counts. A finding is "new" once its fingerprint count is
exhausted; a fingerprint that no longer matches anything is stale and is
dropped on the next ``--update-baseline``.
"""
from __future__ import annotations

import json
from collections import Counter
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

from .engine import Finding

__all__ = ["load_baseline", "save_baseline", "build_baseline", "filter_new"]

_VERSION = 1


def load_baseline(path) -> Dict[str, int]:
    p = Path(path)
    if not p.exists():
        return {}
    data = json.loads(p.read_text(encoding="utf-8"))
    if data.get("version") != _VERSION:
        raise ValueError(
            f"baseline {p}: unsupported version {data.get('version')!r}")
    entries = data.get("entries", {})
    return {str(k): int(v) for k, v in entries.items()}


def save_baseline(path, entries: Dict[str, int]) -> None:
    p = Path(path)
    payload = {
        "version": _VERSION,
        "comment": "graftlint debt ledger — regenerate with "
                   "`python tools/graftlint.py paddle_tpu --update-baseline`",
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    p.write_text(json.dumps(payload, indent=1) + "\n", encoding="utf-8")


def build_baseline(findings: Sequence[Finding]) -> Dict[str, int]:
    return dict(Counter(f.key() for f in findings))


def filter_new(findings: Sequence[Finding], baseline: Dict[str, int],
               ) -> Tuple[List[Finding], int, int]:
    """Split findings against the baseline.

    Returns (new_findings, #baselined, #stale) where #stale counts
    baseline occurrences no current finding consumed (removed code —
    worth an ``--update-baseline`` to keep the ledger honest).
    """
    budget = dict(baseline)
    new: List[Finding] = []
    n_base = 0
    for f in findings:
        k = f.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            n_base += 1
        else:
            new.append(f)
    stale = sum(v for v in budget.values() if v > 0)
    return new, n_base, stale
