"""graftlint rule engine: AST-based tracing-safety analysis for the
jax_graft codebase.

The reference Paddle ships heavy op-level correctness tooling (nan/inf
sanitizers, kernel checkers under paddle/fluid/framework/details/). A
pjit-based stack has a different hazard class: *tracer-unsafe Python* —
host syncs in library code, Python control flow on traced values, impure
RNG inside trace regions — which breaks or silently deoptimizes only once
the code runs under ``jax.jit`` on a real TPU. Those patterns are
statically detectable, so we detect them statically.

Design:

- A :class:`Rule` visits one parsed module (:class:`ModuleContext`) and
  yields :class:`Finding`s. Rules register via :func:`register` so the
  set is pluggable (tools, tests and the pytest gate all share it).
- Per-line suppression: ``# graftlint: noqa`` silences every rule on
  that line; ``# graftlint: noqa[host-sync,np-random]`` silences only
  the listed rules (ids like ``GL001`` also accepted).
- Existing debt is tracked in a committed baseline (see baseline.py)
  instead of blocking the gate; new violations fail immediately.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Type

__all__ = [
    "Finding", "ModuleContext", "Rule", "register", "all_rules",
    "parse_suppressions", "analyze_source", "analyze_paths", "iter_py_files",
]


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    path: str          # repo-relative posix path
    line: int          # 1-based
    col: int           # 0-based
    rule_id: str       # "GL001"
    rule_name: str     # "host-sync"
    message: str
    snippet: str = ""  # stripped source line (baseline fingerprint)

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col + 1}: "
                f"{self.rule_id} [{self.rule_name}] {self.message}")

    def key(self) -> str:
        """Baseline fingerprint — deliberately line-number-free so
        unrelated edits above a known violation don't churn the baseline."""
        return f"{self.path}::{self.rule_id}::{self.snippet}"


# Modules whose *job* is host-side data preparation: RNG-based synthesis
# and numpy math there is the workload, not a tracing hazard.
_DATA_MODULE_PARTS = (
    "dataset", "vision", "io", "text", "audio", "reader", "hub",
)


@dataclass
class ModuleContext:
    """Everything a rule may look at for one module."""

    path: str                      # repo-relative posix path
    tree: ast.Module
    lines: List[str]
    is_data_module: bool = False
    # function names (local defs / lambdas assigned to names) that flow
    # into jax.jit in this module, plus defs decorated with jit
    jitted_names: frozenset = frozenset()

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


class Rule:
    """Base rule. Subclasses set ``id``/``name``/``description`` and
    implement :meth:`check`."""

    id: str = ""
    name: str = ""
    description: str = ""

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(path=ctx.path, line=line,
                       col=getattr(node, "col_offset", 0),
                       rule_id=self.id, rule_name=self.name,
                       message=message, snippet=ctx.line_text(line))


_REGISTRY: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    if not cls.id or not cls.name:
        raise ValueError(f"rule {cls.__name__} needs id and name")
    if cls.id in _REGISTRY and _REGISTRY[cls.id] is not cls:
        raise ValueError(f"duplicate rule id {cls.id}")
    _REGISTRY[cls.id] = cls
    return cls


def all_rules() -> List[Rule]:
    # import for side effect: rule modules self-register
    from . import rules  # noqa: F401

    return [cls() for _, cls in sorted(_REGISTRY.items())]


# --------------------------------------------------------------------------- #
# Suppressions
# --------------------------------------------------------------------------- #

_NOQA_RE = re.compile(r"#\s*graftlint:\s*noqa(?:\[([^\]]*)\])?", re.I)


def parse_suppressions(lines: Sequence[str]) -> Dict[int, Optional[frozenset]]:
    """Map 1-based line numbers to suppressed rule sets.

    ``None`` means blanket (all rules); otherwise a frozenset of
    lower-cased rule names/ids listed in ``noqa[...]``.
    """
    out: Dict[int, Optional[frozenset]] = {}
    for i, text in enumerate(lines, start=1):
        m = _NOQA_RE.search(text)
        if not m:
            continue
        spec = m.group(1)
        if spec is None or not spec.strip():
            out[i] = None
        else:
            out[i] = frozenset(
                s.strip().lower() for s in spec.split(",") if s.strip())
    return out


def _suppressed(f: Finding, sup: Dict[int, Optional[frozenset]]) -> bool:
    rules = sup.get(f.line, False)
    if rules is False:
        return False
    if rules is None:
        return True
    return f.rule_id.lower() in rules or f.rule_name.lower() in rules


# --------------------------------------------------------------------------- #
# Per-module analysis
# --------------------------------------------------------------------------- #


def _collect_jitted_names(tree: ast.Module) -> frozenset:
    """Names of functions this module hands to jax.jit — via decorator
    (``@jax.jit``, ``@jit``, ``@partial(jax.jit, ...)``) or call-site
    (``jax.jit(fn)``). Used by the effect-in-jit rule."""

    def is_jit_ref(node: ast.AST) -> bool:
        if isinstance(node, ast.Attribute):
            return node.attr == "jit"
        if isinstance(node, ast.Name):
            return node.id in ("jit", "pjit")
        return False

    names = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if is_jit_ref(target):
                    names.add(node.name)
                elif (isinstance(dec, ast.Call) and dec.args
                      and is_jit_ref(dec.args[0])):  # @partial(jax.jit, ...)
                    names.add(node.name)
        elif isinstance(node, ast.Call) and is_jit_ref(node.func):
            for a in node.args[:1]:
                if isinstance(a, ast.Name):
                    names.add(a.id)
                elif isinstance(a, ast.Attribute):
                    names.add(a.attr)
    return frozenset(names)


def _is_data_module(rel_path: str) -> bool:
    parts = Path(rel_path).parts
    return any(p.split(".")[0] in _DATA_MODULE_PARTS for p in parts)


def analyze_source(src: str, path: str,
                   rules: Optional[Sequence[Rule]] = None,
                   ) -> Tuple[List[Finding], int]:
    """Analyze one module's source. Returns (active findings, #suppressed)."""
    rules = list(rules) if rules is not None else all_rules()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(path=path, line=e.lineno or 1, col=0,
                        rule_id="GL000", rule_name="syntax-error",
                        message=f"could not parse: {e.msg}")], 0
    lines = src.splitlines()
    ctx = ModuleContext(path=path, tree=tree, lines=lines,
                        is_data_module=_is_data_module(path),
                        jitted_names=_collect_jitted_names(tree))
    sup = parse_suppressions(lines)
    active: List[Finding] = []
    n_suppressed = 0
    seen = set()
    for rule in rules:
        for f in rule.check(ctx):
            dk = (f.path, f.line, f.col, f.rule_id)
            if dk in seen:
                continue
            seen.add(dk)
            if _suppressed(f, sup):
                n_suppressed += 1
            else:
                active.append(f)
    active.sort(key=lambda f: (f.line, f.col, f.rule_id))
    return active, n_suppressed


def iter_py_files(paths: Sequence[str], root: Optional[Path] = None):
    """Yield (abs_path, repo_relative_posix) for every .py under ``paths``."""
    root = Path(root) if root is not None else Path.cwd()
    for p in paths:
        base = Path(p)
        if not base.is_absolute():
            base = root / base
        if base.is_file():
            files = [base]
        else:
            files = sorted(base.rglob("*.py"))
        for f in files:
            try:
                rel = f.resolve().relative_to(root.resolve()).as_posix()
            except ValueError:
                rel = f.as_posix()
            yield f, rel


def analyze_paths(paths: Sequence[str], root: Optional[Path] = None,
                  rules: Optional[Sequence[Rule]] = None,
                  ) -> Tuple[List[Finding], int, int]:
    """Analyze every .py file under ``paths``.

    Returns (findings, #files, #suppressed)."""
    rules = list(rules) if rules is not None else all_rules()
    findings: List[Finding] = []
    n_files = 0
    n_sup = 0
    for f, rel in iter_py_files(paths, root):
        n_files += 1
        src = f.read_text(encoding="utf-8")
        got, sup = analyze_source(src, rel, rules)
        findings.extend(got)
        n_sup += sup
    findings.sort(key=lambda x: (x.path, x.line, x.col, x.rule_id))
    return findings, n_files, n_sup
