"""paddle.audio parity (ref: python/paddle/audio/ — features + functional)."""
from . import features, functional

__all__ = ["features", "functional"]
