"""paddle.audio parity (ref: python/paddle/audio/ — features, functional,
backends; init_backend binds load/save/info onto paddle.audio)."""
from . import backends, features, functional
from .backends import (get_current_backend, info, list_available_backends,
                       load, save, set_backend)

__all__ = ["features", "functional", "backends", "load", "save", "info",
           "list_available_backends", "get_current_backend", "set_backend"]
