"""Audio IO backends (ref: python/paddle/audio/backends/ — backend.py
AudioInfo:21, wave_backend.py info:37/load:89/save:168, init_backend.py
list_available_backends:37/get_current_backend:93/set_backend:135).

``load``/``save``/``info`` dispatch through the registry: the stdlib
``wave`` backend (16-bit PCM WAV) is always available; ``soundfile`` is
used for other formats when the optional package is installed — mirroring
the reference's wave_backend / paddleaudio split."""
from __future__ import annotations

from . import soundfile_backend, wave_backend
from .init_backend import (get_current_backend, list_available_backends,
                           set_backend)
from .wave_backend import AudioInfo

_MODULES = {"wave": wave_backend, "soundfile": soundfile_backend}


def _backend():
    return _MODULES[get_current_backend()]


def info(filepath):
    return _backend().info(filepath)


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    return _backend().load(filepath, frame_offset, num_frames, normalize,
                           channels_first)


def save(filepath, src, sample_rate, channels_first=True, encoding="PCM_S",
         bits_per_sample=16):
    return _backend().save(filepath, src, sample_rate, channels_first,
                           encoding, bits_per_sample)


__all__ = ["AudioInfo", "info", "load", "save", "list_available_backends",
           "get_current_backend", "set_backend"]
