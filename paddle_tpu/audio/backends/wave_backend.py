"""WAV backend on the stdlib ``wave`` module (ref:
python/paddle/audio/backends/wave_backend.py — info:37, load:89, save:168;
AudioInfo ref backend.py:21).

16-bit PCM in/out like the reference's wave_backend: ``load`` returns
float32 in [-1, 1] when ``normalize`` (else raw int16), shaped
``(channels, frames)`` when ``channels_first``."""
from __future__ import annotations

import wave
from typing import Tuple, Union

import numpy as np

from ...framework.core import Tensor


class AudioInfo:
    """Ref backends/backend.py:21."""

    def __init__(self, sample_rate: int, num_frames: int, num_channels: int,
                 bits_per_sample: int, encoding: str):
        self.sample_rate = sample_rate
        self.num_frames = num_frames
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def _open(filepath):
    if hasattr(filepath, "read"):
        return filepath, False
    return open(filepath, "rb"), True


def info(filepath) -> AudioInfo:
    """Signal info of a WAV file (ref wave_backend.py:37)."""
    file_obj, owned = _open(filepath)
    try:
        f = wave.open(file_obj)
    except wave.Error as e:
        if owned:
            file_obj.close()
        raise NotImplementedError(
            f"only 16-bit PCM WAV is supported by the wave backend ({e}); "
            f"install soundfile for other formats") from e
    try:
        width = f.getsampwidth()
        # WAV spec: 1-byte samples are unsigned; wider are signed PCM
        out = AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                        width * 8, "PCM_U" if width == 1 else "PCM_S")
    finally:
        if owned:
            file_obj.close()
    return out


def load(filepath, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True
         ) -> Tuple[Tensor, int]:
    """Load WAV audio (ref wave_backend.py:89): float32 in [-1,1] when
    ``normalize`` else int16; (channels, time) when ``channels_first``."""
    file_obj, owned = _open(filepath)
    try:
        try:
            f = wave.open(file_obj)
        except wave.Error as e:
            raise NotImplementedError(
                f"only 16-bit PCM WAV is supported by the wave backend "
                f"({e}); install soundfile for other formats") from e
        channels = f.getnchannels()
        rate = f.getframerate()
        width = f.getsampwidth()
        if width != 2:
            raise NotImplementedError(
                f"wave backend reads 16-bit PCM only, got {width * 8}-bit")
        if frame_offset:
            f.setpos(min(frame_offset, f.getnframes()))
        n = f.getnframes() - f.tell() if num_frames < 0 else num_frames
        raw = f.readframes(max(n, 0))
    finally:
        if owned:
            file_obj.close()
    data = np.frombuffer(raw, dtype="<i2").reshape(-1, channels)
    if normalize:
        data = (data.astype(np.float32) / 32768.0)
    if channels_first:
        data = data.T
    import jax.numpy as jnp

    return Tensor(jnp.asarray(np.ascontiguousarray(data))), rate


def save(filepath: str, src, sample_rate: int, channels_first: bool = True,
         encoding: str = "PCM_S", bits_per_sample: int = 16) -> None:
    """Save to 16-bit PCM WAV (ref wave_backend.py:168). ``src``:
    (channels, time) when ``channels_first`` else (time, channels); float
    input is clipped to [-1, 1] and scaled."""
    if bits_per_sample != 16 or encoding != "PCM_S":
        raise NotImplementedError(
            "wave backend writes 16-bit PCM_S only; install soundfile for "
            "other encodings")
    a = np.asarray(src.value if isinstance(src, Tensor) else src)
    if a.ndim == 1:
        a = a[None, :] if channels_first else a[:, None]
    if channels_first:
        a = a.T  # -> (frames, channels)
    if np.issubdtype(a.dtype, np.floating):
        a = (np.clip(a, -1.0, 1.0) * 32767.0).astype("<i2")
    elif a.dtype == np.int16:
        a = a.astype("<i2")
    else:
        # wider ints would wrap mod 2^16 and write garbage noise
        raise ValueError(
            f"wave backend writes int16 or float input, got {a.dtype}; "
            f"rescale to [-1, 1] float or int16 first")
    with wave.open(filepath, "wb") as f:
        f.setnchannels(a.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(np.ascontiguousarray(a).tobytes())
