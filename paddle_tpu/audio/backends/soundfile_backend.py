"""Optional ``soundfile`` backend (ref: the paddleaudio backend role in
init_backend.py) — full-format load/save/info when the package exists."""
from __future__ import annotations

import numpy as np

from ...framework.core import Tensor
from .wave_backend import AudioInfo


def _sf():
    import soundfile

    return soundfile


def info(filepath) -> AudioInfo:
    i = _sf().info(filepath)
    bits = {"PCM_16": 16, "PCM_24": 24, "PCM_32": 32, "PCM_S8": 8,
            "PCM_U8": 8}.get(i.subtype, 16)
    return AudioInfo(int(i.samplerate), int(i.frames), int(i.channels),
                     bits, i.subtype or "PCM_S")


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    sf = _sf()
    stop = None if num_frames < 0 else frame_offset + num_frames
    data, rate = sf.read(filepath, start=frame_offset, stop=stop,
                         dtype="float32" if normalize else "int16",
                         always_2d=True)
    if channels_first:
        data = data.T
    import jax.numpy as jnp

    return Tensor(jnp.asarray(np.ascontiguousarray(data))), int(rate)


def save(filepath, src, sample_rate, channels_first=True, encoding="PCM_S",
         bits_per_sample=16):
    a = np.asarray(src.value if isinstance(src, Tensor) else src)
    if a.ndim == 1:
        a = a[None, :] if channels_first else a[:, None]
    if channels_first:
        a = a.T
    subtype = {16: "PCM_16", 24: "PCM_24", 32: "PCM_32"}.get(
        bits_per_sample, "PCM_16")
    _sf().write(filepath, a, int(sample_rate), subtype=subtype)
