"""Backend registry (ref: python/paddle/audio/backends/init_backend.py —
list_available_backends:37, get_current_backend:93, set_backend:135).

"wave" (stdlib, always available) plus "soundfile" when the optional
package is installed — mirroring the reference's wave_backend /
paddleaudio split."""
from __future__ import annotations

from typing import List

_CURRENT = "wave"


def list_available_backends() -> List[str]:
    out = ["wave"]
    try:
        import soundfile  # noqa: F401

        out.append("soundfile")
    except ImportError:
        pass
    return out


def get_current_backend() -> str:
    return _CURRENT


def set_backend(backend_name: str):
    global _CURRENT
    if backend_name not in list_available_backends():
        raise NotImplementedError(
            f"backend {backend_name!r} not available "
            f"(have {list_available_backends()})")
    _CURRENT = backend_name
