"""paddle.static.nn shim — static-graph layer builders have no TPU analogue;
the dynamic `paddle_tpu.nn` layers cover the capability."""


def __getattr__(name):
    raise NotImplementedError(
        f"paddle.static.nn.{name} is a ProgramDesc builder; use the paddle_tpu.nn layer "
        "equivalent under jit.to_static instead.")
