"""paddle.static — working static-graph facade.

The reference's ProgramDesc IR + standalone executor
(ref python/paddle/static/, fluid/executor.py:921,
framework/new_executor/interpretercore.h:42) are re-designed TPU-first:
a Program is a recorded op list captured at the central eager dispatch
point; Executor.run replays it as ONE pure function under jax.jit, so
XLA does dependency analysis / scheduling / memory planning.  See
paddle_tpu/static/graph.py for the design notes.
"""
from __future__ import annotations

from ..jit import InputSpec  # noqa: F401
from .graph import (CompiledProgram, Executor, GradMarker,  # noqa: F401
                    ParallelExecutor, Program, Scope, Variable,
                    append_backward, data, default_main_program,
                    default_startup_program, global_scope, gradients,
                    load_inference_model, program_guard,
                    reset_default_programs, save_inference_model, scope_guard)
from . import nn  # noqa: F401
from . import amp  # noqa: F401


def name_scope(prefix=None):
    import contextlib

    return contextlib.nullcontext()


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False,
                      name=None):
    import jax.numpy as jnp

    from ..framework.core import Parameter
    from .graph import _register_param, current_programs

    p = Parameter(jnp.full(shape, value, dtype=dtype), trainable=False,
                  name=name or "")
    main, startup = current_programs()
    _register_param(main, p, startup)
    return p


