"""paddle.static shim.

The reference's static graph (ProgramDesc IR + Executor,
ref python/paddle/static/) is replaced by jaxpr + XLA under
paddle_tpu.jit.to_static. This module keeps the most-used static symbols
importable so user code ports cleanly; Program-building APIs raise with
guidance.
"""
from __future__ import annotations

from ..jit import InputSpec


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape=shape, dtype=dtype, name=name)


class Program:
    def __init__(self):
        raise NotImplementedError(
            "paddle_tpu has no ProgramDesc IR; use paddle_tpu.jit.to_static (jaxpr/XLA) "
            "for compiled execution.")


def default_main_program():
    raise NotImplementedError("No static graph: see paddle_tpu.jit.to_static")


def default_startup_program():
    raise NotImplementedError("No static graph: see paddle_tpu.jit.to_static")


class Executor:
    def __init__(self, place=None):
        raise NotImplementedError(
            "The standalone executor (ref interpretercore.cc) is replaced by XLA; "
            "run models eagerly or under paddle_tpu.jit.to_static.")


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, **kwargs):
    raise NotImplementedError("Use paddle_tpu.jit.save / paddle_tpu.inference export")


def load_inference_model(path_prefix, executor=None, **kwargs):
    raise NotImplementedError("Use paddle_tpu.jit.load")


from . import nn  # noqa: E402,F401
