"""paddle.static — working static-graph facade.

The reference's ProgramDesc IR + standalone executor
(ref python/paddle/static/, fluid/executor.py:921,
framework/new_executor/interpretercore.h:42) are re-designed TPU-first:
a Program is a recorded op list captured at the central eager dispatch
point; Executor.run replays it as ONE pure function under jax.jit, so
XLA does dependency analysis / scheduling / memory planning.  See
paddle_tpu/static/graph.py for the design notes.
"""
from __future__ import annotations

from ..jit import InputSpec  # noqa: F401
from .graph import (CompiledProgram, Executor, GradMarker,  # noqa: F401
                    ParallelExecutor, Program, Scope, Variable,
                    append_backward, data, default_main_program,
                    default_startup_program, global_scope, gradients,
                    load_inference_model, program_guard,
                    reset_default_programs, save_inference_model, scope_guard)
from . import nn  # noqa: F401
from . import amp  # noqa: F401


def name_scope(prefix=None):
    import contextlib

    return contextlib.nullcontext()


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False,
                      name=None):
    import jax.numpy as jnp

    from ..framework.core import Parameter
    from .graph import _register_param, current_programs

    p = Parameter(jnp.full(shape, value, dtype=dtype), trainable=False,
                  name=name or "")
    main, startup = current_programs()
    _register_param(main, p, startup)
    return p




# ---------------------------------------------------------------------------
# static surface completeness (ref python/paddle/static/__init__.py __all__):
# places, strategy configs, serialization family, metric ops, misc helpers
# ---------------------------------------------------------------------------


def cpu_places(device_count=None):
    """ref static.cpu_places — host devices (XLA CPU)."""
    from ..fluid.core import CPUPlace

    n = device_count or 1
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """ref static.cuda_places — accelerator devices; on this backend the
    accelerators are TPU chips (CustomPlace), returned for API parity."""
    import jax

    from ..fluid.core import CustomPlace

    devs = [d for d in jax.devices() if d.platform != "cpu"]
    ids = device_ids if device_ids is not None else range(len(devs) or 1)
    return [CustomPlace("tpu", int(i)) for i in ids]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


def npu_places(device_ids=None):
    return cuda_places(device_ids)


def mlu_places(device_ids=None):
    return cuda_places(device_ids)


def device_guard(device=None):
    """ref static.device_guard — device placement context. XLA owns
    placement; the guard is accepted and recorded as a no-op."""
    import contextlib

    return contextlib.nullcontext()


class BuildStrategy:
    """ref BuildStrategy (pybind bind_build_strategy): attribute bag; the
    XLA compiler owns fusion/memory decisions, so flags are accepted and
    recorded only."""

    def __init__(self):
        self.enable_inplace = True
        self.enable_addto = False
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.fuse_bn_add_act_ops = False
        self.fuse_broadcast_ops = False
        self.fuse_all_optimizer_ops = False
        self.enable_auto_fusion = True
        self.memory_optimize = True
        self.reduce_strategy = 0
        self.gradient_scale_strategy = 0
        self.debug_graphviz_path = ""
        self.build_cinn_pass = True  # the whole backend is a compiler


class ExecutionStrategy:
    """ref ExecutionStrategy: attribute bag (XLA runtime owns execution)."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100
        self.num_iteration_per_run = 1
        self.use_thread_barrier = False


class IpuStrategy:
    """ref IpuStrategy — Graphcore IPU backend config. IPU is outside this
    framework's hardware scope (README non-goals cover non-TPU engines);
    the config object exists for import parity and raises on use."""

    def __init__(self):
        raise NotImplementedError(
            "IPU support is not part of the TPU-native backend "
            "(README non-goals); use the XLA/TPU path")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "IPU support is not part of the TPU-native backend "
            "(README non-goals)")


def ipu_shard_guard(index=-1, stage=-1):
    raise NotImplementedError("IPU support is not part of this backend")


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise NotImplementedError("IPU support is not part of this backend")


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase="both"):
    """ref static.Print op: print the tensor when executed, pass it
    through.  Under jit this becomes a jax.debug.print."""
    import numpy as np

    import jax

    from ..framework.core import Tensor
    from ..framework.dispatch import apply_op

    def f(v):
        jax.debug.print((message or "") + " {}", v)
        return v

    return apply_op(f, input)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """ref static.create_parameter."""
    from ..nn.layer_base import Layer

    holder = Layer()
    return holder.create_parameter(shape, attr=attr, dtype=dtype,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)


class WeightNormParamAttr:
    """ref static.WeightNormParamAttr — ParamAttr requesting g·v/||v||
    reparameterization (apply nn.utils.weight_norm on the built layer)."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        from ..framework.param_attr import ParamAttr

        self._attr = ParamAttr(name=name, initializer=initializer,
                               learning_rate=learning_rate,
                               regularizer=regularizer, trainable=trainable)
        self.dim = dim

    def __getattr__(self, k):
        return getattr(self.__dict__["_attr"], k)


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """ref static.accuracy op: top-k accuracy over a batch."""
    import jax.numpy as jnp

    from ..framework.dispatch import apply_op

    def f(pred, lbl):
        topk = jnp.argsort(-pred, axis=-1)[..., :k]
        hit = jnp.any(topk == lbl.reshape(-1, 1), axis=-1)
        return jnp.mean(hit.astype(jnp.float32))

    return apply_op(f, input, label)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    """ref static.auc op: batch ROC-AUC from positive-class scores
    (threshold-bucketed, matching the reference's discretization)."""
    import jax.numpy as jnp

    from ..framework.core import Tensor, to_array

    pred = to_array(input)
    lbl = to_array(label).reshape(-1)
    pos_score = pred[..., -1].reshape(-1)
    buckets = jnp.clip((pos_score * num_thresholds).astype(jnp.int32), 0,
                       num_thresholds)
    pos_hist = jnp.zeros(num_thresholds + 1).at[buckets].add(
        (lbl == 1).astype(jnp.float32))
    neg_hist = jnp.zeros(num_thresholds + 1).at[buckets].add(
        (lbl == 0).astype(jnp.float32))
    # sweep thresholds high->low accumulating TPR/FPR trapezoids
    tp = jnp.cumsum(pos_hist[::-1])
    fp = jnp.cumsum(neg_hist[::-1])
    tot_p = jnp.maximum(tp[-1], 1e-9)
    tot_n = jnp.maximum(fp[-1], 1e-9)
    tpr = tp / tot_p
    fpr = fp / tot_n
    a = jnp.trapezoid(tpr, fpr) if hasattr(jnp, "trapezoid") else \
        jnp.trapz(tpr, fpr)
    auc_out = Tensor(a)
    return auc_out, [auc_out]


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """ref static.ctr_metric_bundle: (auc, squared error, absolute error,
    prediction sum, label sum, instance count) for CTR models."""
    import jax.numpy as jnp

    from ..framework.core import Tensor, to_array

    pred = to_array(input).reshape(-1)
    lbl = to_array(label).reshape(-1).astype(jnp.float32)
    a, _ = auc(input, label)
    sqrerr = Tensor(jnp.sum((pred - lbl) ** 2))
    abserr = Tensor(jnp.sum(jnp.abs(pred - lbl)))
    prob = Tensor(jnp.sum(pred))
    q = Tensor(jnp.sum(lbl))
    pos = Tensor(jnp.sum(lbl))
    total = Tensor(jnp.asarray(float(pred.shape[0])))
    return a, sqrerr, abserr, prob, q, pos, total


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    """ref static exponential_decay: lr * decay_rate^(step/decay_steps)
    (integer division when staircase)."""
    from ..optimizer.lr import LambdaDecay

    def factor(step):
        e = step // decay_steps if staircase else step / decay_steps
        return decay_rate ** e

    return LambdaDecay(learning_rate=learning_rate, lr_lambda=factor)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """ref static.py_func: run a host python function as an op (the
    reference registers it in ProgramDesc; eagerly it just runs — under jit
    wrap with paddle_tpu.utils.cpp_extension host callbacks instead)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    res = func(*xs)
    if out is not None and hasattr(out, "_value") and hasattr(res, "value"):
        out._value = res.value
    return res


# ---- Program/state serialization family (our own format: the protobuf
# ProgramDesc is a documented non-goal; recorded Programs pickle cleanly
# and params ride framework.io_state) ----


def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    """Program MANIFEST serialization (op list, var names, param metadata).

    The protobuf ProgramDesc format is a documented non-goal (README); the
    EXECUTABLE serialization of a recorded Program is
    :func:`save_inference_model` (batch-polymorphic StableHLO).  This
    manifest supports introspection and persistable save/load, the
    dominant uses of ``static.save``/``static.load``."""
    import pickle

    import numpy as np

    from .graph import default_main_program

    prog = program or default_main_program()
    return pickle.dumps({
        "ops": [getattr(op, "type", getattr(op, "name", str(op)))
                for op in prog.ops],
        "vars": sorted(getattr(prog, "vars", {}).keys()
                       if hasattr(prog, "vars") else []),
        "params": {n: (tuple(np.asarray(p.value).shape),
                       str(np.asarray(p.value).dtype))
                   for n, p in prog.params.items()},
    })


def deserialize_program(data):
    """Inverse of :func:`serialize_program`: returns the manifest dict (see
    its docstring for the executable-program path)."""
    import pickle

    return pickle.loads(data)


def serialize_persistables(feed_vars, fetch_vars, executor=None,
                           program=None, **kwargs):
    import pickle

    from .graph import default_main_program, global_scope

    prog = program or default_main_program()
    store = global_scope().store
    state = {name: store.get(name, p.value)
             for name, p in prog.params.items()}
    return pickle.dumps(state)


def deserialize_persistables(program, data, executor=None):
    import pickle

    from .graph import global_scope

    state = pickle.loads(data)
    global_scope().store.update(state)
    return state


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def save(program, model_prefix, protocol=4, **configs):
    """ref static.save: <prefix>.pdmodel (program manifest) + .pdiparams
    (persistables).  Executable program export = save_inference_model
    (StableHLO); ProgramDesc protobuf is a documented non-goal."""
    save_to_file(model_prefix + ".pdmodel", serialize_program(None, None,
                                                              program))
    save_to_file(model_prefix + ".pdiparams",
                 serialize_persistables(None, None, program=program))


def load(program, model_prefix, executor=None, var_list=None):
    """ref static.load: restore persistables saved by :func:`save` into the
    executor scope (and the program's param init values)."""
    data = load_from_file(model_prefix + ".pdiparams")
    state = deserialize_persistables(program, data, executor)
    for name, val in state.items():
        if name in program.params:
            program.params[name]._value = val
    return state


def load_program_state(model_prefix, var_list=None):
    import pickle

    return pickle.loads(load_from_file(model_prefix + ".pdiparams"))


def set_program_state(program, state_dict):
    from .graph import global_scope

    global_scope().store.update(state_dict)
    for name, val in state_dict.items():
        if name in program.params:
            program.params[name]._value = getattr(val, "value", val)


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """ref static.normalize_program — prune to the feed→fetch subgraph; our
    recorded Programs are already minimal, so clone is the normal form."""
    return program.clone()


class ExponentialMovingAverage:
    """ref static.ExponentialMovingAverage: shadow = decay*shadow +
    (1-decay)*param with optional bias-corrected thres_steps;
    ``update()`` after each step, ``apply()`` context swaps shadows in
    for evaluation, ``restore()`` swaps back."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._thres_steps = thres_steps
        self._step = 0
        self._shadow = {}
        self._backup = {}
        self._params = []

    def _tracked(self):
        if not self._params:
            from .graph import default_main_program

            self._params = [(n, p) for n, p in
                            default_main_program().params.items()
                            if getattr(p, "trainable", True)]
        return self._params

    def update(self):
        import numpy as np

        self._step += 1
        # warm-up ramp only when thres_steps is given (ref contract);
        # otherwise constant decay from the first update
        d = (min(self._decay, (1.0 + self._step) / (10.0 + self._step))
             if self._thres_steps is not None else self._decay)
        for name, p in self._tracked():
            cur = np.asarray(p.value)
            prev = self._shadow.get(name, cur)
            self._shadow[name] = d * prev + (1.0 - d) * cur

    def apply(self, executor=None, need_restore=True):
        import contextlib

        import jax.numpy as jnp

        @contextlib.contextmanager
        def ctx():
            for name, p in self._tracked():
                if name in self._shadow:
                    self._backup[name] = p.value
                    p._value = jnp.asarray(self._shadow[name])
            try:
                yield
            finally:
                if need_restore:
                    self.restore()

        return ctx()

    def restore(self, executor=None):
        for name, p in self._tracked():
            if name in self._backup:
                p._value = self._backup.pop(name)
