"""Working static-graph facade: Program / Variable / Executor.

Reference surface: python/paddle/static (Program/Executor API,
python/paddle/fluid/executor.py:921 Executor, fluid/framework.py
Program/Block/Variable/Parameter) and the static training idiom::

    paddle.enable_static()
    with static.program_guard(main, startup):
        x = static.data('x', [None, 4])
        out = static.nn.fc(x, 8)
        loss = paddle.mean(out)
        paddle.optimizer.SGD(0.01).minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    exe.run(main, feed={'x': ...}, fetch_list=[loss])

TPU-native redesign: there is no ProgramDesc protobuf IR and no
InterpreterCore (ref framework/new_executor/interpretercore.h:42).  A
``Program`` here is a recorded list of pure-jax op closures captured at the
central dispatch point (framework/dispatch.py:apply_op) — every op of our
~300-op surface is recordable with zero per-op work, the analogue of the
reference's LayerHelper.append_op happening inside every tensor function.
``Executor.run`` replays the op list as ONE pure function and hands it to
``jax.jit`` — XLA is the standalone executor: dependency analysis, stream
assignment and memory planning (ref interpreter/dependency_builder.cc,
stream_analyzer.cc) all happen inside the compiler.  ``minimize`` on a
Program records the optimizer; grads come from ``jax.grad`` of the replayed
loss (the analogue of fluid/backward.py append_backward) and the update uses
the optimizer's ``pure_update`` — so a static train step is a single fused
XLA program: feeds+params in, fetches+new params out.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Parameter, Tensor


class _BuildState(threading.local):
    def __init__(self):
        self.static_mode = False
        self.guard_stack: List[Tuple["Program", Optional["Program"]]] = []
        self.counter = 0

    def fresh_name(self, prefix="tmp"):
        self.counter += 1
        return f"{prefix}_{self.counter}"


_STATE = _BuildState()


def enable_static_mode():
    _STATE.static_mode = True


def disable_static_mode():
    _STATE.static_mode = False


def in_static_mode() -> bool:
    # a live program_guard is static mode too — op recording and mode checks
    # must agree, or layer code branches on in_dynamic_mode() while dispatch
    # records ops
    return _STATE.static_mode or bool(_STATE.guard_stack)


def current_programs() -> Tuple["Program", Optional["Program"]]:
    if _STATE.guard_stack:
        return _STATE.guard_stack[-1]
    return default_main_program(), default_startup_program()


def static_build_active() -> bool:
    return _STATE.static_mode or bool(_STATE.guard_stack)


class Variable(Tensor):
    """Symbolic tensor inside a Program (ref fluid/framework.py Variable).

    Carries only shape/dtype metadata; ``_value`` holds a zero placeholder
    (dynamic dims -> 1) so shape-dependent Python in layer code keeps
    working during graph build."""

    def __init__(self, name: str, shape, dtype, program: "Program",
                 is_feed: bool = False):
        self.sym_shape = [(-1 if d in (None, -1) else int(d)) for d in shape]
        placeholder = jnp.zeros([1 if d == -1 else d for d in self.sym_shape],
                               dtype=dtype)
        super().__init__(placeholder, stop_gradient=True, name=name)
        self.var_name = name
        self.program = program
        self.is_feed = is_feed
        self.persistable = False

    def __repr__(self):
        return (f"Variable(name={self.var_name}, shape={self.sym_shape}, "
                f"dtype={self.dtype})")


class Operator:
    """One recorded op: pure fn + input refs + static kwargs + output names."""

    __slots__ = ("fn", "in_refs", "kwargs", "out_names", "op_name", "multi")

    def __init__(self, fn, in_refs, kwargs, out_names, op_name, multi):
        self.fn = fn
        self.in_refs = in_refs        # list of ("var", name)|("param", name)|("const", np)
        self.kwargs = kwargs
        self.out_names = out_names
        self.op_name = op_name
        self.multi = multi

    @property
    def type(self):
        return self.op_name


class Block:
    def __init__(self, program):
        self.program = program

    @property
    def ops(self):
        return self.program.ops

    @property
    def vars(self):
        return self.program.vars

    def var(self, name):
        return self.program.vars[name]

    def all_parameters(self):
        return list(self.program.params.values())


_PROGRAM_UID = [0]


class Program:
    """Recorded op graph (ref fluid/framework.py Program; no protobuf IR —
    jaxpr/XLA takes that role at Executor.run time)."""

    def __init__(self):
        # unique forever (id() can be reused after gc, which would leak one
        # program's optimizer state into another)
        _PROGRAM_UID[0] += 1
        self._uid = _PROGRAM_UID[0]
        self.ops: List[Operator] = []
        self.vars: Dict[str, Variable] = {}
        self.params: Dict[str, Parameter] = {}
        self.feeds: List[str] = []
        self.loss_name: Optional[str] = None
        self.optimizer = None
        self._block = Block(self)
        self.random_seed = None
        self._version = 0

    def global_block(self):
        return self._block

    def blocks(self):
        return [self._block]

    def list_vars(self):
        return list(self.vars.values())

    def all_parameters(self):
        return list(self.params.values())

    def clone(self, for_test: bool = False):
        p = Program()
        p.ops = list(self.ops)
        p.vars = dict(self.vars)
        p.params = dict(self.params)
        p.feeds = list(self.feeds)
        p.loss_name = self.loss_name
        p.optimizer = None if for_test else self.optimizer
        return p

    def __str__(self):
        lines = [f"Program({len(self.ops)} ops, {len(self.params)} params)"]
        for op in self.ops:
            ins = ", ".join(f"{k}:{v if k != 'const' else '<const>'}"
                            for k, v in op.in_refs)
            lines.append(f"  {op.op_name}({ins}) -> {', '.join(op.out_names)}")
        return "\n".join(lines)


_DEFAULT_MAIN: Optional[Program] = None
_DEFAULT_STARTUP: Optional[Program] = None


def default_main_program() -> Program:
    global _DEFAULT_MAIN
    if _DEFAULT_MAIN is None:
        _DEFAULT_MAIN = Program()
    return _DEFAULT_MAIN


def default_startup_program() -> Program:
    global _DEFAULT_STARTUP
    if _DEFAULT_STARTUP is None:
        _DEFAULT_STARTUP = Program()
    return _DEFAULT_STARTUP


def reset_default_programs():
    global _DEFAULT_MAIN, _DEFAULT_STARTUP
    _DEFAULT_MAIN = Program()
    _DEFAULT_STARTUP = Program()


class program_guard:
    """ref paddle.static.program_guard"""

    def __init__(self, main_program: Program, startup_program: Optional[Program] = None):
        self.pair = (main_program, startup_program)

    def __enter__(self):
        _STATE.guard_stack.append(self.pair)
        return self.pair[0]

    def __exit__(self, *exc):
        _STATE.guard_stack.pop()
        return False


def data(name: str, shape, dtype="float32", lod_level=0) -> Variable:
    """ref paddle.static.data — declare a feed Variable."""
    from ..framework.dtype import convert_dtype

    prog, _ = current_programs()
    v = Variable(name, shape, convert_dtype(dtype), prog, is_feed=True)
    prog.vars[name] = v
    if name not in prog.feeds:
        prog.feeds.append(name)
    return v


def _register_param(prog: Program, p: Parameter,
                    startup: Optional[Program] = None) -> str:
    name = getattr(p, "name", "") or ""
    if not name or (name in prog.params and prog.params[name] is not p):
        name = _STATE.fresh_name("param")
        p.name = name
    prog.params[name] = p
    if startup is not None:
        startup.params[name] = p
    return name


def record_op(fn: Callable, args: Sequence[Any], kwargs: Dict[str, Any],
              op_name: str):
    """Called from apply_op when a Variable is among the inputs: append an
    Operator to the current main program and return symbolic outputs (shape
    inference via jax.eval_shape — the analogue of phi/infermeta)."""
    prog, startup = current_programs()
    in_refs = []
    avals = []
    for a in args:
        if isinstance(a, Variable):
            in_refs.append(("var", a.var_name))
            if a.var_name not in prog.vars:
                prog.vars[a.var_name] = a
            avals.append(jax.ShapeDtypeStruct(a._value.shape, a.dtype))
        elif isinstance(a, Parameter):
            in_refs.append(("param", _register_param(prog, a, startup)))
            avals.append(jax.ShapeDtypeStruct(a.value.shape, a.value.dtype))
        elif isinstance(a, Tensor):
            c = np.asarray(a.value)
            in_refs.append(("const", c))
            avals.append(jax.ShapeDtypeStruct(c.shape, c.dtype))
        else:
            in_refs.append(("const", a))
            avals.append(a)

    out_shapes = jax.eval_shape(lambda *xs: fn(*xs, **kwargs), *avals)
    multi = isinstance(out_shapes, (tuple, list))
    outs = list(out_shapes) if multi else [out_shapes]

    out_vars = []
    for o in outs:
        name = _STATE.fresh_name(op_name or "tmp")
        v = Variable(name, o.shape, o.dtype, prog)
        prog.vars[name] = v
        out_vars.append(v)
    prog.ops.append(Operator(fn, in_refs, dict(kwargs),
                             [v.var_name for v in out_vars], op_name, multi))
    prog._version += 1
    return tuple(out_vars) if multi else out_vars[0]


def append_backward(loss: Variable, parameter_list=None, no_grad_set=None):
    """ref fluid/backward.py append_backward — here it only marks the loss;
    gradients materialize inside Executor.run via jax.grad over the replay."""
    prog = loss.program
    prog.loss_name = loss.var_name
    params = parameter_list or list(prog.params.values())
    return [(p, f"{getattr(p, 'name', 'param')}@GRAD") for p in params]


class GradMarker:
    """Symbolic gradient handle returned by static.gradients; resolvable by
    Executor.run fetch_list (grad of sum(target) w.r.t. a feed var or param)."""

    __slots__ = ("target", "wrt_kind", "wrt_ref", "name")

    def __init__(self, target: str, wrt_kind: str, wrt_ref: str):
        self.target = target
        self.wrt_kind = wrt_kind  # "feed" | "param"
        self.wrt_ref = wrt_ref
        self.name = f"{wrt_ref}@GRAD"

    def __repr__(self):
        return f"GradMarker(d({self.target})/d({self.wrt_ref}))"


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """ref paddle.static.gradients — symbolic grads of targets w.r.t. inputs.
    Returns one GradMarker per (target, input); fetch them via Executor.run
    on an inference program."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    out = []
    for t in targets:
        if not isinstance(t, Variable):
            raise TypeError(f"gradients target must be a static Variable, got {type(t)}")
        for x in inputs:
            if isinstance(x, Variable):
                out.append(GradMarker(t.var_name, "feed", x.var_name))
            elif isinstance(x, Parameter):
                out.append(GradMarker(t.var_name, "param",
                                      getattr(x, "name", "") or ""))
            else:
                raise TypeError(f"gradients input must be Variable|Parameter, got {type(x)}")
    return out


# --------------------------------------------------------------------------- #
# Executor
# --------------------------------------------------------------------------- #


class Scope:
    """Name -> value store (ref paddle/fluid/framework/scope.h). Holds param
    values and optimizer state between Executor.run calls."""

    def __init__(self):
        self.store: Dict[str, Any] = {}
        # per-program optimizer state: prog_id -> {"state","step","pnames"}
        self.opt_state: Dict[int, Dict[str, Any]] = {}

    def find_var(self, name):
        return self.store.get(name)


_GLOBAL_SCOPE = Scope()


def global_scope() -> Scope:
    return _GLOBAL_SCOPE


class scope_guard:
    def __init__(self, scope: Scope):
        self.scope = scope
        self._saved = None

    def __enter__(self):
        global _GLOBAL_SCOPE
        self._saved, _GLOBAL_SCOPE = _GLOBAL_SCOPE, self.scope

    def __exit__(self, *exc):
        global _GLOBAL_SCOPE
        _GLOBAL_SCOPE = self._saved
        return False


def _prune_ops(program: Program, fetch_names: Sequence[str]) -> List[Operator]:
    """Backward slice: keep only ops that (transitively) produce the fetches —
    the analogue of Program pruning in Executor._prune (ref fluid/executor.py).
    Makes clone(for_test=True) inference runs independent of label feeds."""
    needed = set(fetch_names)
    kept: List[Operator] = []
    for op in reversed(program.ops):
        if any(n in needed for n in op.out_names):
            kept.append(op)
            for kind, ref in op.in_refs:
                if kind == "var":
                    needed.add(ref)
    kept.reverse()
    return kept


def exec_ops(ops: List[Operator], env: Dict[str, Any],
             param_vals: Dict[str, Any], program: "Program",
             feed_keys: Optional[set] = None) -> None:
    """Execute a contiguous op segment against a mutable env — the shared
    inner loop of whole-program replay (_replay) and per-TaskNode segment
    execution (distributed.fleet_executor.FleetExecutor.from_program).
    ``feed_keys``: the caller's original feed names, for error messages
    (env accumulates intermediates, which would mislead)."""
    if feed_keys is None:
        feed_keys = set(env)
    for op in ops:
        ins = []
        for kind, ref in op.in_refs:
            if kind == "var":
                if ref not in env:
                    v = program.vars.get(ref)
                    if v is not None and v.is_feed:
                        raise KeyError(
                            f"feed Variable {ref!r} was not fed (feed keys: "
                            f"{sorted(feed_keys)}); pass it in "
                            "Executor.run(feed=...)")
                    env[ref] = v._value
                ins.append(env[ref])
            elif kind == "param":
                ins.append(param_vals[ref])
            else:
                ins.append(ref)
        out = op.fn(*ins, **op.kwargs)
        outs = list(out) if op.multi else [out]
        for name, o in zip(op.out_names, outs):
            env[name] = o


def _replay(program: Program, param_vals: Dict[str, Any],
            feed_vals: Dict[str, Any], fetch_names: Sequence[str],
            ops: Optional[List[Operator]] = None):
    """Execute the recorded ops as a pure function."""
    env: Dict[str, Any] = dict(feed_vals)
    exec_ops(program.ops if ops is None else ops, env, param_vals, program,
             feed_keys=set(feed_vals))
    return [env[n] for n in fetch_names]


class Executor:
    """ref fluid/executor.py:921 Executor — replay + jit with a plan cache
    (the analogue of _ExecutorCache at executor.py:750)."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[Any, Callable] = {}

    def close(self):
        self._cache.clear()

    def run(self, program: Optional[Program] = None, feed=None, fetch_list=None,
            scope: Optional[Scope] = None, return_numpy: bool = True, **kwargs):
        program = program or default_main_program()
        scope = scope or global_scope()
        feed = feed or {}

        if isinstance(program, _LoadedInferenceModel):
            # load_inference_model returns this in the program slot — keep the
            # reference idiom exe.run(program, feed, fetch_list) working
            return program.run_feed(feed, fetch_list, return_numpy)
        if isinstance(program, CompiledProgram):
            program = program.program

        # startup program: (re)materialize initial parameter values into scope
        # (a main program that merely fetches feed vars has feeds/fetches and
        # must NOT take this branch)
        if not program.ops and not program.loss_name and not program.feeds \
                and not fetch_list:
            main = default_main_program()
            reinit = {}
            for name, p in list(main.params.items()) + list(program.params.items()):
                reinit[name] = p.value
            scope.store.update(reinit)
            # drop optimizer state only for programs whose params were re-init'd
            for pid in [pid for pid, ent in scope.opt_state.items()
                        if ent["pnames"] & set(reinit)]:
                del scope.opt_state[pid]
            return []

        for name, p in program.params.items():
            if name not in scope.store:
                scope.store[name] = p.value

        fetch_list = fetch_list or []
        fetch_names: List[str] = []
        grad_markers: List[GradMarker] = []
        for f in fetch_list:
            if isinstance(f, GradMarker):
                grad_markers.append(f)
            elif isinstance(f, Variable):
                fetch_names.append(f.var_name)
            elif isinstance(f, str):
                fetch_names.append(f)
            else:
                raise TypeError(f"fetch_list entries must be Variable|str, got {type(f)}")

        feed_vals = {k: jnp.asarray(v.value if isinstance(v, Tensor) else v)
                     for k, v in feed.items()}
        param_vals = {k: scope.store[k] for k in program.params}
        trainable = {k for k, p in program.params.items()
                     if getattr(p, "trainable", True)}

        opt = program.optimizer
        if opt is not None and program.loss_name:
            if grad_markers:
                raise NotImplementedError(
                    "static.gradients fetches are supported on inference "
                    "programs (clone(for_test=True)); a train program already "
                    "applies its own backward")
            train_vals = {k: v for k, v in param_vals.items() if k in trainable}
            frozen_vals = {k: v for k, v in param_vals.items() if k not in trainable}
            pid = program._uid
            if pid not in scope.opt_state:
                scope.opt_state[pid] = {
                    "state": opt.init_state(train_vals), "step": 0,
                    "pnames": set(train_vals)}
            ent = scope.opt_state[pid]
            key = (pid, program._version, "train", tuple(fetch_names),
                   tuple((k, v.shape, str(v.dtype)) for k, v in sorted(feed_vals.items())))
            if key not in self._cache:
                loss_name = program.loss_name
                pruned = _prune_ops(program, [loss_name] + list(fetch_names))
                regs = {k: p.regularizer for k, p in program.params.items()
                        if k in trainable
                        and getattr(p, "regularizer", None) is not None}

                def train_step(params, frozen, feeds, state, lr, step):
                    def loss_fn(ps):
                        outs = _replay(program, {**ps, **frozen}, feeds,
                                       [loss_name] + list(fetch_names), pruned)
                        return outs[0], outs[1:]

                    (loss, fetches), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(params)
                    new_params, new_state = opt.pure_update(
                        params, grads, state, lr, step, regularizers=regs)
                    return fetches, new_params, new_state

                self._cache[key] = jax.jit(train_step)
            lr = jnp.asarray(opt.get_lr(), dtype=jnp.float32)
            ent["step"] += 1
            fetches, new_params, new_state = self._cache[key](
                train_vals, frozen_vals, feed_vals, ent["state"], lr,
                jnp.asarray(ent["step"], dtype=jnp.int32))
            scope.store.update(new_params)
            ent["state"] = new_state
        else:
            marker_keys = tuple((m.target, m.wrt_kind, m.wrt_ref)
                                for m in grad_markers)
            key = (program._uid, program._version, "infer", tuple(fetch_names),
                   marker_keys,
                   tuple((k, v.shape, str(v.dtype)) for k, v in sorted(feed_vals.items())))
            if key not in self._cache:
                pruned = _prune_ops(
                    program,
                    list(fetch_names) + [m.target for m in grad_markers])

                def infer_step(params, feeds):
                    outs = _replay(program, params, feeds, fetch_names, pruned)
                    grads = []
                    for m in grad_markers:
                        if m.wrt_kind == "feed":
                            gfn = jax.grad(lambda fv, _m=m: jnp.sum(_replay(
                                program, params, {**feeds, _m.wrt_ref: fv},
                                [_m.target], pruned)[0]))
                            grads.append(gfn(feeds[m.wrt_ref]))
                        else:
                            gfn = jax.grad(lambda pv, _m=m: jnp.sum(_replay(
                                program, {**params, _m.wrt_ref: pv}, feeds,
                                [_m.target], pruned)[0]))
                            grads.append(gfn(params[m.wrt_ref]))
                    return outs, grads

                self._cache[key] = jax.jit(infer_step)
            fetches, grads = self._cache[key](param_vals, feed_vals)
            fetches = list(fetches) + list(grads)

        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]


class CompiledProgram:
    """ref compiler.CompiledProgram — everything is compiled here; identity."""

    def __init__(self, program, build_strategy=None):
        self.program = program

    def __getattr__(self, item):
        return getattr(self.program, item)


class ParallelExecutor:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "ParallelExecutor is superseded: multi-device execution comes from "
            "paddle_tpu.parallel.ParallelEngine (GSPMD) — see SURVEY.md §3.3")


def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor=None,
                         program: Optional[Program] = None, **kwargs):
    """ref paddle.static.save_inference_model — serializes the replay function
    as StableHLO (jax.export) + param values; loadable standalone."""
    import os
    import pickle

    from jax import export as jexport

    program = program or (feed_vars[0].program if isinstance(feed_vars[0], Variable)
                          else default_main_program())
    scope = global_scope()
    feed_names = [v.var_name for v in feed_vars]
    fetch_names = [v.var_name for v in fetch_vars]
    param_vals = {k: scope.store.get(k, p.value) for k, p in program.params.items()}

    pruned = _prune_ops(program, fetch_names)

    def fn(params, *feeds):
        return _replay(program, params, dict(zip(feed_names, feeds)), fetch_names,
                       pruned)

    # dynamic (-1/None) feed dims export as jax.export symbolic dimensions —
    # batch-polymorphic StableHLO, same policy as jit.save
    scope_sym = jexport.SymbolicScope()
    in_avals = []
    n_sym = 0
    for v in feed_vars:
        dims = list(getattr(v, "sym_shape", v._value.shape))
        if any(d == -1 for d in dims):
            spec = []
            for d in dims:
                if d == -1:
                    spec.append(f"b{n_sym}")
                    n_sym += 1
                else:
                    spec.append(str(d))
            shape = jexport.symbolic_shape(", ".join(spec), scope=scope_sym)
        else:
            shape = tuple(dims)
        in_avals.append(jax.ShapeDtypeStruct(shape, v.dtype))
    exported = jexport.export(jax.jit(fn))(
        jax.tree_util.tree_map(lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype),
                               param_vals), *in_avals)
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump({"feeds": feed_names, "fetches": fetch_names,
                     "stablehlo": exported.serialize()}, f)
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump(jax.tree_util.tree_map(np.asarray, param_vals), f)


class _LoadedInferenceModel:
    def __init__(self, meta, params):
        from jax import export as jexport

        self.feed_names = meta["feeds"]
        self.fetch_names = meta["fetches"]
        self._exported = jexport.deserialize(meta["stablehlo"])
        self._params = params

    def run(self, feeds: Dict[str, Any]):
        raw = [jnp.asarray(feeds[n]) for n in self.feed_names]
        return [np.asarray(o) for o in self._exported.call(self._params, *raw)]

    def run_feed(self, feed, fetch_list, return_numpy: bool = True):
        missing = [n for n in self.feed_names if n not in feed]
        if missing:
            raise KeyError(f"missing feeds {missing} (expects {self.feed_names})")
        outs = self.run({k: (v.value if isinstance(v, Tensor) else v)
                         for k, v in feed.items()})
        if fetch_list:
            by_name = dict(zip(self.fetch_names, outs))
            sel = []
            for f in fetch_list:
                name = f if isinstance(f, str) else getattr(f, "var_name", None)
                if name not in by_name:
                    raise KeyError(
                        f"fetch {name!r} not among exported fetches {self.fetch_names}")
                sel.append(by_name[name])
            outs = sel
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    import pickle

    with open(path_prefix + ".pdmodel", "rb") as f:
        meta = pickle.load(f)
    with open(path_prefix + ".pdiparams", "rb") as f:
        params = pickle.load(f)
    m = _LoadedInferenceModel(meta, params)
    # reference returns (program, feed_target_names, fetch_targets)
    return m, m.feed_names, m.fetch_names
