"""paddle.static.nn — static-graph layer builders (ref python/paddle/static/nn/).

Each builder instantiates the corresponding dynamic ``paddle_tpu.nn`` layer
(parameters eagerly initialized, the analogue of LayerHelper.create_parameter
+ startup-program init ops) and calls it on the symbolic Variable, which
records its ops into the current Program via the central dispatch hook."""
from __future__ import annotations

from typing import Optional


def _activation(x, act: Optional[str]):
    if act is None:
        return x
    import paddle_tpu.nn.functional as F

    return getattr(F, act)(x)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn

    in_shape = x.sym_shape if hasattr(x, "sym_shape") else list(x.shape)
    tail = list(in_shape[num_flatten_dims:])
    if any(d in (-1, None) for d in tail):
        raise ValueError(
            f"static.nn.fc: dims after num_flatten_dims={num_flatten_dims} "
            f"must be static, got {in_shape} (ref fluid layers.fc requires "
            "a known flattened input width)")
    flat_dim = int(np.prod(tail))
    if len(in_shape) > num_flatten_dims + 1:
        x = paddle.reshape(x, [-1] * num_flatten_dims + [flat_dim]
                           if num_flatten_dims == 1 else
                           list(in_shape[:num_flatten_dims]) + [flat_dim])
    layer = nn.Linear(flat_dim, size, weight_attr=weight_attr, bias_attr=bias_attr)
    return _activation(layer(x), activation)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None,
           data_format="NCHW"):
    from paddle_tpu import nn

    in_ch = input.sym_shape[1] if data_format == "NCHW" else input.sym_shape[-1]
    layer = nn.Conv2D(abs(in_ch), num_filters, filter_size, stride=stride,
                      padding=padding, dilation=dilation, groups=groups,
                      weight_attr=param_attr, bias_attr=bias_attr,
                      data_format=data_format)
    return _activation(layer(input), act)


def embedding(input, size, is_sparse=False, padding_idx=None, param_attr=None,
              dtype="float32"):
    from paddle_tpu import nn

    layer = nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                         weight_attr=param_attr)
    return layer(input)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW", name=None,
               **kwargs):
    """Static BN. ``is_test=False`` (training) normalizes by batch statistics,
    matching the reference's training-graph behavior; ``is_test=True`` uses the
    layer's running stats.  Limitation vs the reference: running statistics are
    not updated by the recorded graph (exported inference programs should be
    built with ``is_test=True`` after loading trained stats)."""
    from paddle_tpu import nn

    ch = input.sym_shape[1] if data_layout == "NCHW" else input.sym_shape[-1]
    layer = nn.BatchNorm2D(abs(ch), momentum=momentum, epsilon=epsilon,
                           weight_attr=param_attr, bias_attr=bias_attr,
                           data_format=data_layout)
    if is_test:
        layer.eval()
    return _activation(layer(input), act)


def cond(pred, true_fn=None, false_fn=None, name=None):
    from ..jit import cond as _cond

    return _cond(pred, true_fn, false_fn)


def while_loop(cond_fn, body, loop_vars, is_test=False, name=None):
    from ..jit import while_loop as _wl

    return _wl(cond_fn, body, loop_vars)

# sequence op family (dense + lengths representation; ref
# fluid/layers/sequence_lod.py)
from .sequence import (sequence_concat, sequence_conv,  # noqa: F401
                       sequence_enumerate, sequence_expand,
                       sequence_expand_as, sequence_first_step,
                       sequence_last_step, sequence_mask, sequence_pad,
                       sequence_pool, sequence_reshape, sequence_reverse,
                       sequence_scatter, sequence_slice, sequence_softmax,
                       sequence_unpad)
