"""Sequence op family (ref python/paddle/fluid/layers/sequence_lod.py —
sequence_conv:49 ... sequence_reverse:1432, 16 LoD-based ops backed by
paddle/fluid/operators/sequence_ops/).

TPU-native redesign: the reference represents ragged batches as LoD tensors
(flat values + offset table). XLA wants static shapes, so the equivalent
representation here is DENSE-PADDED ``[B, T, ...]`` values + an int
``lengths [B]`` vector (exactly what ``sequence_pad`` produces in the
reference). Every op below is the dense+lengths formulation of its LoD
ancestor; ops whose OUTPUT is ragged (``sequence_unpad``) return packed
values eagerly (dynamic output shape — same restriction the reference's
LoD→dense boundary has).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, to_array
from ...framework.dispatch import apply_op

__all__ = ["sequence_conv", "sequence_softmax", "sequence_pool",
           "sequence_concat", "sequence_first_step", "sequence_last_step",
           "sequence_slice", "sequence_expand", "sequence_expand_as",
           "sequence_pad", "sequence_unpad", "sequence_reshape",
           "sequence_scatter", "sequence_enumerate", "sequence_mask",
           "sequence_reverse"]


def _tmask(lengths, T, dtype=jnp.bool_):
    """[B, T] validity mask from lengths."""
    return (jnp.arange(T)[None, :] < lengths[:, None]).astype(dtype)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """Ref sequence_lod.py:1369 — lengths → mask."""
    from ...nn.functional import sequence_mask as _sm

    return _sm(x, maxlen=maxlen, dtype=dtype, name=name)


def sequence_softmax(input, lengths, name=None):
    """Masked softmax over the time dim (ref :189 — softmax within each
    sequence; padded steps get probability 0)."""

    def f(x, ln):
        m = _tmask(ln, x.shape[1])
        shape = (x.shape[0], x.shape[1]) + (1,) * (x.ndim - 2)
        mm = m.reshape(shape)
        z = jnp.where(mm, x, -jnp.inf)
        p = jax.nn.softmax(z, axis=1)
        return jnp.where(mm, p, 0.0)

    return apply_op(f, input, lengths)


def sequence_pool(input, lengths, pool_type="average", pad_value=0.0,
                  name=None):
    """Ref :276 — pool each sequence over time: sum / average / sqrt
    (sum/sqrt(len)) / max / min / first / last. Empty sequences yield
    pad_value."""
    pool_type = pool_type.lower()

    def f(x, ln):
        T = x.shape[1]
        m = _tmask(ln, T).reshape((x.shape[0], T) + (1,) * (x.ndim - 2))
        lnf = jnp.maximum(ln, 1).reshape((-1,) + (1,) * (x.ndim - 2))
        xm = jnp.where(m, x, 0.0)
        if pool_type == "sum":
            out = xm.sum(axis=1)
        elif pool_type == "average":
            out = xm.sum(axis=1) / lnf
        elif pool_type == "sqrt":
            out = xm.sum(axis=1) / jnp.sqrt(lnf.astype(x.dtype))
        elif pool_type == "max":
            out = jnp.where(m, x, -jnp.inf).max(axis=1)
        elif pool_type == "min":
            out = jnp.where(m, x, jnp.inf).min(axis=1)
        elif pool_type == "first":
            out = x[:, 0]
        elif pool_type == "last":
            idx = jnp.maximum(ln - 1, 0)
            out = jnp.take_along_axis(
                x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
            ).squeeze(1)
        else:
            raise ValueError(f"unknown pool_type {pool_type}")
        empty = (ln == 0).reshape((-1,) + (1,) * (x.ndim - 2))
        return jnp.where(empty, jnp.asarray(pad_value, x.dtype), out)

    return apply_op(f, input, lengths)


def sequence_first_step(input, lengths):
    """Ref :462."""
    return sequence_pool(input, lengths, "first")


def sequence_last_step(input, lengths):
    """Ref :520."""
    return sequence_pool(input, lengths, "last")


def sequence_conv(input, lengths, filter_param, context_size=3,
                  context_start=None, bias=None, name=None):
    """Ref :49 — context-window projection: for each timestep, concatenate
    the ``context_size`` neighboring steps (zero-padded at sequence borders
    AND beyond each sequence's length) and project with
    ``filter_param [context_size * D, num_filters]``."""
    if context_start is None:
        context_start = -(context_size // 2)

    def f(x, ln, w, *b):
        B, T, D = x.shape
        m = _tmask(ln, T, x.dtype)[..., None]
        xm = x * m
        cols = []
        for k in range(context_size):
            off = context_start + k
            shifted = jnp.roll(xm, -off, axis=1)
            if off > 0:  # looking forward: zero the wrapped tail
                valid = jnp.arange(T) < (T - off)
            elif off < 0:
                valid = jnp.arange(T) >= (-off)
            else:
                valid = jnp.ones((T,), bool)
            cols.append(shifted * valid[None, :, None].astype(x.dtype))
        ctx = jnp.concatenate(cols, axis=-1)  # [B, T, ctx*D]
        out = ctx @ w
        if b:
            out = out + b[0]
        return out * m

    args = (input, lengths, filter_param) + ((bias,) if bias is not None else ())
    return apply_op(f, *args)


def sequence_concat(input, lengths_list, name=None):
    """Ref :394 — concatenate sequences element-wise: output row b is
    seq0[b] ++ seq1[b] ++ ... Returns (padded values, lengths)."""

    n = len(input)

    def f(*args):
        xs, lens = args[:n], args[n:]
        total = sum(lens[1:], lens[0])  # [B]
        Tout = sum(x.shape[1] for x in xs)
        B = xs[0].shape[0]
        out = jnp.zeros((B, Tout) + xs[0].shape[2:], xs[0].dtype)
        pos = jnp.zeros((B,), jnp.int32)
        for x, ln in zip(xs, lens):
            T = x.shape[1]
            t_idx = jnp.arange(T)[None, :] + pos[:, None]  # [B, T]
            m = _tmask(ln, T)
            safe = jnp.where(m, t_idx, Tout)  # parked writes → dropped
            out = out.at[jnp.arange(B)[:, None], safe].set(
                jnp.where(m.reshape((B, T) + (1,) * (x.ndim - 2)), x, 0),
                mode="drop")
            pos = pos + ln.astype(jnp.int32)
        return out, total

    return apply_op(f, *input, *lengths_list)


def sequence_slice(input, lengths, offset, length, name=None):
    """Ref :579 — per-sequence slice [offset, offset+length); returns
    (padded values, new lengths = clip(len-off, 0, length))."""

    def f(x, ln, off, lgt):
        B, T = x.shape[0], x.shape[1]
        t = jnp.arange(T)[None, :]
        src = t + off[:, None]
        valid = ((t < lgt[:, None]) & (src >= 0) & (src < ln[:, None]) &
                 (src < T))
        src = jnp.clip(src, 0, T - 1)
        out = jnp.take_along_axis(
            x, src.reshape((B, T) + (1,) * (x.ndim - 2)), axis=1)
        return jnp.where(valid.reshape((B, T) + (1,) * (x.ndim - 2)), out, 0)

    new_len = apply_op(
        lambda ln, off, lgt: jnp.clip(ln - off, 0, lgt), lengths, offset, length)
    return apply_op(f, input, lengths, offset, length), new_len


def sequence_expand(x, lengths, ref_lengths, maxlen=None, name=None):
    """Ref :673 (ref_level=-1 dense analogue): repeat each row b of ``x``
    ``ref_lengths[b]`` times along a new time axis — returns padded
    [B, maxlen or max(ref_lengths), ...]. ``lengths`` (x's own lengths) is
    accepted for API shape but dense rows are whole by construction."""
    # maxlen=0 is a real (zero-width) request; only None means "derive".
    # Deriving concretizes ref_lengths — pass maxlen explicitly under
    # static-graph build / jit.
    T = int(maxlen) if maxlen is not None else \
        int(np.asarray(to_array(ref_lengths)).max())

    def f(v, rln):
        rep = jnp.repeat(v[:, None], T, axis=1)
        m = _tmask(rln, T).reshape((v.shape[0], T) + (1,) * (v.ndim - 1))
        return jnp.where(m, rep, 0)

    return apply_op(f, x, ref_lengths)


def sequence_expand_as(x, y, y_lengths, name=None):
    """Ref :812 — expand x rows to y's padded time dim, masked by y's
    lengths."""

    def f(v, yv, yln):
        T = yv.shape[1]
        rep = jnp.repeat(v[:, None], T, axis=1)
        m = _tmask(yln, T).reshape((v.shape[0], T) + (1,) * (v.ndim - 1))
        return jnp.where(m, rep, 0)

    return apply_op(f, x, y, y_lengths)


def sequence_pad(x, pad_value, lengths, maxlen=None, name=None):
    """Ref :932 — packed values [sum(L), ...] + lengths → padded
    [B, maxlen, ...]. Eager (the packed input has data-dependent shape)."""
    v = np.asarray(to_array(x))
    ln = np.asarray(to_array(lengths)).astype(np.int64)
    pv = float(to_array(pad_value)) if isinstance(pad_value, Tensor) else pad_value
    B = len(ln)
    T = int(maxlen) if maxlen is not None else int(ln.max())
    out = np.full((B, T) + v.shape[1:], pv, v.dtype)
    pos = 0
    for b in range(B):
        n = min(int(ln[b]), T)
        out[b, :n] = v[pos:pos + int(ln[b])][:n]
        pos += int(ln[b])
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(ln))


def sequence_unpad(x, length, name=None):
    """Ref :1053 — padded [B, T, ...] + lengths → packed [sum(L), ...].
    Eager (dynamic output shape)."""
    v = np.asarray(to_array(x))
    ln = np.asarray(to_array(length)).astype(np.int64)
    return Tensor(jnp.asarray(
        np.concatenate([v[b, :int(ln[b])] for b in range(len(ln))], axis=0)))


def sequence_reshape(input, lengths, new_dim, name=None):
    """Ref :1134 — refold the feature dim: [B, T, D] → [B, T*D//new_dim,
    new_dim], lengths scale by D/new_dim (must divide evenly per row)."""

    def f(x, ln):
        B, T, D = x.shape
        assert (T * D) % new_dim == 0
        return x.reshape(B, T * D // new_dim, new_dim)

    D = int(input.shape[-1])
    ln_raw = to_array(lengths)
    if not isinstance(ln_raw, jax.core.Tracer):
        bad = np.nonzero((np.asarray(ln_raw) * D) % new_dim)[0]
        assert bad.size == 0, \
            f"rows {bad.tolist()}: length*{D} not divisible by {new_dim}"
    new_len = apply_op(lambda ln: (ln * D) // new_dim, lengths)
    return apply_op(f, input, lengths), new_len


def sequence_scatter(input, index, updates, lengths, name=None):
    """Ref :1203 — per-sequence scatter-add: for each batch row b,
    input[b, index[b, j]] += updates[b, j] for j < lengths[b]."""

    def f(x, idx, upd, ln):
        B, T = idx.shape[0], idx.shape[1]
        m = _tmask(ln, T)
        safe = jnp.where(m, idx, x.shape[1])  # parked → dropped
        return x.at[jnp.arange(B)[:, None], safe].add(
            jnp.where(m.reshape(m.shape + (1,) * (upd.ndim - 2)), upd, 0),
            mode="drop")

    return apply_op(f, input, index, updates, lengths)


def sequence_enumerate(input, win_size, pad_value=0, lengths=None,
                       name=None):
    """Ref :1299 — sliding windows over the time dim: [B, T] int ids →
    [B, T, win_size] (windows starting at each step, padded with pad_value
    past each sequence's end; ``lengths=None`` treats all rows as full)."""

    def f(x, *ln):
        T = x.shape[1]
        t = jnp.arange(T)[:, None] + jnp.arange(win_size)[None, :]
        end = ln[0][:, None, None] if ln else T
        valid = t[None] < end  # window elements past the row's length pad
        win = x[:, jnp.clip(t, 0, T - 1)]
        return jnp.where(valid, win, pad_value)

    args = (input,) + ((lengths,) if lengths is not None else ())
    return apply_op(f, *args)


def sequence_reverse(x, lengths, name=None):
    """Ref :1432 — reverse the VALID prefix of each sequence, keep padding
    in place."""

    def f(v, ln):
        B, T = v.shape[0], v.shape[1]
        t = jnp.arange(T)[None, :]
        src = ln[:, None] - 1 - t
        valid = t < ln[:, None]
        src = jnp.where(valid, src, t)
        return jnp.take_along_axis(
            v, src.reshape((B, T) + (1,) * (v.ndim - 2)), axis=1)

    return apply_op(f, x, lengths)
