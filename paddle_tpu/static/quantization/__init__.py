"""paddle.static.quantization — static-graph quantization entry points
(ref python/paddle/static/quantization/: QuantizationTransformPass,
quant_int8 post-training flows).  Our static Programs replay through jit,
so quantization happens at the layer level: these re-export the dygraph
QAT/PTQ machinery, which works identically on recorded programs."""
from ...quantization import (PTQ, QAT, QATv2, QuantConfig,  # noqa: F401
                             FakeQuanterWithAbsMax,
                             FakeQuanterWithAbsMaxObserver, QuantedConv2D,
                             QuantedLinear, dequantize, fake_quant,
                             quantize_absmax)


def quant_post_static(executor=None, model_dir=None, quantize_model_path=None,
                      sample_generator=None, batch_size=16, batch_nums=None,
                      algo="abs_max", **kwargs):
    """Minimal post-training static quantization driver: load an inference
    model, calibrate abs-max scales over sample batches, store scales next to
    the model (ref static/quantization/post_training_quantization.py)."""
    raise NotImplementedError(
        "paddle_tpu serves quantized inference through PTQ(model).quantize(); "
        "StableHLO export of quantized programs lands with the inference "
        "engine (see paddle_tpu/inference)")
