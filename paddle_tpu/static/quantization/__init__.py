"""paddle.static.quantization — static-graph quantization entry points
(ref python/paddle/static/quantization/: QuantizationTransformPass,
post_training_quantization.py quant_post_static).  Our static Programs
replay through jit, so quantization happens at the layer level: the dygraph
QAT/PTQ machinery works identically on recorded programs."""
import os

import numpy as np

from ...quantization import (PTQ, QAT, QATv2, QuantConfig,  # noqa: F401
                             FakeQuanterWithAbsMax,
                             FakeQuanterWithAbsMaxObserver, QuantedConv2D,
                             QuantedLinear, dequantize, fake_quant,
                             quantize_absmax)


def channelwise_quant_int8(arr):
    """Per-OUTPUT-channel abs-max int8 quantization (ref ChannelWiseAbsMax):
    Linear weights are [in, out] (channel = last axis); conv weights are
    OIHW (channel = axis 0). Returns (int8 q, fp32 per-channel scale,
    broadcast shape for dequant)."""
    if arr.ndim == 2:
        axes, bshape = (0,), (1, arr.shape[1])
    else:
        axes = tuple(range(1, arr.ndim))
        bshape = (arr.shape[0],) + (1,) * (arr.ndim - 1)
    scale = np.maximum(np.abs(arr).max(axis=axes), 1e-8) / 127.0
    q = np.clip(np.round(arr / scale.reshape(bshape)), -128, 127
                ).astype(np.int8)
    return q, scale.astype(np.float32), bshape


# names that are almost never int8-safe: embedding/lookup tables degrade
# accuracy well beyond the reference contract (its quant_post_static
# restricts quantization to a quantizable_op_type list — conv/mul/matmul
# weights; ref static/quantization/post_training_quantization.py)
DEFAULT_SKIP_PATTERNS = ("embed", "wte", "wpe", "pos_emb", "lookup_table",
                         "rotary")


def select_quantizable(state, quantizable=None, skip_patterns=None,
                       param_names=None):
    """Which entries of ``state`` (name -> array) get int8-quantized.

    - ``quantizable``: explicit override — iterable of names or a
      ``name -> bool`` predicate (mirrors the reference's
      quantizable_op_type allow-list).
    - default: >=2D floating PARAMETERS (``param_names`` excludes
      registered buffers when the caller has a live Layer) whose name does
      not match ``skip_patterns`` (default: embedding-family names).
    """
    import jax.numpy as jnp

    if quantizable is not None:
        if callable(quantizable):
            return {n for n in state if quantizable(n)}
        return set(quantizable) & set(state)
    pats = tuple(p.lower() for p in
                 (DEFAULT_SKIP_PATTERNS if skip_patterns is None
                  else skip_patterns))
    out = set()
    for name, arr in state.items():
        if arr.ndim < 2 or not jnp.issubdtype(arr.dtype, jnp.floating):
            continue
        if param_names is not None and name not in param_names:
            continue  # registered buffer, not a weight
        if any(p in name.lower() for p in pats):
            continue
        out.add(name)
    return out


def quant_post_static(executor=None, model_dir=None, quantize_model_path=None,
                      sample_generator=None, model=None, model_filename=None,
                      params_filename=None, batch_size=16, batch_nums=8,
                      algo="abs_max", weight_bits=8, quantizable=None,
                      skip_patterns=None, **kwargs):
    """Post-training quantization driver (ref
    static/quantization/post_training_quantization.py quant_post_static).

    Two entry forms, matching what each artifact allows:

    - ``model=`` a live Layer (+ optional ``sample_generator``): full PTQ —
      calibrate per-layer activation abs-max scales over ``batch_nums``
      sample batches, per-channel abs-max quantize the quantizable >=2D
      weights (see scope below), and
      write the quantized program to ``quantize_model_path`` (int8 weights +
      fp32 scales + activation ranges).

    - ``model_dir=`` a ``jit.save`` artifact prefix: weight-only int8 —
      the serialized StableHLO cannot be re-traced for activation quant, so
      the weights are per-channel abs-max quantized and saved alongside the
      copied program manifest (the reference's weight-only
      ``quant_post_only_weight`` flow).

    Output format at ``quantize_model_path``:
      ``<path>.pdiparams``   — {name: int8 array} for quantized weights,
                               original arrays for the rest
      ``<path>.scales``      — {name: fp32 per-channel scale} +
                               {"act/<layer>": abs-max} activation ranges
      plus the ``.pdmodel``/``.stablehlo``/``.pdexport`` manifest files
      copied from the source when loading from ``model_dir``.
    Use :func:`load_quantized_state` to get a dequantized float state_dict.

    Quantization scope: by default only >=2D floating *parameters* (never
    registered buffers) whose names don't look like embeddings
    (``DEFAULT_SKIP_PATTERNS``) are quantized — the reference restricts to a
    quantizable_op_type list (conv/mul/matmul weights) for the same reason.
    Pass ``quantizable=`` (name list or predicate) to override, or
    ``skip_patterns=`` to adjust the name filter.
    """
    import pickle

    from ...framework.io_state import load as _load
    from ...framework.io_state import save as _save

    assert quantize_model_path, "quantize_model_path is required"
    act_ranges = {}
    if model is not None:
        state = {k: np.asarray(v.value) for k, v in model.state_dict().items()}
        if sample_generator is not None:
            ptq = PTQ({"bits": weight_bits})
            act_ranges = ptq.observe(model, sample_generator,
                                     n_batches=batch_nums or 8)
    elif model_dir is not None:
        state = _load(model_dir + ".pdiparams")
        state = {k: np.asarray(v.value if hasattr(v, "value") else v)
                 for k, v in state.items()}
    else:
        raise ValueError("pass either model= (live Layer) or model_dir= "
                         "(jit.save artifact prefix)")

    param_names = ({n for n, _ in model.named_parameters()}
                   if model is not None else None)
    to_quant = select_quantizable(state, quantizable=quantizable,
                                  skip_patterns=skip_patterns,
                                  param_names=param_names)
    qstate, scales = {}, {}
    for name, arr in state.items():
        if name in to_quant:
            qstate[name], scales[name], _ = channelwise_quant_int8(
                arr.astype(np.float32) if arr.dtype != np.float32 else arr)
        else:
            qstate[name] = arr
    for lname, r in (act_ranges or {}).items():
        scales[f"act/{lname}"] = np.float32(r)

    os.makedirs(os.path.dirname(quantize_model_path) or ".", exist_ok=True)
    _save(qstate, quantize_model_path + ".pdiparams")
    with open(quantize_model_path + ".scales", "wb") as f:
        pickle.dump(scales, f)
    if model_dir is not None:
        import shutil

        for ext in (".pdmodel", ".stablehlo", ".pdexport"):
            src = model_dir + ext
            if os.path.exists(src):
                shutil.copy(src, quantize_model_path + ext)
    return quantize_model_path


def load_quantized_state(path):
    """Load a quant_post_static artifact back to a float32 state dict
    (int8 weight * per-channel scale); activation ranges under 'act/'."""
    import pickle

    from ...framework.io_state import load as _load

    state = _load(path + ".pdiparams")
    with open(path + ".scales", "rb") as f:
        scales = pickle.load(f)
    out = {}
    for name, v in state.items():
        arr = np.asarray(v.value if hasattr(v, "value") else v)
        if name in scales and arr.dtype == np.int8:
            sc = scales[name]
            bshape = ((1, -1) if arr.ndim == 2
                      else (-1,) + (1,) * (arr.ndim - 1))
            out[name] = arr.astype(np.float32) * sc.reshape(bshape)
        else:
            out[name] = arr
    acts = {k[4:]: float(v) for k, v in scales.items() if k.startswith("act/")}
    return out, acts
