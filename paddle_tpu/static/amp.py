"""paddle.static.amp (ref python/paddle/static/amp/decorator.py decorate →
OptimizerWithMixedPrecision, fp16_lists.py AutoMixedPrecisionLists,
fp16_utils.py cast_model_to_fp16 program rewriting; bf16/ variants).

TPU-native: the program rewrite is the auto_parallel_bf16/fp16 pass (cast
matmul-class op inputs; fp32 accumulate via preferred_element_type), applied
at minimize() time. Loss scaling: bf16 needs none (TPU-default policy, same
exponent range as fp32); fp16 wraps the optimizer with grad unscale +
nonfinite-skip + dynamic scale bookkeeping — the GradScaler state machine
living inside the jitted update (ref amp_nn.py update_loss_scaling op).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = ["decorate", "AutoMixedPrecisionLists", "CustomOpLists",
           "fp16_guard", "bf16_guard"]


class AutoMixedPrecisionLists:
    """ref fp16_lists.py:AutoMixedPrecisionLists — white (low precision),
    black (fp32), gray (follow inputs)."""

    def __init__(self, custom_white_list: Optional[Sequence[str]] = None,
                 custom_black_list: Optional[Sequence[str]] = None,
                 custom_black_varnames: Optional[Sequence[str]] = None):
        self.white_list = set(custom_white_list or ())
        self.black_list = set(custom_black_list or ())
        self.black_varnames = set(custom_black_varnames or ())


CustomOpLists = AutoMixedPrecisionLists


class fp16_guard:
    """ref fp16_utils.fp16_guard — region marker; the pass-based rewrite is
    list-driven so the guard is a no-op context manager kept for parity."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


bf16_guard = fp16_guard


class _LossScaleOptimizer:
    """fp16 path: unscale-free dynamic loss-scale bookkeeping around a pure
    optimizer — skip the step when grads are nonfinite, halve the scale;
    grow after incr_every_n consecutive finite steps (the update_loss_scaling
    state machine, ref static/amp/decorator.py + amp_nn.py). Grads are
    produced with fp32 accumulation so the scale only gates step-skipping."""

    def __init__(self, inner, init_loss_scaling=2.0 ** 15,
                 incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
                 incr_ratio=2.0, decr_ratio=0.5):
        self.inner = inner
        self.init_scale = float(init_loss_scaling)
        self.incr_every_n = int(incr_every_n_steps)
        self.decr_every_n = int(decr_every_n_nan_or_inf)
        self.incr_ratio = float(incr_ratio)
        self.decr_ratio = float(decr_ratio)

    def init_state(self, params):
        return {
            "inner": self.inner.init_state(params),
            "scale": jnp.asarray(self.init_scale, jnp.float32),
            "good": jnp.zeros((), jnp.int32),
            "bad": jnp.zeros((), jnp.int32),
        }

    def get_lr(self):
        return self.inner.get_lr()

    def pure_update(self, params, grads, state, lr, step, pnames=None,
                    regularizers=None):
        finite = jnp.asarray(True)
        for g in grads.values():
            finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))

        def do_step(operand):
            params_, inner_state = operand
            new_params, new_inner = self.inner.pure_update(
                params_, grads, inner_state, lr, step,
                regularizers=regularizers)
            return new_params, new_inner

        def skip_step(operand):
            return operand

        new_params, new_inner = jax.lax.cond(
            finite, do_step, skip_step, (params, state["inner"]))

        good = jnp.where(finite, state["good"] + 1, 0)
        bad = jnp.where(finite, 0, state["bad"] + 1)
        scale = state["scale"]
        scale = jnp.where(good >= self.incr_every_n, scale * self.incr_ratio,
                          scale)
        good = jnp.where(good >= self.incr_every_n, 0, good)
        scale = jnp.where(bad >= self.decr_every_n, scale * self.decr_ratio,
                          scale)
        bad = jnp.where(bad >= self.decr_every_n, 0, bad)
        return new_params, {"inner": new_inner, "scale": scale,
                            "good": good, "bad": bad}

    def __getattr__(self, item):
        return getattr(self.inner, item)


class OptimizerWithMixedPrecision:
    """ref decorator.py:OptimizerWithMixedPrecision — minimize() rewrites the
    program to low precision and (fp16) wraps the optimizer with the loss
    scaler."""

    def __init__(self, optimizer, amp_lists, level, dtype,
                 init_loss_scaling, use_dynamic_loss_scaling,
                 incr_every_n_steps, decr_every_n_nan_or_inf,
                 incr_ratio, decr_ratio):
        self._inner = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._level = level
        self._dtype = dtype
        self._scaling = dict(
            init_loss_scaling=init_loss_scaling,
            incr_every_n_steps=incr_every_n_steps,
            decr_every_n_nan_or_inf=decr_every_n_nan_or_inf,
            incr_ratio=incr_ratio, decr_ratio=decr_ratio,
        ) if (dtype == "float16" and use_dynamic_loss_scaling) else None

    def get_loss_scaling(self):
        return self._scaling["init_loss_scaling"] if self._scaling else 1.0

    def amp_init(self, place=None, scope=None, test_program=None,
                 use_fp16_test=False):
        """ref decorator.py amp_init — master weights already live as fp32
        params; nothing to materialize."""
        return None

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..distributed.passes import new_pass

        result = self._inner.minimize(loss, startup_program, parameters,
                                      no_grad_set)
        prog = loss.program
        pass_name = ("auto_parallel_fp16" if self._dtype == "float16"
                     else "auto_parallel_bf16")
        new_pass(pass_name, {
            "custom_white_list": self._amp_lists.white_list or None,
        }).apply([prog], [startup_program])
        if self._scaling is not None and prog.optimizer is not None:
            prog.optimizer = _LossScaleOptimizer(prog.optimizer,
                                                 **self._scaling)
        return result

    def __getattr__(self, item):
        return getattr(self._inner, item)


def decorate(optimizer, amp_lists=None, init_loss_scaling=2.0 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8, use_dynamic_loss_scaling=True,
             use_pure_fp16=False, use_fp16_guard=None, use_bf16=True,
             level="O1", dtype=None):
    """ref static/amp/decorator.py decorate(). dtype defaults to bfloat16
    (TPU policy); pass dtype='float16' (or use_bf16=False) for fp16 + dynamic
    loss scaling."""
    dtype = dtype or ("bfloat16" if use_bf16 else "float16")
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, level, dtype, init_loss_scaling,
        use_dynamic_loss_scaling, incr_every_n_steps,
        decr_every_n_nan_or_inf, incr_ratio, decr_ratio)
