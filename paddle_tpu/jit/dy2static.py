"""dy2static: AST rewriting of Python control flow into traceable ops.

Ref: python/paddle/jit/dy2static/ — the reference rewrites a function's AST
(~20 *_transformer.py; IfElseTransformer, LoopTransformer) so `if`/`while`
over Tensors become conditional_block/while ops in the ProgramDesc.

TPU-native version: the same AST rewrite, but the target ops are
`lax.cond` / `lax.while_loop`, and dispatch happens at RUNTIME —
`convert_ifelse` first tries `bool(pred)`; concrete (eager) predicates keep
exact Python semantics, and only tracer predicates (inside `to_static`'s
jax.jit trace) take the lax path. Locals are threaded through the branches
as a dict pytree (name analysis picks up loads/stores).

Supported subset (documented, mirrors the reference's own restrictions):
- `if`/`elif`/`else` and `while` whose bodies don't `return`/`break`/
  `continue`; such statements are left untouched (they still work whenever
  the predicate is concrete).
- names assigned under a traced branch/loop must already exist before it
  (lax.cond/while_loop need both paths to produce the same structure).
- functions whose source is available and which have no free closure
  variables; otherwise the original function is used unchanged.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Any, Callable, Dict, Sequence, Tuple


class _Undef:
    """Sentinel for names not bound at the capture point."""

    __slots__ = ()

    def __repr__(self):
        return "<undef>"


UNDEF = _Undef()


def pack(local_map: Dict[str, Any], names: Sequence[str]) -> Dict[str, Any]:
    """Capture the subset of ``locals()`` a rewritten block threads through."""
    return {n: local_map[n] for n in names if n in local_map}


def _is_traced(x) -> bool:
    import jax.core

    from ..framework.core import Tensor

    if isinstance(x, Tensor):
        x = x.value
    return isinstance(x, jax.core.Tracer)


def _raw_bool(x):
    from ..framework.core import Tensor

    return x.value if isinstance(x, Tensor) else x


def _partition(vars_dict: Dict[str, Any], promote: Sequence[str]):
    """Split locals into lax-traceable operands and static closure values.

    Returns (dyn, static, wrappers): ``dyn`` maps name → raw jax value;
    ``wrappers`` remembers which names held framework Tensors so branch
    bodies see the type they were written against. Plain Python numbers are
    promoted to arrays only for names in ``promote`` (the block's stores) —
    untouched statics keep exact Python semantics."""
    import jax.numpy as jnp
    import numpy as _np

    from ..framework.core import Tensor

    dyn, static, wrappers = {}, {}, {}
    for k, v in vars_dict.items():
        raw = v.value if isinstance(v, Tensor) else v
        if _is_traced(raw) or hasattr(raw, "dtype") and hasattr(raw, "shape"):
            dyn[k] = raw
            wrappers[k] = isinstance(v, Tensor)
        elif k in promote and isinstance(v, (bool, int, float, _np.number)):
            dyn[k] = jnp.asarray(v)
            wrappers[k] = False
        else:
            static[k] = v
    return dyn, static, wrappers


def _env(dyn, static, wrappers):
    from ..framework.core import Tensor

    out = dict(static)
    for k, v in dyn.items():
        out[k] = Tensor(v) if wrappers.get(k) else v
    return out


def _dyn_outs(result: Dict[str, Any], keys):
    """Extract the lax-carried names from a branch's pack() result as raw
    arrays, coercing numbers so both branches agree."""
    import jax.numpy as jnp

    from ..framework.core import Tensor

    out = {}
    for k in keys:
        v = result.get(k, UNDEF)
        if isinstance(v, _Undef):
            raise TypeError(
                f"dy2static: variable {k!r} must be bound on every path of a "
                "Tensor-predicate block (ref dy2static IfElseTransformer)")
        v = v.value if isinstance(v, Tensor) else v
        out[k] = jnp.asarray(v)
    return out


def convert_ifelse(pred, true_fn: Callable, false_fn: Callable,
                   vars_dict: Dict[str, Any],
                   store_names: Sequence[str] = (),
                   stores_true: Sequence[str] = None,
                   stores_false: Sequence[str] = None) -> Dict[str, Any]:
    """Runtime dispatch for a rewritten ``if`` (ref convert_operators.py
    convert_ifelse): concrete pred → plain Python call; traced pred →
    lax.cond carrying the array-typed locals, statics via closure.

    When per-branch store sets are given, only names bound on BOTH paths —
    either by both branches, or by one branch with a pre-existing binding —
    are carried through lax.cond; a name bound on a single path with no
    prior value is dead after the block (loading it would be undefined
    anyway) and is dropped instead of raising."""
    if not _is_traced(pred):
        return true_fn(dict(vars_dict)) if bool(_raw_bool(pred)) else \
            false_fn(dict(vars_dict))
    import jax

    if stores_true is not None and stores_false is not None:
        both = set(stores_true) & set(stores_false)
        store_names = [n for n in store_names
                       if n in both or not isinstance(
                           vars_dict.get(n, UNDEF), _Undef)]
    dyn, static, wrappers = _partition(vars_dict, store_names)
    carried = list(store_names)
    default_wrap = any(wrappers.values())  # new names follow the block's style

    def t_out(d):
        return _dyn_outs(true_fn(_env(d, static, wrappers)), carried)

    def f_out(d):
        return _dyn_outs(false_fn(_env(d, static, wrappers)), carried)

    res = jax.lax.cond(_raw_bool(pred) != 0, t_out, f_out, dyn)
    from ..framework.core import Tensor

    out = dict(vars_dict)
    for k in carried:
        out[k] = Tensor(res[k]) if wrappers.get(k, default_wrap) else res[k]
    return out


def convert_while_loop(cond_fn: Callable, body_fn: Callable,
                       vars_dict: Dict[str, Any],
                       store_names: Sequence[str] = ()) -> Dict[str, Any]:
    """Runtime dispatch for a rewritten ``while``: concrete condition →
    Python loop; traced condition → lax.while_loop with the array-typed
    locals as carry (numeric stores promoted to arrays)."""
    first = cond_fn(dict(vars_dict))
    if not _is_traced(first):
        vars_dict = dict(vars_dict)
        while bool(_raw_bool(cond_fn(dict(vars_dict)))):
            vars_dict = body_fn(dict(vars_dict))
        return vars_dict
    import jax

    dyn, static, wrappers = _partition(vars_dict, store_names)
    missing = [k for k in store_names if k not in dyn]
    if missing:
        raise TypeError(
            f"dy2static: variables {missing!r} assigned in a Tensor-condition "
            "`while` must be bound to array/number values before the loop "
            "(lax.while_loop fixed-structure restriction)")
    carry_keys = sorted(dyn)

    def c(d):
        return _raw_bool(cond_fn(_env(d, static, wrappers))) != 0

    def b(d):
        res = _dyn_outs(body_fn(_env(d, static, wrappers)), carry_keys)
        # unchanged carries keep their dtype; changed ones must match
        return {k: res[k].astype(d[k].dtype) if hasattr(d[k], "dtype") and
                res[k].dtype != d[k].dtype else res[k] for k in carry_keys}

    res = jax.lax.while_loop(c, b, dyn)
    from ..framework.core import Tensor

    out = dict(vars_dict)
    for k in carry_keys:
        out[k] = Tensor(res[k]) if wrappers.get(k, False) else res[k]
    return out


def convert_cast(pytype, x):
    """``int(x)`` / ``float(x)`` / ``bool(x)`` over tensors (ref
    cast_transformer.py): concrete values keep exact Python semantics;
    tracers become dtype casts (bool() on a tracer would raise)."""
    if pytype not in (int, float, bool):
        # the callee name resolved to something else at runtime — a
        # module-global shadowing the builtin (the AST rewrite only sees
        # function-local shadows): honor the user's object
        return pytype(x)
    raw = _raw_bool(x)
    if not _is_traced(raw):
        return pytype(raw) if hasattr(raw, "dtype") else pytype(x)
    import jax.numpy as jnp

    from ..framework.core import Tensor

    dt = {int: jnp.int64, float: jnp.float64, bool: jnp.bool_}[pytype]
    out = jnp.asarray(raw).astype(dt)
    return Tensor(out) if isinstance(x, Tensor) else out


def convert_assert(value, message=None):
    """``assert`` statements (ref assert_transformer.py → the static Assert
    op). Concrete predicates enforce eagerly with Python semantics; traced
    predicates are a documented no-op — a compiled XLA program has no
    host-side assert without the checkify transform, and numeric guards
    (nan/inf) already live at dispatch behind FLAGS_check_nan_inf."""
    raw = _raw_bool(value)
    if _is_traced(raw):
        return
    if not bool(raw):
        raise AssertionError("" if message is None else message)


def convert_call(fn):
    """Recursive callee conversion (ref call_transformer.py convert_call):
    plain user functions get the same cached AST rewrite, so Tensor control
    flow inside helpers converts too; everything else — builtins, classes,
    bound methods, callables without source, closures — passes through
    untouched via _convert_cached's own fallbacks."""
    if inspect.isfunction(fn) and \
            getattr(fn, "__wrapped_dy2static__", None) is None:
        try:
            return _convert_cached(fn)
        except TypeError:  # unhashable exotic callables
            return fn
    return fn


def convert_print(*args, sep=" ", end="\n", _pt_fn=None, **kw):
    """``print`` with traced arguments routes to jax.debug.print (prints
    from the compiled program with real values); concrete calls keep Python
    semantics including file=/flush=. ``_pt_fn`` carries the runtime-
    resolved ``print`` from the rewritten call site: when a module-global
    shadows the builtin, the user's callable runs instead."""
    import builtins

    if _pt_fn is not None and _pt_fn is not builtins.print:
        if sep != " ":
            kw["sep"] = sep
        if end != "\n":
            kw["end"] = end
        return _pt_fn(*args, **kw)
    raws = [_raw_bool(a) for a in args]
    if any(_is_traced(r) for r in raws):
        import jax

        fmt = sep.join("{a%d}" % i for i in range(len(raws)))
        jax.debug.print(fmt + ("" if end == "\n" else end),
                        **{f"a{i}": r for i, r in enumerate(raws)})
        return
    print(*args, sep=sep, end=end, **kw)


def convert_logical_and(lhs: Callable, rhs: Callable):
    l = lhs()
    if not _is_traced(l):
        return rhs() if bool(_raw_bool(l)) else l
    import jax.numpy as jnp

    return jnp.logical_and(_raw_bool(l) != 0, _raw_bool(rhs()) != 0)


def convert_logical_or(lhs: Callable, rhs: Callable):
    l = lhs()
    if not _is_traced(l):
        return l if bool(_raw_bool(l)) else rhs()
    import jax.numpy as jnp

    return jnp.logical_or(_raw_bool(l) != 0, _raw_bool(rhs()) != 0)


def convert_logical_not(x):
    if not _is_traced(x):
        return not bool(_raw_bool(x))
    import jax.numpy as jnp

    return jnp.logical_not(_raw_bool(x) != 0)


# --------------------------------------------------------------------------- #
# AST transformer
# --------------------------------------------------------------------------- #

_JST = "_pt_jst"          # module alias injected into the compiled namespace
_PREFIX = "__pt_"


def _walk_scoped(node):
    """ast.walk that does not descend into nested function/class scopes."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                stack.append(child)


def _has_escape(nodes) -> bool:
    """True if the block contains return/break/continue/yield at this level
    (not inside a nested function) — those keep Python semantics."""
    for n in nodes:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue  # a def at this level opens its own scope
        for sub in _walk_scoped(n):
            if isinstance(sub, (ast.Return, ast.Break, ast.Continue,
                                ast.Yield, ast.YieldFrom)):
                return True
    return False


_BUILTINS = set(dir(__import__("builtins")))
_COMP_NODES = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)


def _walk_no_comp(node):
    """Walk without descending into comprehension scopes (their targets are
    scope-local in Py3, not block stores)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(child, _COMP_NODES):
                stack.append(child)


def _name_sets(nodes) -> Tuple[set, set]:
    loads, stores = set(), set()
    for n in nodes:
        for sub in ast.walk(n):  # loads: anywhere, incl. comprehensions
            if isinstance(sub, ast.Name) and not sub.id.startswith(_PREFIX) \
                    and sub.id != _JST and not isinstance(sub.ctx, ast.Store):
                loads.add(sub.id)
        for sub in _walk_no_comp(n):  # stores: statement level only
            if isinstance(sub, ast.Name) and not sub.id.startswith(_PREFIX) \
                    and sub.id != _JST and isinstance(sub.ctx, ast.Store):
                stores.add(sub.id)
    # builtins are resolved from the enclosing scope, not threaded — unless
    # the user actually assigns to the name
    loads -= _BUILTINS - stores
    return loads, stores


def _stmt(src: str) -> list:
    return ast.parse(textwrap.dedent(src)).body


def _walk_loop_level(node):
    """Walk without descending into nested loops or function/class scopes —
    break/continue found here belong to the CURRENT loop."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(child, (ast.While, ast.For, ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef,
                                      ast.Lambda)):
                stack.append(child)


class _ForRangeTransformer(ast.NodeTransformer):
    """``for i in range(...)`` → counter ``while`` (ref loop_transformer.py
    for→while lowering). Only range() targets are desugared; other iterables
    keep Python semantics (concrete containers unroll at trace time — the
    JAX idiom). The loop variable is assigned from a private counter at the
    top of each iteration, so body reassignment of it cannot perturb the
    iteration and its after-loop value matches Python's."""

    def __init__(self, shadowed=frozenset()):
        self.n = 0
        # a local/param named `range` must not be treated as the builtin
        self.shadowed = frozenset(shadowed)

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef
    visit_Lambda = lambda self, node: node  # noqa: E731

    def visit_For(self, node):
        self.generic_visit(node)
        it = node.iter
        if (node.orelse or not isinstance(node.target, ast.Name)
                or not isinstance(it, ast.Call)
                or not isinstance(it.func, ast.Name) or it.func.id != "range"
                or "range" in self.shadowed or it.keywords
                or any(isinstance(a, ast.Starred) for a in it.args)):
            return node

        def _literal_step(a):
            # -1 parses as UnaryOp(USub, Constant), not Constant
            if isinstance(a, ast.Constant) and isinstance(a.value, int):
                return a.value
            if (isinstance(a, ast.UnaryOp) and isinstance(a.op, ast.USub)
                    and isinstance(a.operand, ast.Constant)
                    and isinstance(a.operand.value, int)):
                return -a.operand.value
            return None

        args = it.args
        if len(args) == 1:
            start, stop, step = ast.Constant(value=0), args[0], 1
        elif len(args) == 2:
            start, stop, step = args[0], args[1], 1
        elif len(args) == 3 and _literal_step(args[2]) not in (None, 0):
            # non-literal steps keep the Python loop: the comparison
            # direction must be known at rewrite time
            start, stop, step = args[0], args[1], _literal_step(args[2])
        else:
            return node
        i = self.n
        self.n += 1
        # NOT _PREFIX-prefixed: the counter must be a tracked store so the
        # while conversion carries it (same rule as __fold_ret_)
        ctr, stop_n = f"__for_i_{i}", f"__for_stop_{i}"
        init = _stmt(f"{ctr} = 0\n{stop_n} = 0\n{node.target.id} = {ctr}")
        init[0].value = start
        init[1].value = stop
        # pre-binding the target lets lax carry it (Python would leave it
        # unbound on an empty range — a documented divergence)
        cmp_op = "<" if step > 0 else ">"
        loop = _stmt(f"while {ctr} {cmp_op} {stop_n}:\n"
                     f"    {node.target.id} = {ctr}\n"
                     f"    {ctr} = {ctr} + ({step})\n"
                     f"    pass")[0]
        loop.body = loop.body[:-1] + node.body
        return init + [loop]


class _BreakContinueTransformer(ast.NodeTransformer):
    """``break``/``continue`` inside loops → guard flags (ref
    break_continue_transformer.py): the loop becomes escape-free, so the
    control-flow pass can lower it to lax.while_loop when the predicate is
    traced. Loops whose break/continue sit in unsupported positions (inside
    try/with at loop level) or that also contain return/yield stay Python.
    """

    def __init__(self):
        self.n = 0

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef
    visit_Lambda = lambda self, node: node  # noqa: E731

    @staticmethod
    def _has_bc(node) -> bool:
        return any(isinstance(n, (ast.Break, ast.Continue))
                   for n in _walk_loop_level(node))

    def visit_While(self, node):
        self.generic_visit(node)  # inner loops eliminate their own escapes
        if node.orelse or not any(self._has_bc(s) for s in node.body):
            return node
        if any(isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom))
               for s in node.body for n in _walk_loop_level(s)):
            return node  # return-in-loop keeps Python semantics
        i = self.n
        self.n += 1
        brk, cont = f"__brk_{i}", f"__cont_{i}"
        body = self._guard(node.body, brk, cont)
        if body is None:
            return node
        test = ast.BoolOp(op=ast.And(), values=[
            ast.UnaryOp(op=ast.Not(),
                        operand=ast.Name(id=brk, ctx=ast.Load())),
            node.test])
        # both flags pre-bound: lax.while_loop carries need a value before
        # the loop (cont is also reset at the top of every iteration)
        out = _stmt(f"{brk} = False\n{cont} = False")
        loop = ast.While(test=test, body=_stmt(f"{cont} = False") + body,
                         orelse=[])
        return out + [loop]

    def _guard(self, stmts, brk, cont):
        """Rewrite one statement list: break/continue become flag sets and
        everything after a flag-setting statement is wrapped in
        ``if not (brk or cont):``. Returns None when a break/continue sits
        somewhere this rewrite can't reach (inside try/with)."""
        out = []
        for idx, st in enumerate(stmts):
            if isinstance(st, ast.Break):
                return out + _stmt(f"{brk} = True")  # rest is unreachable
            if isinstance(st, ast.Continue):
                return out + _stmt(f"{cont} = True")
            if isinstance(st, ast.If) and self._has_bc(st):
                b = self._guard(st.body, brk, cont)
                o = self._guard(st.orelse, brk, cont)
                if b is None or o is None:
                    return None
                out.append(ast.If(test=st.test, body=b or _stmt("pass"),
                                  orelse=o))
                rest = self._guard(stmts[idx + 1:], brk, cont)
                if rest is None:
                    return None
                if rest:
                    g = _stmt(f"if not ({brk} or {cont}):\n    pass")[0]
                    g.body = rest
                    out.append(g)
                return out
            if self._has_bc(st):
                return None  # break inside try/with at loop level
            out.append(st)
        return out


class _CtrlFlowTransformer(ast.NodeTransformer):
    def __init__(self, shadowed=frozenset()):
        self.n = 0
        # names assigned anywhere in the function: a local `int = ...` or
        # `print = ...` must not be rewritten as the builtin
        self.shadowed = frozenset(shadowed)

    # don't descend into nested function/class definitions
    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef
    visit_Lambda = lambda self, node: node  # noqa: E731

    def visit_BoolOp(self, node):
        """`a and b` / `a or b` → convert_logical_and/or(lambda: a, lambda: b)
        — lazy lambdas preserve short-circuiting for concrete values; traced
        values route to jnp.logical_and/or instead of bool() (which raises)."""
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        out = node.values[0]
        for rhs in node.values[1:]:
            out = ast.Call(
                func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                                   attr=fn, ctx=ast.Load()),
                args=[ast.Lambda(args=ast.arguments(posonlyargs=[], args=[],
                                                    kwonlyargs=[], kw_defaults=[],
                                                    defaults=[]), body=out),
                      ast.Lambda(args=ast.arguments(posonlyargs=[], args=[],
                                                    kwonlyargs=[], kw_defaults=[],
                                                    defaults=[]), body=rhs)],
                keywords=[])
        return out

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if not isinstance(node.op, ast.Not):
            return node
        return ast.Call(
            func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                               attr="convert_logical_not", ctx=ast.Load()),
            args=[node.operand], keywords=[])

    def _jst(self, attr):
        return ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                             attr=attr, ctx=ast.Load())

    def visit_Call(self, node):
        """Three callee rewrites (ref cast_transformer.py,
        call_transformer.py): int/float/bool → convert_cast; print →
        convert_print; other plain-Name calls → convert_call(f)(...) so
        Tensor control flow inside user helpers converts recursively."""
        self.generic_visit(node)
        if not isinstance(node.func, ast.Name):
            return node  # method/attribute calls stay as-is (framework
            #             internals must not be re-compiled)
        name = node.func.id
        if name in self.shadowed or name.startswith(_PREFIX) or name == _JST:
            return node
        if name in ("int", "float", "bool") and len(node.args) == 1 \
                and not node.keywords:
            return ast.Call(func=self._jst("convert_cast"),
                            args=[ast.Name(id=name, ctx=ast.Load()),
                                  node.args[0]], keywords=[])
        if name == "print":
            # pass the runtime-resolved `print` so a module-global shadow
            # keeps the user's callable (function-local shadows are already
            # in self.shadowed)
            node.keywords.append(ast.keyword(
                arg="_pt_fn", value=ast.Name(id="print", ctx=ast.Load())))
            node.func = self._jst("convert_print")
            return node
        if name in _BUILTINS:
            return node
        node.func = ast.Call(func=self._jst("convert_call"),
                             args=[node.func], keywords=[])
        return node

    def visit_Assert(self, node):
        self.generic_visit(node)
        args = [node.test] + ([node.msg] if node.msg is not None else [])
        return ast.Expr(value=ast.Call(func=self._jst("convert_assert"),
                                       args=args, keywords=[]))

    def _make_branch_fn(self, name, body, tracked):
        # unpack with explicit global fallback: any assignment makes the name
        # function-local (so a bare conditional unpack would shadow imports /
        # module helpers with an unbound local); absent-everywhere names get
        # UNDEF and only fail if the body actually reads them before binding
        unpack = [f'{v} = {_PREFIX}vars["{v}"] if "{v}" in {_PREFIX}vars '
                  f'else globals().get("{v}", {_JST}.UNDEF)'
                  for v in sorted(tracked)]
        src = f"def {name}({_PREFIX}vars):\n" + "".join(
            f"    {u}\n" for u in unpack) + "    pass\n"
        fn = _stmt(src)[0]
        fn.body = fn.body[:-1] + body + _stmt(
            f"return {_JST}.pack(locals(), {sorted(tracked)!r})")
        return fn

    def visit_If(self, node):
        self.generic_visit(node)
        if _has_escape(node.body) or _has_escape(node.orelse):
            return node
        loads, stores = _name_sets(node.body + node.orelse)
        cond_loads, _ = _name_sets([node.test])
        tracked = sorted((loads | stores | cond_loads) - {"_", _JST})
        if not stores:
            return node
        _, stores_t = _name_sets(node.body)
        _, stores_f = _name_sets(node.orelse)
        i = self.n
        self.n += 1
        true_fn = self._make_branch_fn(f"{_PREFIX}true_{i}", node.body or
                                       _stmt("pass"), tracked)
        false_fn = self._make_branch_fn(f"{_PREFIX}false_{i}", node.orelse or
                                        _stmt("pass"), tracked)
        call = _stmt(
            f"{_PREFIX}out_{i} = {_JST}.convert_ifelse(PREDPLACEHOLDER, "
            f"{_PREFIX}true_{i}, {_PREFIX}false_{i}, "
            f"{_JST}.pack(locals(), {tracked!r}), {sorted(stores)!r}, "
            f"stores_true={sorted(stores_t)!r}, "
            f"stores_false={sorted(stores_f)!r})")[0]
        call.value.args[0] = node.test
        unpacks = []
        for v in sorted(stores):
            unpacks += _stmt(
                f'if "{v}" in {_PREFIX}out_{i} and not isinstance('
                f'{_PREFIX}out_{i}["{v}"], {_JST}._Undef):\n'
                f'    {v} = {_PREFIX}out_{i}["{v}"]')
        return [true_fn, false_fn, call] + unpacks

    def visit_While(self, node):
        self.generic_visit(node)
        if _has_escape(node.body) or node.orelse:
            return node
        loads, stores = _name_sets(node.body)
        cond_loads, _ = _name_sets([node.test])
        tracked = sorted((loads | stores | cond_loads) - {"_", _JST})
        if not stores:
            return node
        i = self.n
        self.n += 1
        cond_src = f"def {_PREFIX}cond_{i}({_PREFIX}vars):\n" + "".join(
            f'    {v} = {_PREFIX}vars["{v}"] if "{v}" in {_PREFIX}vars '
            f'else globals().get("{v}", {_JST}.UNDEF)\n'
            for v in tracked) + "    return COND\n"
        cond_fn = _stmt(cond_src)[0]
        cond_fn.body[-1] = ast.Return(value=node.test)
        body_fn = self._make_branch_fn(f"{_PREFIX}body_{i}", node.body, tracked)
        call = _stmt(
            f"{_PREFIX}out_{i} = {_JST}.convert_while_loop({_PREFIX}cond_{i}, "
            f"{_PREFIX}body_{i}, {_JST}.pack(locals(), {tracked!r}), "
            f"{sorted(stores)!r})")[0]
        unpacks = []
        for v in sorted(stores):
            unpacks += _stmt(
                f'if "{v}" in {_PREFIX}out_{i} and not isinstance('
                f'{_PREFIX}out_{i}["{v}"], {_JST}._Undef):\n'
                f'    {v} = {_PREFIX}out_{i}["{v}"]')
        return [cond_fn, body_fn, call] + unpacks


def _contains_return(node) -> bool:
    return any(isinstance(n, ast.Return) for n in _walk_scoped(node))


def _loop_holds_return(node) -> bool:
    for n in _walk_scoped(node):
        if isinstance(n, (ast.While, ast.For)) and _contains_return(n):
            return True
    return False


def _fold_tail_returns(stmts, counter):
    """Rewrite early returns inside ``if`` statements into a single trailing
    return (ref dy2static return_transformer.py SingleReturnTransformer,
    simplified):

        if c:              if c:
            <t>; return A      <t>; __pt_ret = A
        <rest>; return B   else:
                               <rest'>; __pt_ret = B
                           return __pt_ret

    The statements after the if ARE its implicit else-continuation. After
    folding, no Return remains inside any If, so the control-flow
    transformer can convert the if to lax.cond. Returns None when the shape
    is unsupported (returns inside loops, bare yields, ...) — callers keep
    the original body and Python semantics."""
    import copy

    out = []
    for idx, st in enumerate(stmts):
        if isinstance(st, ast.Return):
            # statements after a top-level return are dead — truncating here
            # also discards the continuation copies appended below
            out.append(st)
            return out
        if isinstance(st, ast.If) and _contains_return(st):
            if _loop_holds_return(st) or _has_escape([st]) and any(
                    isinstance(n, (ast.Break, ast.Continue, ast.Yield,
                                   ast.YieldFrom))
                    for n in _walk_scoped(st)):
                return None
            # the statements after the if are the continuation of EVERY path
            # that falls through — append them to BOTH branches (dead copies
            # after a return are truncated by the recursion)
            rest = stmts[idx + 1:]
            body = _fold_tail_returns(
                list(st.body) + copy.deepcopy(rest), counter)
            orelse = _fold_tail_returns(
                list(st.orelse or []) + copy.deepcopy(rest), counter)
            if body is None or orelse is None:
                return None
            # a branch that falls off the end implicitly returns None
            if not (body and isinstance(body[-1], ast.Return)):
                body = body + [ast.Return(value=ast.Constant(value=None))]
            if not (orelse and isinstance(orelse[-1], ast.Return)):
                orelse = orelse + [ast.Return(value=ast.Constant(value=None))]
            rv = f"__fold_ret_{counter[0]}"  # NOT _PREFIX: must be a store
            counter[0] += 1

            def land(branch):
                val = branch[-1].value
                assign = ast.Assign(
                    targets=[ast.Name(id=rv, ctx=ast.Store())],
                    value=val if val is not None else ast.Constant(value=None))
                return branch[:-1] + [assign]

            out.append(ast.If(test=st.test, body=land(body),
                              orelse=land(orelse)))
            out.append(ast.Return(value=ast.Name(id=rv, ctx=ast.Load())))
            return out
        out.append(st)
    return out


@functools.lru_cache(maxsize=256)
def _convert_cached(fn):
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn
    if fn.__closure__:
        return fn  # free variables wouldn't resolve in the recompiled scope
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []
    folded = _fold_tail_returns(fdef.body, [0])
    if folded is not None:
        fdef.body = folded
    # pre-passes feeding the while conversion: for-range → counter while,
    # then break/continue → guard flags (order matters: a desugared range
    # loop may itself contain break/continue)
    _, pre_stores = _name_sets(fdef.body)
    pre_args = {a.arg for a in (fdef.args.posonlyargs + fdef.args.args +
                                fdef.args.kwonlyargs)}
    for pre in (_ForRangeTransformer(shadowed=pre_stores | pre_args),
                _BreakContinueTransformer()):
        body = []
        for stmt in fdef.body:
            r = pre.visit(stmt)
            body.extend(r if isinstance(r, list) else [r])
        fdef.body = body
    before = ast.dump(fdef)
    # visit the body statements (visit_FunctionDef guards NESTED defs; the
    # top-level def itself must be descended into)
    _, fn_stores = _name_sets(fdef.body)
    arg_names = {a.arg for a in (fdef.args.posonlyargs + fdef.args.args +
                                 fdef.args.kwonlyargs)}
    for va in (fdef.args.vararg, fdef.args.kwarg):
        if va is not None:
            arg_names.add(va.arg)
    t = _CtrlFlowTransformer(shadowed=fn_stores | arg_names)
    new_body = []
    for stmt in fdef.body:
        r = t.visit(stmt)
        new_body.extend(r if isinstance(r, list) else [r])
    fdef.body = new_body
    ast.fix_missing_locations(tree)
    if ast.dump(fdef) == before:
        return fn  # nothing rewritten
    import paddle_tpu.jit.dy2static as _self

    ns = dict(fn.__globals__)
    ns[_JST] = _self
    try:
        code = compile(tree, filename=f"<dy2static {fn.__qualname__}>",
                       mode="exec")
        exec(code, ns)  # noqa: S102 — recompiling the user's own source
        out = ns[fdef.name]
        out.__wrapped_dy2static__ = fn
        return out
    except Exception:
        return fn


def convert_to_static(fn: Callable) -> Callable:
    """Rewrite ``fn``'s Python `if`/`while` into runtime-dispatched
    convert_ifelse/convert_while_loop calls (ref ProgramTranslator.get_func).
    Bound methods are rewritten on the underlying function and re-bound.
    Falls back to the original on any unsupported construct."""
    if inspect.ismethod(fn):
        conv = _convert_cached(fn.__func__)
        return conv.__get__(fn.__self__) if conv is not fn.__func__ else fn
    if not inspect.isfunction(fn):
        return fn
    return _convert_cached(fn)
