"""dy2static: AST rewriting of Python control flow into traceable ops.

Ref: python/paddle/jit/dy2static/ — the reference rewrites a function's AST
(~20 *_transformer.py; IfElseTransformer, LoopTransformer) so `if`/`while`
over Tensors become conditional_block/while ops in the ProgramDesc.

TPU-native version: the same AST rewrite, but the target ops are
`lax.cond` / `lax.while_loop`, and dispatch happens at RUNTIME —
`convert_ifelse` first tries `bool(pred)`; concrete (eager) predicates keep
exact Python semantics, and only tracer predicates (inside `to_static`'s
jax.jit trace) take the lax path. Locals are threaded through the branches
as a dict pytree (name analysis picks up loads/stores).

Supported subset (documented, mirrors the reference's own restrictions):
- `if`/`elif`/`else` and `while` whose bodies don't `return`/`break`/
  `continue`; such statements are left untouched (they still work whenever
  the predicate is concrete).
- names assigned under a traced branch/loop must already exist before it
  (lax.cond/while_loop need both paths to produce the same structure).
- functions whose source is available and which have no free closure
  variables; otherwise the original function is used unchanged.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
from typing import Any, Callable, Dict, Sequence, Tuple


class _Undef:
    """Sentinel for names not bound at the capture point."""

    __slots__ = ()

    def __repr__(self):
        return "<undef>"


UNDEF = _Undef()


def pack(local_map: Dict[str, Any], names: Sequence[str]) -> Dict[str, Any]:
    """Capture the subset of ``locals()`` a rewritten block threads through."""
    return {n: local_map[n] for n in names if n in local_map}


def _is_traced(x) -> bool:
    import jax.core

    from ..framework.core import Tensor

    if isinstance(x, Tensor):
        x = x.value
    return isinstance(x, jax.core.Tracer)


def _raw_bool(x):
    from ..framework.core import Tensor

    return x.value if isinstance(x, Tensor) else x


def _partition(vars_dict: Dict[str, Any], promote: Sequence[str]):
    """Split locals into lax-traceable operands and static closure values.

    Returns (dyn, static, wrappers): ``dyn`` maps name → raw jax value;
    ``wrappers`` remembers which names held framework Tensors so branch
    bodies see the type they were written against. Plain Python numbers are
    promoted to arrays only for names in ``promote`` (the block's stores) —
    untouched statics keep exact Python semantics."""
    import jax.numpy as jnp
    import numpy as _np

    from ..framework.core import Tensor

    dyn, static, wrappers = {}, {}, {}
    for k, v in vars_dict.items():
        raw = v.value if isinstance(v, Tensor) else v
        if _is_traced(raw) or hasattr(raw, "dtype") and hasattr(raw, "shape"):
            dyn[k] = raw
            wrappers[k] = isinstance(v, Tensor)
        elif k in promote and isinstance(v, (bool, int, float, _np.number)):
            dyn[k] = jnp.asarray(v)
            wrappers[k] = False
        else:
            static[k] = v
    return dyn, static, wrappers


def _env(dyn, static, wrappers):
    from ..framework.core import Tensor

    out = dict(static)
    for k, v in dyn.items():
        out[k] = Tensor(v) if wrappers.get(k) else v
    return out


def _dyn_outs(result: Dict[str, Any], keys):
    """Extract the lax-carried names from a branch's pack() result as raw
    arrays, coercing numbers so both branches agree."""
    import jax.numpy as jnp

    from ..framework.core import Tensor

    out = {}
    for k in keys:
        v = result.get(k, UNDEF)
        if isinstance(v, _Undef):
            raise TypeError(
                f"dy2static: variable {k!r} must be bound on every path of a "
                "Tensor-predicate block (ref dy2static IfElseTransformer)")
        v = v.value if isinstance(v, Tensor) else v
        out[k] = jnp.asarray(v)
    return out


def convert_ifelse(pred, true_fn: Callable, false_fn: Callable,
                   vars_dict: Dict[str, Any],
                   store_names: Sequence[str] = (),
                   stores_true: Sequence[str] = None,
                   stores_false: Sequence[str] = None) -> Dict[str, Any]:
    """Runtime dispatch for a rewritten ``if`` (ref convert_operators.py
    convert_ifelse): concrete pred → plain Python call; traced pred →
    lax.cond carrying the array-typed locals, statics via closure.

    When per-branch store sets are given, only names bound on BOTH paths —
    either by both branches, or by one branch with a pre-existing binding —
    are carried through lax.cond; a name bound on a single path with no
    prior value is dead after the block (loading it would be undefined
    anyway) and is dropped instead of raising."""
    if not _is_traced(pred):
        return true_fn(dict(vars_dict)) if bool(_raw_bool(pred)) else \
            false_fn(dict(vars_dict))
    import jax

    if stores_true is not None and stores_false is not None:
        both = set(stores_true) & set(stores_false)
        store_names = [n for n in store_names
                       if n in both or not isinstance(
                           vars_dict.get(n, UNDEF), _Undef)]
    dyn, static, wrappers = _partition(vars_dict, store_names)
    carried = list(store_names)
    default_wrap = any(wrappers.values())  # new names follow the block's style

    def t_out(d):
        return _dyn_outs(true_fn(_env(d, static, wrappers)), carried)

    def f_out(d):
        return _dyn_outs(false_fn(_env(d, static, wrappers)), carried)

    res = jax.lax.cond(_raw_bool(pred) != 0, t_out, f_out, dyn)
    from ..framework.core import Tensor

    out = dict(vars_dict)
    for k in carried:
        out[k] = Tensor(res[k]) if wrappers.get(k, default_wrap) else res[k]
    return out


def convert_while_loop(cond_fn: Callable, body_fn: Callable,
                       vars_dict: Dict[str, Any],
                       store_names: Sequence[str] = ()) -> Dict[str, Any]:
    """Runtime dispatch for a rewritten ``while``: concrete condition →
    Python loop; traced condition → lax.while_loop with the array-typed
    locals as carry (numeric stores promoted to arrays)."""
    first = cond_fn(dict(vars_dict))
    if not _is_traced(first):
        vars_dict = dict(vars_dict)
        while bool(_raw_bool(cond_fn(dict(vars_dict)))):
            vars_dict = body_fn(dict(vars_dict))
        return vars_dict
    import jax

    dyn, static, wrappers = _partition(vars_dict, store_names)
    missing = [k for k in store_names if k not in dyn]
    if missing:
        raise TypeError(
            f"dy2static: variables {missing!r} assigned in a Tensor-condition "
            "`while` must be bound to array/number values before the loop "
            "(lax.while_loop fixed-structure restriction)")
    carry_keys = sorted(dyn)

    def c(d):
        return _raw_bool(cond_fn(_env(d, static, wrappers))) != 0

    def b(d):
        res = _dyn_outs(body_fn(_env(d, static, wrappers)), carry_keys)
        # unchanged carries keep their dtype; changed ones must match
        return {k: res[k].astype(d[k].dtype) if hasattr(d[k], "dtype") and
                res[k].dtype != d[k].dtype else res[k] for k in carry_keys}

    res = jax.lax.while_loop(c, b, dyn)
    from ..framework.core import Tensor

    out = dict(vars_dict)
    for k in carry_keys:
        out[k] = Tensor(res[k]) if wrappers.get(k, False) else res[k]
    return out


def convert_logical_and(lhs: Callable, rhs: Callable):
    l = lhs()
    if not _is_traced(l):
        return rhs() if bool(_raw_bool(l)) else l
    import jax.numpy as jnp

    return jnp.logical_and(_raw_bool(l) != 0, _raw_bool(rhs()) != 0)


def convert_logical_or(lhs: Callable, rhs: Callable):
    l = lhs()
    if not _is_traced(l):
        return l if bool(_raw_bool(l)) else rhs()
    import jax.numpy as jnp

    return jnp.logical_or(_raw_bool(l) != 0, _raw_bool(rhs()) != 0)


def convert_logical_not(x):
    if not _is_traced(x):
        return not bool(_raw_bool(x))
    import jax.numpy as jnp

    return jnp.logical_not(_raw_bool(x) != 0)


# --------------------------------------------------------------------------- #
# AST transformer
# --------------------------------------------------------------------------- #

_JST = "_pt_jst"          # module alias injected into the compiled namespace
_PREFIX = "__pt_"


def _walk_scoped(node):
    """ast.walk that does not descend into nested function/class scopes."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef, ast.Lambda)):
                stack.append(child)


def _has_escape(nodes) -> bool:
    """True if the block contains return/break/continue/yield at this level
    (not inside a nested function) — those keep Python semantics."""
    for n in nodes:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue  # a def at this level opens its own scope
        for sub in _walk_scoped(n):
            if isinstance(sub, (ast.Return, ast.Break, ast.Continue,
                                ast.Yield, ast.YieldFrom)):
                return True
    return False


_BUILTINS = set(dir(__import__("builtins")))
_COMP_NODES = (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)


def _walk_no_comp(node):
    """Walk without descending into comprehension scopes (their targets are
    scope-local in Py3, not block stores)."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if not isinstance(child, _COMP_NODES):
                stack.append(child)


def _name_sets(nodes) -> Tuple[set, set]:
    loads, stores = set(), set()
    for n in nodes:
        for sub in ast.walk(n):  # loads: anywhere, incl. comprehensions
            if isinstance(sub, ast.Name) and not sub.id.startswith(_PREFIX) \
                    and sub.id != _JST and not isinstance(sub.ctx, ast.Store):
                loads.add(sub.id)
        for sub in _walk_no_comp(n):  # stores: statement level only
            if isinstance(sub, ast.Name) and not sub.id.startswith(_PREFIX) \
                    and sub.id != _JST and isinstance(sub.ctx, ast.Store):
                stores.add(sub.id)
    # builtins are resolved from the enclosing scope, not threaded — unless
    # the user actually assigns to the name
    loads -= _BUILTINS - stores
    return loads, stores


def _stmt(src: str) -> list:
    return ast.parse(textwrap.dedent(src)).body


class _CtrlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.n = 0

    # don't descend into nested function/class definitions
    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef
    visit_Lambda = lambda self, node: node  # noqa: E731

    def visit_BoolOp(self, node):
        """`a and b` / `a or b` → convert_logical_and/or(lambda: a, lambda: b)
        — lazy lambdas preserve short-circuiting for concrete values; traced
        values route to jnp.logical_and/or instead of bool() (which raises)."""
        self.generic_visit(node)
        fn = ("convert_logical_and" if isinstance(node.op, ast.And)
              else "convert_logical_or")
        out = node.values[0]
        for rhs in node.values[1:]:
            out = ast.Call(
                func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                                   attr=fn, ctx=ast.Load()),
                args=[ast.Lambda(args=ast.arguments(posonlyargs=[], args=[],
                                                    kwonlyargs=[], kw_defaults=[],
                                                    defaults=[]), body=out),
                      ast.Lambda(args=ast.arguments(posonlyargs=[], args=[],
                                                    kwonlyargs=[], kw_defaults=[],
                                                    defaults=[]), body=rhs)],
                keywords=[])
        return out

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if not isinstance(node.op, ast.Not):
            return node
        return ast.Call(
            func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                               attr="convert_logical_not", ctx=ast.Load()),
            args=[node.operand], keywords=[])

    def _make_branch_fn(self, name, body, tracked):
        # unpack with explicit global fallback: any assignment makes the name
        # function-local (so a bare conditional unpack would shadow imports /
        # module helpers with an unbound local); absent-everywhere names get
        # UNDEF and only fail if the body actually reads them before binding
        unpack = [f'{v} = {_PREFIX}vars["{v}"] if "{v}" in {_PREFIX}vars '
                  f'else globals().get("{v}", {_JST}.UNDEF)'
                  for v in sorted(tracked)]
        src = f"def {name}({_PREFIX}vars):\n" + "".join(
            f"    {u}\n" for u in unpack) + "    pass\n"
        fn = _stmt(src)[0]
        fn.body = fn.body[:-1] + body + _stmt(
            f"return {_JST}.pack(locals(), {sorted(tracked)!r})")
        return fn

    def visit_If(self, node):
        self.generic_visit(node)
        if _has_escape(node.body) or _has_escape(node.orelse):
            return node
        loads, stores = _name_sets(node.body + node.orelse)
        cond_loads, _ = _name_sets([node.test])
        tracked = sorted((loads | stores | cond_loads) - {"_", _JST})
        if not stores:
            return node
        _, stores_t = _name_sets(node.body)
        _, stores_f = _name_sets(node.orelse)
        i = self.n
        self.n += 1
        true_fn = self._make_branch_fn(f"{_PREFIX}true_{i}", node.body or
                                       _stmt("pass"), tracked)
        false_fn = self._make_branch_fn(f"{_PREFIX}false_{i}", node.orelse or
                                        _stmt("pass"), tracked)
        call = _stmt(
            f"{_PREFIX}out_{i} = {_JST}.convert_ifelse(PREDPLACEHOLDER, "
            f"{_PREFIX}true_{i}, {_PREFIX}false_{i}, "
            f"{_JST}.pack(locals(), {tracked!r}), {sorted(stores)!r}, "
            f"stores_true={sorted(stores_t)!r}, "
            f"stores_false={sorted(stores_f)!r})")[0]
        call.value.args[0] = node.test
        unpacks = []
        for v in sorted(stores):
            unpacks += _stmt(
                f'if "{v}" in {_PREFIX}out_{i} and not isinstance('
                f'{_PREFIX}out_{i}["{v}"], {_JST}._Undef):\n'
                f'    {v} = {_PREFIX}out_{i}["{v}"]')
        return [true_fn, false_fn, call] + unpacks

    def visit_While(self, node):
        self.generic_visit(node)
        if _has_escape(node.body) or node.orelse:
            return node
        loads, stores = _name_sets(node.body)
        cond_loads, _ = _name_sets([node.test])
        tracked = sorted((loads | stores | cond_loads) - {"_", _JST})
        if not stores:
            return node
        i = self.n
        self.n += 1
        cond_src = f"def {_PREFIX}cond_{i}({_PREFIX}vars):\n" + "".join(
            f'    {v} = {_PREFIX}vars["{v}"] if "{v}" in {_PREFIX}vars '
            f'else globals().get("{v}", {_JST}.UNDEF)\n'
            for v in tracked) + "    return COND\n"
        cond_fn = _stmt(cond_src)[0]
        cond_fn.body[-1] = ast.Return(value=node.test)
        body_fn = self._make_branch_fn(f"{_PREFIX}body_{i}", node.body, tracked)
        call = _stmt(
            f"{_PREFIX}out_{i} = {_JST}.convert_while_loop({_PREFIX}cond_{i}, "
            f"{_PREFIX}body_{i}, {_JST}.pack(locals(), {tracked!r}), "
            f"{sorted(stores)!r})")[0]
        unpacks = []
        for v in sorted(stores):
            unpacks += _stmt(
                f'if "{v}" in {_PREFIX}out_{i} and not isinstance('
                f'{_PREFIX}out_{i}["{v}"], {_JST}._Undef):\n'
                f'    {v} = {_PREFIX}out_{i}["{v}"]')
        return [cond_fn, body_fn, call] + unpacks


def _contains_return(node) -> bool:
    return any(isinstance(n, ast.Return) for n in _walk_scoped(node))


def _loop_holds_return(node) -> bool:
    for n in _walk_scoped(node):
        if isinstance(n, (ast.While, ast.For)) and _contains_return(n):
            return True
    return False


def _fold_tail_returns(stmts, counter):
    """Rewrite early returns inside ``if`` statements into a single trailing
    return (ref dy2static return_transformer.py SingleReturnTransformer,
    simplified):

        if c:              if c:
            <t>; return A      <t>; __pt_ret = A
        <rest>; return B   else:
                               <rest'>; __pt_ret = B
                           return __pt_ret

    The statements after the if ARE its implicit else-continuation. After
    folding, no Return remains inside any If, so the control-flow
    transformer can convert the if to lax.cond. Returns None when the shape
    is unsupported (returns inside loops, bare yields, ...) — callers keep
    the original body and Python semantics."""
    import copy

    out = []
    for idx, st in enumerate(stmts):
        if isinstance(st, ast.Return):
            # statements after a top-level return are dead — truncating here
            # also discards the continuation copies appended below
            out.append(st)
            return out
        if isinstance(st, ast.If) and _contains_return(st):
            if _loop_holds_return(st) or _has_escape([st]) and any(
                    isinstance(n, (ast.Break, ast.Continue, ast.Yield,
                                   ast.YieldFrom))
                    for n in _walk_scoped(st)):
                return None
            # the statements after the if are the continuation of EVERY path
            # that falls through — append them to BOTH branches (dead copies
            # after a return are truncated by the recursion)
            rest = stmts[idx + 1:]
            body = _fold_tail_returns(
                list(st.body) + copy.deepcopy(rest), counter)
            orelse = _fold_tail_returns(
                list(st.orelse or []) + copy.deepcopy(rest), counter)
            if body is None or orelse is None:
                return None
            # a branch that falls off the end implicitly returns None
            if not (body and isinstance(body[-1], ast.Return)):
                body = body + [ast.Return(value=ast.Constant(value=None))]
            if not (orelse and isinstance(orelse[-1], ast.Return)):
                orelse = orelse + [ast.Return(value=ast.Constant(value=None))]
            rv = f"__fold_ret_{counter[0]}"  # NOT _PREFIX: must be a store
            counter[0] += 1

            def land(branch):
                val = branch[-1].value
                assign = ast.Assign(
                    targets=[ast.Name(id=rv, ctx=ast.Store())],
                    value=val if val is not None else ast.Constant(value=None))
                return branch[:-1] + [assign]

            out.append(ast.If(test=st.test, body=land(body),
                              orelse=land(orelse)))
            out.append(ast.Return(value=ast.Name(id=rv, ctx=ast.Load())))
            return out
        out.append(st)
    return out


@functools.lru_cache(maxsize=256)
def _convert_cached(fn):
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return fn
    if fn.__closure__:
        return fn  # free variables wouldn't resolve in the recompiled scope
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    fdef.decorator_list = []
    folded = _fold_tail_returns(fdef.body, [0])
    if folded is not None:
        fdef.body = folded
    before = ast.dump(fdef)
    # visit the body statements (visit_FunctionDef guards NESTED defs; the
    # top-level def itself must be descended into)
    t = _CtrlFlowTransformer()
    new_body = []
    for stmt in fdef.body:
        r = t.visit(stmt)
        new_body.extend(r if isinstance(r, list) else [r])
    fdef.body = new_body
    ast.fix_missing_locations(tree)
    if ast.dump(fdef) == before:
        return fn  # nothing rewritten
    import paddle_tpu.jit.dy2static as _self

    ns = dict(fn.__globals__)
    ns[_JST] = _self
    try:
        code = compile(tree, filename=f"<dy2static {fn.__qualname__}>",
                       mode="exec")
        exec(code, ns)  # noqa: S102 — recompiling the user's own source
        out = ns[fdef.name]
        out.__wrapped_dy2static__ = fn
        return out
    except Exception:
        return fn


def convert_to_static(fn: Callable) -> Callable:
    """Rewrite ``fn``'s Python `if`/`while` into runtime-dispatched
    convert_ifelse/convert_while_loop calls (ref ProgramTranslator.get_func).
    Bound methods are rewritten on the underlying function and re-bound.
    Falls back to the original on any unsupported construct."""
    if inspect.ismethod(fn):
        conv = _convert_cached(fn.__func__)
        return conv.__get__(fn.__self__) if conv is not fn.__func__ else fn
    if not inspect.isfunction(fn):
        return fn
    return _convert_cached(fn)
