"""paddle.jit parity (ref: python/paddle/jit/api.py:222 to_static,
dy2static/program_translator.py).

TPU-native redesign: there is no AST transformation pipeline (the reference's
~20 *_transformer.py rewrite Python into ProgramDesc ops). Here ``to_static``
= trace the layer/function with jax.jit over a functional view of its
parameters.  The traced jaxpr plays the role of ProgramDesc; XLA plays the
role of the static executor (ref interpretercore.cc — no runtime equivalent
needed).
"""
from __future__ import annotations

import contextlib
import functools
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..framework.core import Parameter, Tensor, no_grad_ctx, to_array


class InputSpec:
    """Ref python/paddle/static/input.py InputSpec."""

    def __init__(self, shape=None, dtype="float32", name=None, stop_gradient=True):
        from ..framework.dtype import convert_dtype

        self.shape = list(shape) if shape is not None else None
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


# --------------------------------------------------------------------------- #
# functional view of a Layer: swap param values, run, restore.
# --------------------------------------------------------------------------- #


@contextlib.contextmanager
def _swapped_params(layer, named_values: Dict[str, Any],
                    mutated_out: Optional[Dict[str, Any]] = None):
    """Swap in values, run, restore. If ``mutated_out`` is given, any entry
    whose ``_value`` the call reassigned (BN running stats and similar eager
    side effects, ref nn/functional/norm.py batch_norm) is captured into it
    before restore — the functionalized form of that state update."""
    saved = {}
    params = dict(layer.named_parameters())
    buffers = dict(layer.named_buffers())
    store = {**params, **buffers}
    try:
        for name, val in named_values.items():
            t = store.get(name)
            if t is None:
                continue
            saved[name] = t._value
            t._value = val
        yield
        if mutated_out is not None:
            for name, val in named_values.items():
                t = store.get(name)
                if t is not None and t._value is not val:
                    mutated_out[name] = t._value
    finally:
        for name, val in saved.items():
            store[name]._value = val


def state_values(layer) -> Dict[str, jax.Array]:
    """Extract {name: raw array} for params + buffers."""
    out = {}
    for name, p in layer.named_parameters():
        out[name] = p.value
    for name, b in layer.named_buffers():
        out[name] = b.value
    return out


def param_values(layer) -> Dict[str, jax.Array]:
    return {name: p.value for name, p in layer.named_parameters() if p.trainable}


def functional_call(layer, named_values: Dict[str, Any], *args, call_fn=None,
                    mutated_state: Optional[Dict[str, Any]] = None, **kwargs):
    """Run ``layer(*args)`` with parameters/buffers temporarily replaced by
    ``named_values`` (possibly tracers). The tape is disabled: gradients on
    this path come from jax.grad over this function. ``call_fn`` overrides the
    callable (used by to_static to avoid re-entering a patched forward).
    ``mutated_state``: dict filled with buffer values the call reassigned
    (e.g. BN running stats) so jitted callers can thread them as outputs."""
    with _swapped_params(layer, named_values, mutated_out=mutated_state), \
            no_grad_ctx():
        out = (call_fn or layer)(*args, **kwargs)
    return out


def _unwrap(o):
    return jax.tree_util.tree_map(
        lambda x: x.value if isinstance(x, Tensor) else x, o,
        is_leaf=lambda x: isinstance(x, Tensor))


def _wrap(o):
    return jax.tree_util.tree_map(
        lambda x: Tensor(x) if isinstance(x, jax.Array) else x, o)


class StaticFunction:
    """Ref dy2static/program_translator.py:282 StaticFunction: a callable that
    runs the jit-compiled program while looking like the original method."""

    def __init__(self, fn: Callable, layer=None, input_spec=None, build_strategy=None,
                 backend=None):
        # AST-rewrite Python if/while over Tensors into lax.cond/while_loop
        # (ref dy2static *_transformer.py); no-op when nothing applies
        from .dy2static import convert_to_static

        self._fn = convert_to_static(fn)
        self._layer = layer
        self._input_spec = input_spec
        self._jitted = None
        self._donate = False
        functools.update_wrapper(self, self._fn)

    @property
    def forward_fn(self):
        return self._fn

    def _build(self):
        layer = self._layer

        if layer is not None:
            orig_forward = self._fn

            def pure(params, arg_vals, kw_vals):
                out = functional_call(layer, params, *_wrap(arg_vals),
                                      call_fn=orig_forward, **_wrap(kw_vals))
                return _unwrap(out)
        else:
            fn = self._fn

            def pure(params, arg_vals, kw_vals):
                with no_grad_ctx():
                    out = fn(*_wrap(arg_vals), **_wrap(kw_vals))
                return _unwrap(out)

        self._pure = pure
        self._jitted = jax.jit(pure)

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            # enable_to_static(False): run the original dygraph code
            return self._fn(*args, **kwargs)
        if self._jitted is None:
            self._build()
        params = state_values(self._layer) if self._layer is not None else {}
        arg_vals = _unwrap(args)
        kw_vals = _unwrap(kwargs)
        out = self._jitted(params, arg_vals, kw_vals)
        return _wrap(out)

    def concrete_program(self, *args, **kwargs):
        params = state_values(self._layer) if self._layer is not None else {}
        return jax.make_jaxpr(self._pure if self._jitted else self._build() or self._pure)(
            params, _unwrap(args), _unwrap(kwargs))

    @property
    def code(self):
        import inspect

        return inspect.getsource(self._fn)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """@paddle.jit.to_static parity (ref jit/api.py:222)."""

    def decorate(fn):
        from ..nn.layer_base import Layer

        if isinstance(fn, Layer):
            sf = StaticFunction(fn.forward, layer=fn, input_spec=input_spec)
            fn.forward = sf
            return fn
        return StaticFunction(fn, layer=None, input_spec=input_spec)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


# ---------------------------------------------------------------------------
# Structured control flow for compiled code — the replacement for the
# reference's dy2static AST transformers (ref dy2static/*_transformer.py):
# instead of rewriting Python if/while into conditional_block/while ops, user
# code calls these directly (lax.cond / lax.while_loop / lax.scan on Tensors).
# ---------------------------------------------------------------------------


def cond(pred, true_fn, false_fn, *operands):
    """paddle.static.nn.cond capability (traceable branch select)."""
    import jax

    pred_v = pred.value if isinstance(pred, Tensor) else pred
    ops = _unwrap(operands)
    out = jax.lax.cond(pred_v, lambda o: _unwrap(true_fn(*_wrap(o))),
                       lambda o: _unwrap(false_fn(*_wrap(o))), ops)
    return _wrap(out)


def while_loop(cond_fn, body_fn, loop_vars):
    """paddle.static.nn.while_loop capability."""
    import jax

    init = _unwrap(loop_vars)

    def c(vals):
        out = cond_fn(*_wrap(vals))
        return out.value if isinstance(out, Tensor) else out

    def b(vals):
        return _unwrap(body_fn(*_wrap(vals)))

    out = jax.lax.while_loop(c, b, init)
    return _wrap(out)


def scan(body_fn, init, xs, length=None):
    """lax.scan over Tensors: body_fn(carry, x) -> (carry, y)."""
    import jax

    def b(carry, x):
        c2, y = body_fn(_wrap(carry), _wrap(x))
        return _unwrap(c2), _unwrap(y)

    carry, ys = jax.lax.scan(b, _unwrap(init), _unwrap(xs), length=length)
    return _wrap(carry), _wrap(ys)


def ignore_module(modules):
    pass


def _trace_to_exported(layer, input_spec):
    """Trace layer.forward over input_spec into a jax.export Exported
    (StableHLO) + its param values; the jit.save export path."""
    from jax import export as jexport

    was_training = layer.training
    layer.eval()
    try:
        params = state_values(layer)

        def fn(params, *args):
            out = functional_call(layer, params, *[Tensor(a) for a in args])
            return jax.tree_util.tree_map(
                lambda t: t.value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda t: isinstance(t, Tensor))

        # None/-1 dims (the canonical dynamic-batch InputSpec) export as
        # jax.export symbolic dimensions — batch-polymorphic StableHLO
        scope = jexport.SymbolicScope()
        in_avals = []
        n_sym = 0
        for s in input_spec:
            if any(d is None or d == -1 for d in s.shape):
                dims = []
                for d in s.shape:
                    if d is None or d == -1:
                        dims.append(f"b{n_sym}")
                        n_sym += 1
                    else:
                        dims.append(str(d))
                shape = jexport.symbolic_shape(", ".join(dims), scope=scope)
            else:
                shape = tuple(s.shape)
            in_avals.append(jax.ShapeDtypeStruct(shape, s.dtype))
        exported = jexport.export(jax.jit(fn))(
            jax.tree_util.tree_map(lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype),
                                   params), *in_avals)
        return exported, params
    finally:
        if was_training:
            layer.train()


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save parity (ref jit/api.py jit.save → TranslatedLayer).

    Persists state_dict + an input-spec manifest; when ``input_spec`` is
    given, ALSO serializes the traced forward as StableHLO (jax.export) so
    ``jit.load`` returns a standalone runnable TranslatedLayer — the direct
    analogue of the reference's serialized ProgramDesc + params files.
    """
    import os
    import pickle

    from ..framework.io_state import save as _save

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    _save(layer.state_dict(), path + ".pdiparams")
    meta = {
        "class": type(layer).__name__,
        "input_spec": [
            {"shape": s.shape, "dtype": str(jnp.dtype(s.dtype)), "name": s.name}
            for s in (input_spec or [])
        ],
    }
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump(meta, f)
    if input_spec:
        import numpy as np

        exported, params = _trace_to_exported(layer, input_spec)
        with open(path + ".stablehlo", "wb") as f:
            f.write(exported.serialize())
        with open(path + ".pdexport", "wb") as f:
            pickle.dump(jax.tree_util.tree_map(np.asarray, params), f)


class TranslatedLayer:
    """Loaded inference layer (ref jit/translated_layer.py). Standalone
    runnable when the save included a StableHLO export; otherwise bind a
    model instance to supply the code."""

    def __init__(self, state_dict, meta, exported=None, params=None):
        self._state_dict = state_dict
        self._meta = meta
        self._layer = None
        self._exported = exported
        self._params = params

    def bind(self, layer):
        layer.set_state_dict(self._state_dict)
        self._layer = layer
        return layer

    def state_dict(self):
        return self._state_dict

    def eval(self):
        return self

    def __call__(self, *args, **kwargs):
        if self._layer is not None:
            return self._layer(*args, **kwargs)
        if self._exported is not None:
            if kwargs:
                raise TypeError(
                    "exported TranslatedLayer takes positional inputs only "
                    f"(got kwargs {sorted(kwargs)}); re-save with those folded into "
                    "input_spec, or bind() a model instance")
            raw = [a.value if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
            out = self._exported.call(self._params, *raw)
            return jax.tree_util.tree_map(Tensor, out)
        raise RuntimeError(
            "this artifact was saved without input_spec; call "
            "TranslatedLayer.bind(model) with a model instance first")


def load(path, **configs):
    import os
    import pickle

    from ..framework.io_state import load as _load

    sd = _load(path + ".pdiparams")
    with open(path + ".pdmodel", "rb") as f:
        meta = pickle.load(f)
    exported = params = None
    if os.path.exists(path + ".stablehlo"):
        from jax import export as jexport

        with open(path + ".stablehlo", "rb") as f:
            exported = jexport.deserialize(f.read())
        with open(path + ".pdexport", "rb") as f:
            params = pickle.load(f)
    return TranslatedLayer(sd, meta, exported, params)


# --- dy2static global switches (ref jit/api.py enable_to_static,
# jit/dy2static/logging_utils.py set_code_level/set_verbosity) ---
_to_static_enabled = True
_code_level = 0
_verbosity = 0


def enable_to_static(enable: bool = True):
    """Globally enable/disable @to_static conversion (ref jit/api.py:88):
    when off, to_static-wrapped callables run eagerly."""
    global _to_static_enabled
    _to_static_enabled = bool(enable)


def set_code_level(level: int = 100, also_to_stdout: bool = False):
    """Transformed-code dump level (ref dy2static logging_utils)."""
    global _code_level
    _code_level = int(level)


def set_verbosity(level: int = 0, also_to_stdout: bool = False):
    """Dy2static logging verbosity (ref dy2static logging_utils)."""
    global _verbosity
    _verbosity = int(level)
