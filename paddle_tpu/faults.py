"""Deterministic fault injection — the shared substrate for the serving
AND training stacks.

Everything here is host-only by contract (graftlint GL011): injector
hooks fire between compiled programs, never inside them, and the
disabled path is a single attribute check so production servers and
train loops pay nothing. Faults are *scripted*, not random-at-runtime: a
``FaultPlan`` names the hook site, the call ordinal at which to fire,
and how many consecutive calls to hit, so a chaos run replays
bit-identically from its seed — the property every token-identity and
bit-exact-resume assertion in the chaos tests leans on.

Serving hook sites (threaded through ``paddle_tpu/inference/``):

========== =================================================== ==========
site       fires inside                                        effect
========== =================================================== ==========
alloc      ``BlockAllocator.alloc``                            raises the same pool-exhausted ``RuntimeError`` as a genuinely dry pool
host_put   ``KVOffloadEngine.swap_out``                        host pool refuses the payload (swap-out returns ``None`` → stall path)
swap_corrupt ``KVOffloadEngine.swap_in``                       flips one bit in the parked payload before checksum verification
drafter    ``GenerationServer._spec_tick`` / drafter.propose   raises ``DrafterFault`` (server falls back to the plain decode program)
tick       ``GenerationServer._dispatch_trips``                raises ``TickFault`` *before* compiled dispatch (``kind="fatal"`` raises a plain ``RuntimeError`` instead — unrecoverable)
clock      ``FaultInjector.wrap_clock`` wrapper                stalls the clock (``kind="stall"``) or jumps it backwards (``kind="jump_back"`` by ``magnitude`` seconds)
replica_down ``FleetRouter.step`` health probe                 marks the probed replica dead mid-decode; the router salvages its in-flight requests onto peers (``inference/fleet.py``)
migrate_payload ``FleetRouter`` migration transfer             flips one bit in a migrating KV payload; the receiving engine's CRC-verified swap-in degrades it to re-prefill
route      ``FleetRouter`` routing decision                    misroutes one submission to the worst-scoring live replica (correctness unaffected — routing is a hint)
========== =================================================== ==========

Training hook sites (threaded through ``parallel/engine.py``,
``distributed/train_checkpoint.py`` and the elastic chaos harness,
``distributed/fleet/chaos.py``):

========== =================================================== ==========
site       fires inside                                        effect
========== =================================================== ==========
train_step ``ParallelEngine.train_batch``                      raises ``StepFault`` *before* compiled dispatch — donated state intact, the step retries verbatim (``kind="fatal"`` → plain ``RuntimeError``, unrecoverable)
data_feed  ``CheckpointableDataFeed.next_batch``               raises ``DataFeedFault`` before the cursor advances; a retry re-fetches the identical batch
ckpt_write ``TrainCheckpointer`` commit                        torn write: partial files land in the staging dir and the commit raises ``OSError`` before the atomic rename — the degradation ladder retries, then falls back to the last manifest-valid generation
ckpt_read  ``TrainCheckpointer`` manifest verification         flips one seeded bit in a committed checkpoint file ON DISK; the CRC32 manifest must detect it and restore must skip to the previous generation
kill       elastic chaos harness step loop                     raises ``SimulatedKill`` — the in-process analogue of SIGKILL; the harness drives rendezvous + restore-latest-valid + continue
========== =================================================== ==========

Injected faults at the ``tick``/``train_step`` sites fire *before* the
compiled call is dispatched, so donated buffers are still intact and the
trip can be retried verbatim — that ordering is what makes the
degradation ladders' retry rung safe on both stacks.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

SITES = frozenset({
    "alloc", "host_put", "swap_corrupt", "drafter", "tick", "clock",
    "replica_down", "migrate_payload", "route",
    "train_step", "data_feed", "ckpt_write", "ckpt_read", "kill",
})


class TickFault(RuntimeError):
    """A decode/prefill trip failed before compiled dispatch.

    Recoverable by construction: nothing was donated, nothing moved, so
    the server retries the trip after a backoff. ``rid`` (optional)
    attributes the fault to one request for poison quarantine.
    """

    def __init__(self, msg: str = "injected tick fault",
                 rid: Optional[int] = None):
        super().__init__(msg)
        self.rid = rid


class StepFault(RuntimeError):
    """A train step failed before compiled dispatch (site ``train_step``).

    The training twin of :class:`TickFault`: the injector fires before
    the jitted step consumes its donated params/opt-state, so the engine
    is untouched and ``train_batch`` can be retried with the same batch.
    """


class DataFeedFault(RuntimeError):
    """The host data feed failed to produce a batch (site ``data_feed``).

    Raised before the feed's cursor advances, so a retry fetches the
    bit-identical batch — resume determinism is unaffected.
    """


class SimulatedKill(Exception):
    """In-process stand-in for SIGKILL (site ``kill``).

    Raised by the elastic chaos harness between train steps; nothing
    downstream may catch-and-continue it except the harness's restart
    loop (mirroring the launcher's --max_restart relaunch path).
    """


class EngineFailedError(RuntimeError):
    """The server hit an unrecoverable error and refuses further work.

    Raised by ``submit()`` once the engine is in a terminal failed state
    (an exception escaped *after* compiled dispatch may have consumed
    donated buffers, so no further trip is safe). Restore a snapshot
    into a fresh server instead.
    """


@dataclass
class FaultSpec:
    """One scripted fault: fire at site-call ordinal ``at`` (0-based),
    for ``count`` consecutive calls. ``kind`` selects a site-specific
    variant, ``rid`` attributes tick faults to a request, ``magnitude``
    parameterises clock jumps."""

    site: str
    at: int = 0
    count: int = 1
    kind: str = ""
    rid: Optional[int] = None
    magnitude: float = 0.0

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(
                f"unknown fault site {self.site!r}; expected one of "
                f"{sorted(SITES)}")
        if self.at < 0 or self.count < 1:
            raise ValueError("FaultSpec needs at >= 0 and count >= 1")


@dataclass
class FaultPlan:
    """An ordered script of :class:`FaultSpec` plus the seed that makes
    payload corruption deterministic."""

    specs: List[FaultSpec] = field(default_factory=list)
    seed: int = 0

    @classmethod
    def chaos(cls, seed: int, *, intensity: int = 2,
              horizon: int = 240) -> "FaultPlan":
        """A seeded mixed plan for serving soak runs: allocator-exhaustion
        bursts, host-pool refusals, swap corruption, drafter failures,
        and sub-quarantine tick faults spread over ``horizon`` site
        calls. Same seed → same plan → same run."""
        # explicit-seed generator ON PURPOSE: a fault plan must replay
        # bit-identically across processes (capture vs. restore vs. CI),
        # independent of whatever paddle.seed the host program set
        rng = np.random.RandomState(seed)  # graftlint: noqa[np-random]
        specs: List[FaultSpec] = []
        for _ in range(intensity):
            specs.append(FaultSpec("alloc", at=int(rng.randint(8, horizon)),
                                   count=int(rng.randint(1, 4))))
            specs.append(FaultSpec("tick", at=int(rng.randint(4, horizon)),
                                   count=1))
        specs.append(FaultSpec("host_put",
                               at=int(rng.randint(0, max(4, horizon // 8)))))
        specs.append(FaultSpec("swap_corrupt",
                               at=int(rng.randint(0, 2))))
        specs.append(FaultSpec("drafter",
                               at=int(rng.randint(0, max(4, horizon // 4)))))
        return cls(specs=specs, seed=seed)

    @classmethod
    def fleet_chaos(cls, seed: int, *, replicas: int = 2,
                    horizon: int = 24) -> "FaultPlan":
        """A seeded fleet plan: kill one replica mid-decode, corrupt one
        migrating payload, and misroute a couple of submissions. The
        ``replica_down`` ordinal counts the router's per-replica health
        probes (``replicas`` per router step), so the kill lands at a
        deterministic (step, replica) pair within the first
        ``horizon // replicas`` router ticks — early enough that any
        real workload is still mid-decode when the replica dies. Same
        seed → same plan."""
        rng = np.random.RandomState(seed)  # graftlint: noqa[np-random]
        kill_step = int(rng.randint(2, max(3, horizon // replicas)))
        specs = [
            FaultSpec("replica_down",
                      at=kill_step * replicas + int(rng.randint(0, replicas))),
            FaultSpec("migrate_payload", at=int(rng.randint(0, 2))),
            FaultSpec("route", at=int(rng.randint(0, 8)),
                      count=int(rng.randint(1, 3))),
        ]
        return cls(specs=specs, seed=seed)

    @classmethod
    def disagg_chaos(cls, seed: int, *, replicas: int = 2,
                     prefill: int = 1, horizon: int = 24) -> "FaultPlan":
        """A seeded plan for DISAGGREGATED fleets: kill one
        PREFILL-class replica mid-chunk and corrupt one handoff payload.
        The caller orders its replicas prefill-first, so a
        ``replica_down`` ordinal of ``step * replicas + idx`` with
        ``idx < prefill`` is guaranteed to land on the prefill class —
        the generic :meth:`fleet_chaos` draw could hit a decode replica
        instead, which tests a different (and for a 1+1 fleet,
        unrecoverable-by-class) failure. Same seed → same plan."""
        if not 1 <= prefill < replicas:
            raise ValueError("disagg_chaos needs 1 <= prefill < replicas")
        rng = np.random.RandomState(seed)  # graftlint: noqa[np-random]
        kill_step = int(rng.randint(2, max(3, horizon // replicas)))
        specs = [
            FaultSpec("replica_down",
                      at=kill_step * replicas + int(rng.randint(0, prefill))),
            FaultSpec("migrate_payload", at=int(rng.randint(0, 2))),
        ]
        return cls(specs=specs, seed=seed)

    @classmethod
    def train_chaos(cls, seed: int, *, horizon: int = 32,
                    intensity: int = 1, kills: int = 1) -> "FaultPlan":
        """A seeded training plan for the elastic chaos harness:
        transient step-dispatch faults, a data-feed hiccup, one torn
        checkpoint write, one on-disk bit corruption caught at restore,
        and ``kills`` scripted SIGKILL analogues spread over ``horizon``
        train steps. Same seed → same plan → same (bit-exact) run."""
        rng = np.random.RandomState(seed)  # graftlint: noqa[np-random]
        specs: List[FaultSpec] = []
        for _ in range(intensity):
            specs.append(FaultSpec(
                "train_step", at=int(rng.randint(1, max(2, horizon // 2)))))
            specs.append(FaultSpec(
                "data_feed", at=int(rng.randint(1, max(2, horizon)))))
        specs.append(FaultSpec(
            "ckpt_write", at=int(rng.randint(1, max(2, horizon // 4))),
            kind="torn"))
        specs.append(FaultSpec("ckpt_read", at=int(rng.randint(0, 2))))
        # distinct kill ordinals: two kills scripted at the same site-call
        # ordinal collapse into one firing (fire() returns the first
        # match), silently halving the restart coverage the plan promises
        lo = min(2, max(1, horizon - 2))
        pool = list(range(lo, max(lo + 1, horizon - 1)))
        rng.shuffle(pool)
        ats = sorted(pool[:kills])
        while len(ats) < kills:
            ats.append((ats[-1] + 2) if ats else lo)
        for at in ats:
            specs.append(FaultSpec("kill", at=int(at)))
        return cls(specs=specs, seed=seed)


class FaultInjector:
    """Consults a :class:`FaultPlan` at named hook sites.

    Each ``fire(site)`` call increments that site's ordinal counter and
    returns the matching :class:`FaultSpec` (or ``None``). With no plan
    the injector is permanently disabled — hooks check ``enabled`` first
    so the production path is one attribute read.
    """

    def __init__(self, plan: Optional[FaultPlan] = None):
        self.plan = plan
        self.enabled = plan is not None and bool(plan.specs)
        self._by_site: Dict[str, List[FaultSpec]] = {}
        if plan is not None:
            for spec in plan.specs:
                self._by_site.setdefault(spec.site, []).append(spec)
        self._counts: Dict[str, int] = {}
        # same rationale as FaultPlan.chaos: plan-seeded, paddle-independent
        self._rng = np.random.RandomState(  # graftlint: noqa[np-random]
            plan.seed if plan else 0)
        self.fired: List[Tuple[str, int]] = []

    def fire(self, site: str) -> Optional[FaultSpec]:
        """Host-only hook. Returns the spec to apply, or ``None``."""
        if not self.enabled:
            return None
        n = self._counts.get(site, 0)
        self._counts[site] = n + 1
        for spec in self._by_site.get(site, ()):
            if spec.at <= n < spec.at + spec.count:
                self.fired.append((site, n))
                return spec
        return None

    def corrupt(self, arrays: Sequence[np.ndarray]) -> None:
        """Flip one seeded-deterministic bit in-place across ``arrays``
        (a parked swap payload) — the checksum verifier must catch it."""
        sizes = [a.nbytes for a in arrays]
        total = int(sum(sizes))
        if total == 0:
            return
        off = int(self._rng.randint(0, total))
        bit = int(self._rng.randint(0, 8))
        for a, sz in zip(arrays, sizes):
            if off < sz:
                flat = a.reshape(-1).view(np.uint8)
                flat[off] ^= np.uint8(1 << bit)
                return
            off -= sz

    def corrupt_file(self, path: str) -> int:
        """Flip one seeded-deterministic bit of the file at ``path``, in
        place on disk (site ``ckpt_read`` uses this against a committed
        checkpoint shard) — the CRC32 manifest must catch it. Returns
        the byte offset flipped, or -1 for an empty file."""
        import os

        size = os.path.getsize(path)
        if size == 0:
            return -1
        off = int(self._rng.randint(0, size))
        bit = int(self._rng.randint(0, 8))
        with open(path, "r+b") as f:
            f.seek(off)
            b = f.read(1)
            f.seek(off)
            f.write(bytes([b[0] ^ (1 << bit)]))
        return off

    def wrap_clock(self, clock: Callable[[], float]) -> Callable[[], float]:
        """Wrap an injectable clock with scripted stalls and backwards
        jumps (site ``clock``). The scheduler's monotonic clamp is the
        defense this exercises."""
        state: Dict[str, Any] = {"last": None}

        def faulty_clock() -> float:
            t = clock()
            spec = self.fire("clock")
            if spec is not None:
                if spec.kind == "stall" and state["last"] is not None:
                    return state["last"]
                if spec.kind == "jump_back":
                    t = t - (spec.magnitude or 10.0)
            state["last"] = t
            return t

        return faulty_clock

    def stats(self) -> Dict[str, Any]:
        """Site-call ordinals seen and faults actually fired."""
        return {
            "calls": dict(self._counts),
            "fired": len(self.fired),
            "fired_sites": sorted({s for s, _ in self.fired}),
        }


#: Shared disabled injector — hook sites default to this so the hot path
#: is a single ``enabled`` attribute check.
NULL_INJECTOR = FaultInjector()
