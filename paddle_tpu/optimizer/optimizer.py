"""Optimizers (ref: python/paddle/optimizer/optimizer.py base :294 state_dict;
adam.py, adamw.py, momentum.py, lamb.py ...).

Eager API parity: ``opt.step()`` reads ``param.grad`` slots and updates
``param._value`` in place.  Each parameter's update rule is a pure jitted
function, so the math runs fused on-device; the jit/pjit training path uses
the same rules through ``functional_update`` (no tape, no .grad slots).
"""
from __future__ import annotations

import functools
from collections import OrderedDict
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..framework.core import Parameter, Tensor
from ..framework.dtype import convert_dtype
from .lr import LRScheduler


def _pure_grad_clip(clip, grads):
    """Traceable counterpart of ClipGradBy*'s eager _dygraph_clip, applied
    inside compiled train steps (pure_update): same math, no host
    concretization. Unknown custom clip classes are skipped with a warning
    (their eager hook cannot run under jit)."""
    from ..nn.clip import (ClipGradByGlobalNorm, ClipGradByNorm,
                           ClipGradByValue)

    if isinstance(clip, ClipGradByValue):
        return {n: jnp.clip(g, clip.min, clip.max) for n, g in grads.items()}
    if isinstance(clip, ClipGradByGlobalNorm):
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                 for g in grads.values())
        scale = jnp.minimum(
            clip.clip_norm / jnp.maximum(jnp.sqrt(sq), 1e-12), 1.0)
        return {n: (g.astype(jnp.float32) * scale).astype(g.dtype)
                for n, g in grads.items()}
    if isinstance(clip, ClipGradByNorm):
        out = {}
        for n, g in grads.items():
            nrm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            sc = jnp.minimum(clip.clip_norm / jnp.maximum(nrm, 1e-12), 1.0)
            out[n] = (g.astype(jnp.float32) * sc).astype(g.dtype)
        return out
    import warnings

    warnings.warn(f"grad_clip {type(clip).__name__} has no traceable form; "
                  f"compiled train step proceeds UNCLIPPED", UserWarning)
    return grads


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._grad_clip = grad_clip
        if isinstance(weight_decay, (int, float)):
            self._weight_decay = float(weight_decay)
            self._l2_coeff = float(weight_decay)
            self._reg_mode = "l2"
        else:
            self._weight_decay = weight_decay
            self._l2_coeff = getattr(weight_decay, "_coeff",
                                     getattr(weight_decay, "_regularization_coeff", 0.0)) \
                if weight_decay is not None else 0.0
            # L1Decay folds coeff*sign(w); L2Decay folds coeff*w (paddle semantics)
            self._reg_mode = getattr(weight_decay, "_mode", "l2")
        # per-param slot state: name -> dict of arrays
        self._accumulators: Dict[int, Dict[str, jax.Array]] = {}
        self._global_step = 0

    # ----------------------------------------------------------------- lr
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value: float):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("optimizer's learning rate can't be LRScheduler when invoke"
                               " this API, because this will lead to conflict.")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler: LRScheduler):
        self._learning_rate = scheduler

    # --------------------------------------------------------------- state
    def _slots_for(self, p: Parameter) -> Dict[str, jax.Array]:
        key = id(p)
        if key not in self._accumulators:
            self._accumulators[key] = self._create_slots(p)
            self._accumulators[key]["__param_ref"] = p
        return self._accumulators[key]

    def _create_slots(self, p: Parameter) -> Dict[str, jax.Array]:
        return {}

    def state_dict(self) -> dict:
        """Ref optimizer.py:294 — accumulator tensors + LR scheduler state."""
        sd = {}
        for i, (key, slots) in enumerate(self._accumulators.items()):
            p = slots.get("__param_ref")
            pname = p.name if p is not None and p.name else f"param_{i}"
            for sname, val in slots.items():
                if sname.startswith("__"):
                    continue
                sd[f"{pname}.{sname}"] = Tensor(val) if not isinstance(val, Tensor) else val
        sd["global_step"] = self._global_step
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        return sd

    def set_state_dict(self, state_dict: dict):
        self._global_step = int(state_dict.get("global_step", 0))
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        params = self._get_params()
        by_name = {}
        for i, p in enumerate(params):
            pname = p.name if p.name else f"param_{i}"
            by_name[pname] = p
        for k, v in state_dict.items():
            if k in ("global_step", "LR_Scheduler"):
                continue
            if "." not in k:
                continue
            pname, sname = k.rsplit(".", 1)
            p = by_name.get(pname)
            if p is None:
                continue
            slots = self._slots_for(p)
            slots[sname] = v.value if isinstance(v, Tensor) else jnp.asarray(v)

    set_dict = set_state_dict

    # ---------------------------------------------------------------- step
    def _get_params(self) -> List[Parameter]:
        if self._parameter_list is None:
            raise ValueError("Optimizer created without explicit parameters; pass "
                             "parameters=model.parameters()")
        out = []
        for item in self._parameter_list:
            if isinstance(item, dict):
                out.extend(item["params"])
            else:
                out.append(item)
        return out

    def step(self):
        params = [p for p in self._get_params() if p.trainable]
        params_grads = [(p, p.grad) for p in params if p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._global_step += 1
        lr = self.get_lr()
        for p, g in params_grads:
            if g is None:
                continue
            slots = self._slots_for(p)
            g_val = g.value.astype(jnp.float32)
            if self._use_l2_decay() and (
                    self._l2_coeff or getattr(p, "regularizer", None) is not None):
                g_val = g_val + self._reg_grad(p.value.astype(jnp.float32), p)
            new_val, new_slots = self._apply_one(
                p.value, g_val, lr, self._global_step,
                {k: v for k, v in slots.items() if not k.startswith("__")})
            p._value = new_val
            slots.update(new_slots)

    def _use_l2_decay(self) -> bool:
        return True  # L2 regularization folded into grads (paddle weight_decay semantics)

    def _reg_grad(self, pval, p=None):
        """d(penalty)/d(w), honouring a per-param ParamAttr regularizer override
        (ref: python/paddle/fluid/regularizer.py append_regularization_ops)."""
        reg = getattr(p, "regularizer", None) if p is not None else None
        if reg is not None:
            return reg(pval)
        if self._reg_mode == "l1":
            return self._l2_coeff * jnp.sign(pval)
        return self._l2_coeff * pval

    def _apply_one(self, param, grad, lr, step, slots):
        raise NotImplementedError

    # ---------------------------------------------------- functional (jit/pjit)
    def init_state(self, params: Dict[str, jax.Array]) -> Dict[str, Dict[str, jax.Array]]:
        """Pure slot-state init for the compiled path (params: name → array)."""

        class _P:
            def __init__(self, v):
                self.shape = tuple(v.shape)
                self.dtype = v.dtype
                self.value = v

        return {name: self._create_slots(_P(v)) for name, v in params.items()}

    def pure_update(self, params, grads, state, lr, step, pnames=None,
                    regularizers=None):
        """One optimizer step as a pure function — used inside pjit train steps
        (the ZeRO/master-weight sharding comes from the state's shardings).
        ``regularizers``: name → per-param regularizer callable (the ParamAttr
        override the eager step() reads from p.regularizer)."""
        regularizers = regularizers or {}
        if self._grad_clip is not None:
            grads = _pure_grad_clip(self._grad_clip, grads)
        new_params, new_state = {}, {}
        for name, p in params.items():
            g = grads.get(name)
            if g is None:
                new_params[name] = p
                new_state[name] = state.get(name, {})
                continue
            g = g.astype(jnp.float32)
            reg = regularizers.get(name)
            if self._use_l2_decay() and (self._l2_coeff or reg is not None):
                g = g + (reg(p.astype(jnp.float32)) if reg is not None
                         else self._reg_grad(p.astype(jnp.float32)))
            np_, ns = self._apply_one(p, g, lr, step, state.get(name, {}))
            new_params[name] = np_
            new_state[name] = ns
        return new_params, new_state

    def clear_grad(self, set_to_zero: bool = True):
        for p in self._get_params():
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..static.graph import Variable as _StaticVar

        if isinstance(loss, _StaticVar):
            # static-graph branch (ref Optimizer.minimize appending backward +
            # update ops): mark the program; Executor.run fuses jax.grad +
            # pure_update into one XLA train step.
            prog = loss.program
            prog.loss_name = loss.var_name
            prog.optimizer = self
            prog._version += 1
            return None, [(p, f"{getattr(p, 'name', 'param')}@GRAD")
                          for p in prog.params.values()]
        loss.backward()
        self.step()
        return None, None

    @property
    def _param_groups(self):
        return self._parameter_list


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _apply_one(self, param, grad, lr, step, slots):
        return (param.astype(jnp.float32) - lr * grad).astype(param.dtype), {}


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _create_slots(self, p):
        return {"velocity": jnp.zeros(tuple(p.shape), jnp.float32)}

    def _apply_one(self, param, grad, lr, step, slots):
        v = slots["velocity"] * self._momentum + grad
        if self._nesterov:
            upd = grad + self._momentum * v
        else:
            upd = v
        return (param.astype(jnp.float32) - lr * upd).astype(param.dtype), {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._multi_precision = multi_precision

    def _create_slots(self, p):
        slots = {
            "moment1": jnp.zeros(tuple(p.shape), jnp.float32),
            "moment2": jnp.zeros(tuple(p.shape), jnp.float32),
        }
        if self._multi_precision and p.dtype != jnp.float32:
            slots["master_weight"] = p.value.astype(jnp.float32)
        return slots

    def _apply_one(self, param, grad, lr, step, slots):
        from ..ops.fused_adamw import fused_adamw_update

        new_p, m2, v2, new_master = fused_adamw_update(
            param, grad, slots["moment1"], slots["moment2"], lr=lr,
            step=step, b1=self._beta1, b2=self._beta2, eps=self._epsilon,
            decay=0.0, master=slots.get("master_weight"))
        out = {"moment1": m2, "moment2": v2}
        if "master_weight" in slots:
            out["master_weight"] = new_master
        return new_p, out


class AdamW(Adam):
    """Decoupled weight decay (ref python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters, None, grad_clip,
                         lazy_mode, multi_precision, name)
        self._wd_coeff = float(weight_decay) if isinstance(weight_decay, (int, float)) \
            else 0.01
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio
        self._current_param = None

    def _use_l2_decay(self):
        return False

    def step(self):
        params = [p for p in self._get_params() if p.trainable]
        params_grads = [(p, p.grad) for p in params if p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._global_step += 1
        lr = self.get_lr()
        for p, g in params_grads:
            if g is None:
                continue
            slots = self._slots_for(p)
            g_val = g.value.astype(jnp.float32)
            if getattr(p, "regularizer", None) is not None:
                # per-param ParamAttr regularizer adds its gradient even
                # though AdamW's own decay is decoupled (ref
                # append_regularization_ops is optimizer-independent)
                g_val = g_val + p.regularizer(p.value.astype(jnp.float32))
            decay = self._wd_coeff
            if self._apply_decay_param_fun is not None and \
                    not self._apply_decay_param_fun(p.name):
                decay = 0.0
            lr_r = self._lr_ratio(p) if self._lr_ratio is not None else 1.0
            new_val, new_slots = self._apply_adamw(
                p.value, g_val, lr * lr_r, self._global_step, decay,
                {k: v for k, v in slots.items() if not k.startswith("__")})
            p._value = new_val
            slots.update(new_slots)

    # ---------------------------------------------- multi-tensor (flat) apply
    _MT_ROW = 512          # flat view (K, 512); total padded to 128*512

    def _mt_active(self) -> bool:
        """PT_MT_ADAMW=1 selects ONE fused launch over the concatenated
        flat state instead of per-tensor updates (the overlap-preserving
        design from the round-3 fused-AdamW postmortem; ref
        incubate/optimizer/distributed_fused_lamb.py multi-tensor
        precedent). Uniform-hyperparameter runs only: per-param decay
        masks, lr ratios and master-weight mode keep the per-tensor path.
        Multi-device TPU runs also keep it: the flat state would replicate
        on every device (engine shards opt state by owning-param name) and
        the kernel itself gates on single-device — all cost, no benefit.
        The virtual CPU mesh (tests) is exempt as the correctness seam.
        """
        import os

        multi_dev_tpu = jax.device_count() != 1 and \
            jax.default_backend() != "cpu"
        return (os.environ.get("PT_MT_ADAMW") == "1" and not multi_dev_tpu
                and self._apply_decay_param_fun is None
                and self._lr_ratio is None and not self._multi_precision)

    def init_state(self, params):
        """With PT_MT_ADAMW=1 the flat state's 'p' buffer IS the
        authoritative weight copy from this point on: _mt_update rebuilds
        params from it and ignores incoming values, so any external param
        mutation (checkpoint load, set_state_dict, sync_from_model) must
        happen BEFORE engine/opt state init — later loads are silently
        discarded. Re-init the state (or unset PT_MT_ADAMW) to load
        weights mid-run."""
        if not self._mt_active() or len(params) < 2 or \
                len({jnp.asarray(v).dtype if not hasattr(v, "dtype") else
                     v.dtype for v in params.values()}) != 1:
            return super().init_state(params)
        import numpy as np

        layout = [(n, tuple(v.shape), int(np.prod(v.shape, dtype=np.int64)))
                  for n, v in sorted(params.items())]
        total = sum(s for _, _, s in layout)
        unit = 128 * self._MT_ROW  # (128, 512) min tile of the flat view
        padded = -(-total // unit) * unit
        self._mt_layout = layout
        self._mt_padded = padded
        flat = jnp.concatenate(
            [jnp.reshape(params[n], (-1,)) for n, _, _ in layout] +
            ([jnp.zeros((padded - total,), next(iter(params.values())).dtype)]
             if padded > total else []))
        p2 = flat.reshape(-1, self._MT_ROW)
        return {"__mt__": {
            "p": p2,
            "moment1": jnp.zeros(p2.shape, jnp.float32),
            "moment2": jnp.zeros(p2.shape, jnp.float32),
        }}

    def _mt_update(self, params, grads, state, lr, step):
        from ..ops.fused_adamw import flat_adamw_update

        if self._grad_clip is not None:
            grads = _pure_grad_clip(self._grad_clip, grads)
        mt = state["__mt__"]
        layout, padded = self._mt_layout, self._mt_padded
        total = sum(s for _, _, s in layout)
        # grads concat in f32: the kernel's grad operand upcasts internally
        # regardless of the param dtype, so a bf16 concat would throw away
        # gradient precision the per-tensor path keeps
        g = jnp.concatenate(
            [jnp.reshape(grads[n], (-1,)).astype(jnp.float32)
             for n, _, _ in layout] +
            ([jnp.zeros((padded - total,), jnp.float32)]
             if padded > total else []))
        new_p2, m2, v2 = flat_adamw_update(
            mt["p"], g.reshape(-1, self._MT_ROW), mt["moment1"],
            mt["moment2"], lr=lr, step=step, b1=self._beta1, b2=self._beta2,
            eps=self._epsilon, decay=self._wd_coeff)
        flat = new_p2.reshape(-1)
        new_params = dict(params)
        off = 0
        for n, shape, size in layout:
            # static slices: XLA fuses the per-tensor reads into consumers
            new_params[n] = jax.lax.slice(flat, (off,), (off + size,)
                                          ).reshape(shape)
            off += size
        return new_params, {"__mt__": {"p": new_p2, "moment1": m2,
                                       "moment2": v2}}

    def pure_update(self, params, grads, state, lr, step, pnames=None,
                    regularizers=None):
        if "__mt__" in state:
            missing = [n for n, _, _ in self._mt_layout
                       if grads.get(n) is None]
            if missing or regularizers:
                raise ValueError(
                    f"PT_MT_ADAMW flat state cannot skip per-tensor work "
                    f"(missing grads {missing[:3]}... or per-param "
                    f"regularizers); unset PT_MT_ADAMW for this run")
            return self._mt_update(params, grads, state, lr, step)
        # AdamW decay is decoupled; a per-param ParamAttr regularizer still
        # adds its gradient (same as the eager step() path)
        regularizers = regularizers or {}
        if self._grad_clip is not None:
            grads = _pure_grad_clip(self._grad_clip, grads)
        new_params, new_state = {}, {}
        for name, p in params.items():
            g = grads.get(name)
            if g is None:
                new_params[name] = p
                new_state[name] = state.get(name, {})
                continue
            reg = regularizers.get(name)
            if reg is not None:
                g = g.astype(jnp.float32) + reg(p.astype(jnp.float32))
            decay = self._wd_coeff
            if self._apply_decay_param_fun is not None and \
                    not self._apply_decay_param_fun(name):
                decay = 0.0
            np_, ns = self._apply_adamw(p, g.astype(jnp.float32), lr, step, decay,
                                        state.get(name, {}))
            new_params[name] = np_
            new_state[name] = ns
        return new_params, new_state

    def _apply_adamw(self, param, grad, lr, step, decay, slots):
        from ..ops.fused_adamw import fused_adamw_update

        new_p, m2, v2, new_master = fused_adamw_update(
            param, grad, slots["moment1"], slots["moment2"], lr=lr,
            step=step, b1=self._beta1, b2=self._beta2, eps=self._epsilon,
            decay=decay, master=slots.get("master_weight"))
        out = {"moment1": m2, "moment2": v2}
        if "master_weight" in slots:
            out["master_weight"] = new_master
        return new_p, out


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_slots(self, p):
        return {"moment": jnp.zeros(tuple(p.shape), jnp.float32),
                "inf_norm": jnp.zeros(tuple(p.shape), jnp.float32)}

    def _apply_one(self, param, grad, lr, step, slots):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * slots["moment"] + (1 - b1) * grad
        u = jnp.maximum(b2 * slots["inf_norm"], jnp.abs(grad))
        upd = lr / (1 - b1 ** step) * m / (u + eps)
        return (param.astype(jnp.float32) - upd).astype(param.dtype), \
            {"moment": m, "inf_norm": u}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _create_slots(self, p):
        return {"moment": jnp.full(tuple(p.shape), self._init_acc, jnp.float32)}

    def _apply_one(self, param, grad, lr, step, slots):
        acc = slots["moment"] + grad * grad
        return (param.astype(jnp.float32) - lr * grad / (jnp.sqrt(acc) + self._epsilon)
                ).astype(param.dtype), {"moment": acc}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon, self._momentum, self._centered = rho, epsilon, momentum, \
            centered

    def _create_slots(self, p):
        s = {"mean_square": jnp.zeros(tuple(p.shape), jnp.float32),
             "momentum_acc": jnp.zeros(tuple(p.shape), jnp.float32)}
        if self._centered:
            s["mean_grad"] = jnp.zeros(tuple(p.shape), jnp.float32)
        return s

    def _apply_one(self, param, grad, lr, step, slots):
        ms = self._rho * slots["mean_square"] + (1 - self._rho) * grad * grad
        out = {"mean_square": ms}
        if self._centered:
            mg = self._rho * slots["mean_grad"] + (1 - self._rho) * grad
            out["mean_grad"] = mg
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * slots["momentum_acc"] + lr * grad / denom
        out["momentum_acc"] = mom
        return (param.astype(jnp.float32) - mom).astype(param.dtype), out


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon, self._rho = epsilon, rho

    def _create_slots(self, p):
        return {"avg_squared_grad": jnp.zeros(tuple(p.shape), jnp.float32),
                "avg_squared_update": jnp.zeros(tuple(p.shape), jnp.float32)}

    def _apply_one(self, param, grad, lr, step, slots):
        g2 = self._rho * slots["avg_squared_grad"] + (1 - self._rho) * grad * grad
        upd = grad * jnp.sqrt(slots["avg_squared_update"] + self._epsilon) / \
            jnp.sqrt(g2 + self._epsilon)
        u2 = self._rho * slots["avg_squared_update"] + (1 - self._rho) * upd * upd
        return (param.astype(jnp.float32) - lr * upd).astype(param.dtype), \
            {"avg_squared_grad": g2, "avg_squared_update": u2}


class Lamb(Optimizer):
    """Ref python/paddle/optimizer/lamb.py."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _create_slots(self, p):
        return {"moment1": jnp.zeros(tuple(p.shape), jnp.float32),
                "moment2": jnp.zeros(tuple(p.shape), jnp.float32)}

    def _apply_one(self, param, grad, lr, step, slots):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * slots["moment1"] + (1 - b1) * grad
        v = b2 * slots["moment2"] + (1 - b2) * grad * grad
        mhat = m / (1 - b1 ** step)
        vhat = v / (1 - b2 ** step)
        p32 = param.astype(jnp.float32)
        r = mhat / (jnp.sqrt(vhat) + eps) + self._lamb_wd * p32
        w_norm = jnp.sqrt(jnp.sum(p32 * p32))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (p32 - lr * trust * r).astype(param.dtype), {"moment1": m, "moment2": v}


class Lars(Momentum):
    """LARS momentum (ref fluid LarsMomentumOptimizer)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None, name=None,
                 exclude_from_weight_decay=None, epsilon=0):
        super().__init__(learning_rate, momentum, parameters, False, None, grad_clip,
                         name=name)
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._lars_eps = epsilon

    def _apply_one(self, param, grad, lr, step, slots):
        p32 = param.astype(jnp.float32)
        p_norm = jnp.sqrt(jnp.sum(p32 * p32))
        g_norm = jnp.sqrt(jnp.sum(grad * grad))
        local_lr = jnp.where(
            (p_norm > 0) & (g_norm > 0),
            self._lars_coeff * p_norm / (g_norm + self._lars_wd * p_norm + self._lars_eps),
            1.0)
        upd = grad + self._lars_wd * p32
        v = self._momentum * slots["velocity"] + lr * local_lr * upd
        return (p32 - v).astype(param.dtype), {"velocity": v}
