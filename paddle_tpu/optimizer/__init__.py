"""paddle.optimizer parity (ref: python/paddle/optimizer/__init__.py)."""
from . import lr  # noqa: F401
from .optimizer import (Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, Lars, Momentum,
                        Optimizer, RMSProp, SGD)

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad", "RMSProp",
           "Adadelta", "Lamb", "Lars", "lr"]
