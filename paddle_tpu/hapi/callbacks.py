"""Callbacks (ref: python/paddle/hapi/callbacks.py — ProgBarLogger,
ModelCheckpoint, LRScheduler, EarlyStopping, VisualDL)."""
from __future__ import annotations

import numbers
import os
import time
from typing import List, Optional

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_begin(self, mode, logs=None):
        getattr(self, f"on_{mode}_begin", lambda l=None: None)(logs)

    def on_end(self, mode, logs=None):
        getattr(self, f"on_{mode}_end", lambda l=None: None)(logs)

    def on_batch_begin(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_begin", lambda s, l=None: None)(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_end", lambda s, l=None: None)(step, logs)

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = callbacks

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def on_begin(self, mode, logs=None):
        for c in self.callbacks:
            c.on_begin(mode, logs)

    def on_end(self, mode, logs=None):
        for c in self.callbacks:
            c.on_end(mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_begin(epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_end(epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        for c in self.callbacks:
            c.on_batch_begin(mode, step, logs)

    def on_batch_end(self, mode, step, logs=None):
        for c in self.callbacks:
            c.on_batch_end(mode, step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose
        self._start = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._start = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            logs = logs or {}
            metrics = " - ".join(f"{k}: {v:.4f}" for k, v in logs.items()
                                 if isinstance(v, numbers.Number) and k != "step"
                                 and k != "batch_size")
            elapsed = time.time() - self._start
            total = self.steps if self.steps else "?"
            print(f"Epoch {self.epoch}: step {step}/{total} - {metrics} "
                  f"- {elapsed:.1f}s", flush=True)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            logs = logs or {}
            metrics = " - ".join(f"{k}: {v:.4f}" for k, v in logs.items()
                                 if isinstance(v, numbers.Number))
            print(f"Epoch {epoch} done - {metrics}", flush=True)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        from ..optimizer.lr import LRScheduler as Sched

        if opt is not None and isinstance(opt._learning_rate, Sched):
            return opt._learning_rate
        return None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.monitor_op = np.greater
            self.min_delta *= 1
        else:
            self.monitor_op = np.less
            self.min_delta *= -1
        self.best = None
        self.wait = 0

    def on_eval_end(self, logs=None):
        logs = logs or {}
        current = logs.get(self.monitor)
        if current is None:
            return
        if isinstance(current, (list, tuple)):
            current = current[0]
        if self.best is None or self.monitor_op(current - self.min_delta, self.best):
            self.best = current
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: best {self.monitor}={self.best}")


class VisualDL(Callback):
    """Scalar logger; writes TSV (VisualDL itself is not in this image)."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        os.makedirs(self.log_dir, exist_ok=True)
        logs = logs or {}
        with open(os.path.join(self.log_dir, "scalars.tsv"), "a") as f:
            for k, v in logs.items():
                if isinstance(v, numbers.Number):
                    f.write(f"{self._step}\t{k}\t{v}\n")
        self._step += 1


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1, mode="auto",
                 min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.best = None
        self.wait = 0

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self.best is None or cur < self.best:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                opt = self.model._optimizer
                try:
                    opt.set_lr(opt.get_lr() * self.factor)
                except RuntimeError:
                    pass
                self.wait = 0


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None, steps=None,
                     log_freq=2, verbose=2, save_freq=1, save_dir=None, metrics=None,
                     mode="train"):
    cbks = list(callbacks) if callbacks else []
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({"batch_size": batch_size, "epochs": epochs, "steps": steps,
                   "verbose": verbose, "metrics": metrics or []})
    return cl
