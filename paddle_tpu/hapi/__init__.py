"""High-level API (ref: python/paddle/hapi/model.py Model:1004, fit:1696)."""
from .model import Model
from . import callbacks  # noqa: F401
from .summary import summary

__all__ = ["Model", "callbacks", "summary"]
