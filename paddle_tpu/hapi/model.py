"""paddle.Model (ref: python/paddle/hapi/model.py:1004; DynamicGraphAdapter
:732).  Single adapter: eager training with the tape; users wanting compiled
steps wrap the network with paddle_tpu.jit.to_static before Model().
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..framework.core import Tensor, no_grad_ctx
from ..framework.io_state import load as _load
from ..framework.io_state import save as _save
from ..io import DataLoader
from ..metric import Metric
from . import callbacks as cbks_mod


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False

    # ------------------------------------------------------------------ prep
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        """Ref model.py:1619.  ``amp_configs``: "O1"/"O2" or a dict with
        ``level`` plus GradScaler/auto_cast knobs (init_loss_scaling,
        incr/decr ratios, custom_white_list/custom_black_list), matching the
        reference's _check_amp_configs surface; training then runs under
        ``paddle.amp.auto_cast`` with dynamic loss scaling."""
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) else [metrics]
        else:
            self._metrics = []
        # parse/validate FIRST, commit to self only once everything checks
        # out — a ValueError must not leave the Model half-configured
        level, white, black, scaler = "O0", None, None, None
        if amp_configs:
            if isinstance(amp_configs, str):
                level, cfg = amp_configs, {}
            else:
                cfg = dict(amp_configs)
                level = cfg.pop("level", "O1")
            if level not in ("O0", "O1", "O2"):
                raise ValueError(f"amp level must be O0/O1/O2, got {level!r}")
            white = cfg.pop("custom_white_list", None)
            black = cfg.pop("custom_black_list", None)
            scaler_kw = {k: cfg.pop(k) for k in (
                "init_loss_scaling", "incr_ratio", "decr_ratio",
                "incr_every_n_steps", "decr_every_n_nan_or_inf",
                "use_dynamic_loss_scaling") if k in cfg}
            if cfg:
                raise ValueError(f"unknown amp_configs keys: {sorted(cfg)}")
            if level != "O0":
                from ..amp import GradScaler, decorate

                scaler = GradScaler(**scaler_kw)
                if level == "O2":
                    # reference O2 contract: params cast to bf16, optimizer
                    # keeps fp32 master weights (amp.decorate)
                    decorate(self.network, optimizers=optimizer, level="O2")
        self._amp_level = level
        self._amp_custom_white = white
        self._amp_custom_black = black
        self._scaler = scaler

    # ------------------------------------------------------------------ steps
    def _compute_loss(self, outputs, labels):
        if self._loss is None:
            raise RuntimeError("call prepare(loss=...) first")
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        lbls = labels if isinstance(labels, (list, tuple)) else [labels]
        if isinstance(self._loss, (list, tuple)):
            # per-output loss fns summed (ref Model multi-output contract)
            if not (len(self._loss) == len(outs) == len(lbls)):
                raise ValueError(
                    f"loss list/outputs/labels length mismatch: "
                    f"{len(self._loss)}/{len(outs)}/{len(lbls)}")
            parts = [fn(o, l) for fn, o, l in zip(self._loss, outs, lbls)]
            total = parts[0]
            for p in parts[1:]:
                total = total + p
            return total
        return self._loss(*outs, *lbls)

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        from ..amp import auto_cast

        amp_on = getattr(self, "_amp_level", "O0") != "O0"
        with auto_cast(enable=amp_on,
                       level=self._amp_level if amp_on else "O1",
                       custom_white_list=getattr(self, "_amp_custom_white",
                                                 None),
                       custom_black_list=getattr(self, "_amp_custom_black",
                                                 None)):
            outputs = self.network(*inputs)
            loss = self._compute_loss(outputs, labels)
        scaler = getattr(self, "_scaler", None)
        if scaler is not None:
            scaler.scale(loss).backward()
            if update:
                scaler.step(self._optimizer)
                scaler.update()
                self._optimizer.clear_grad()
        else:
            loss.backward()
            if update:
                self._optimizer.step()
                self._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        return ([float(loss.item())], metrics) if metrics else [float(loss.item())]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        with no_grad_ctx():
            outputs = self.network(*inputs)
            loss = self._compute_loss(outputs, labels) if self._loss else None
        metrics = self._update_metrics(outputs, labels)
        out = [float(loss.item())] if loss is not None else []
        return (out, metrics) if metrics else out

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        with no_grad_ctx():
            outputs = self.network(*inputs)
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        return [np.asarray(o.value) for o in outs]

    def _update_metrics(self, outputs, labels):
        vals = []
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        lbls = labels if isinstance(labels, (list, tuple)) else [labels]
        for m in self._metrics:
            res = m.compute(*outs, *lbls)
            m.update(res)
            vals.append(m.accumulate())
        return vals

    # ------------------------------------------------------------------- fit
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        """Ref model.py:1696."""
        if not isinstance(train_data, DataLoader):
            train_loader = DataLoader(train_data, batch_size=batch_size, shuffle=shuffle,
                                      drop_last=drop_last, num_workers=num_workers)
        else:
            train_loader = train_data
        eval_loader = None
        if eval_data is not None:
            eval_loader = eval_data if isinstance(eval_data, DataLoader) else DataLoader(
                eval_data, batch_size=batch_size, num_workers=num_workers)

        cbks = cbks_mod.config_callbacks(
            callbacks, model=self, epochs=epochs, steps=self._safe_len(train_loader),
            log_freq=log_freq, save_freq=save_freq, save_dir=save_dir, verbose=verbose,
            metrics=["loss"] + [self._flat_names()] if self._metrics else ["loss"])

        cbks.on_begin("train")
        step_count = 0
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(train_loader):
                cbks.on_batch_begin("train", step, logs)
                ins, lbl = self._split_batch(batch)
                res = self.train_batch(ins, lbl,
                                       update=(step + 1) % accumulate_grad_batches == 0)
                logs = self._make_logs(res)
                logs["step"] = step
                logs["batch_size"] = self._batch_size_of(ins)
                cbks.on_batch_end("train", step, logs)
                step_count += 1
                if num_iters is not None and step_count >= num_iters:
                    self.stop_training = True
                    break
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self._run_eval(eval_loader, cbks)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cbks.on_epoch_end(epoch, logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
        if save_dir:
            self.save(os.path.join(save_dir, "final"))
        cbks.on_end("train", logs if "logs" in dir() else {})

    def _run_eval(self, eval_loader, cbks):
        for m in self._metrics:
            m.reset()
        cbks.on_begin("eval")
        logs = {}
        for step, batch in enumerate(eval_loader):
            cbks.on_batch_begin("eval", step, logs)
            ins, lbl = self._split_batch(batch)
            res = self.eval_batch(ins, lbl)
            logs = self._make_logs(res)
            cbks.on_batch_end("eval", step, logs)
        cbks.on_end("eval", logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2, num_workers=0,
                 callbacks=None, num_iters=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else DataLoader(
            eval_data, batch_size=batch_size, num_workers=num_workers)
        for m in self._metrics:
            m.reset()
        logs = {}
        for step, batch in enumerate(loader):
            ins, lbl = self._split_batch(batch)
            res = self.eval_batch(ins, lbl)
            logs = self._make_logs(res)
            if num_iters is not None and step + 1 >= num_iters:
                break
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = test_data if isinstance(test_data, DataLoader) else DataLoader(
            test_data, batch_size=batch_size, num_workers=num_workers)
        outputs = []
        has_label = self._loss is not None
        for batch in loader:
            ins, _ = self._split_batch(batch, has_label=has_label)
            outputs.append(self.predict_batch(ins))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n_out)]
        return outputs

    # ------------------------------------------------------------------- io
    def save(self, path, training=True):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        sd = _load(path + ".pdparams")
        self.network.set_state_dict(sd)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and os.path.exists(opt_path):
            self._optimizer.set_state_dict(_load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary

        return _summary(self.network, input_size, dtypes=dtype)

    # ---------------------------------------------------------------- helpers
    @staticmethod
    def _safe_len(loader):
        try:
            return len(loader)
        except TypeError:
            return None

    def _flat_names(self):
        names = []
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    def _split_batch(self, batch, has_label=True):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2 and has_label:
                return list(batch[:-1]), batch[-1]
            return list(batch), None
        return [batch], None

    @staticmethod
    def _batch_size_of(ins):
        t = ins[0]
        try:
            return t.shape[0]
        except Exception:
            return 1

    def _make_logs(self, res):
        logs = {}
        if isinstance(res, tuple):
            losses, metrics = res
            logs["loss"] = losses[0] if isinstance(losses, list) else losses
            for m, v in zip(self._metrics, metrics):
                n = m.name()
                logs[n if isinstance(n, str) else n[0]] = v
        else:
            logs["loss"] = res[0] if isinstance(res, list) else res
        return logs
