"""Model summary (ref: python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np


def summary(net, input_size=None, dtypes=None, input=None):
    rows = []
    total_params = 0
    trainable_params = 0
    for name, layer in net.named_sublayers(include_self=True):
        n_params = 0
        for _, p in layer._parameters.items():
            if p is None:
                continue
            n = int(np.prod(p.shape)) if p.shape else 1
            n_params += n
        if not name:
            continue
        if n_params:
            rows.append((name, type(layer).__name__, n_params))
    seen = set()
    for _, p in net.named_parameters():
        if id(p) in seen:
            continue
        seen.add(id(p))
        n = int(np.prod(p.shape)) if p.shape else 1
        total_params += n
        if p.trainable:
            trainable_params += n
    lines = [f"{'Layer':<45}{'Type':<25}{'Params':>12}"]
    lines.append("-" * 82)
    for name, tname, n in rows:
        lines.append(f"{name:<45}{tname:<25}{n:>12,}")
    lines.append("-" * 82)
    lines.append(f"Total params: {total_params:,}")
    lines.append(f"Trainable params: {trainable_params:,}")
    lines.append(f"Non-trainable params: {total_params - trainable_params:,}")
    print("\n".join(lines))
    return {"total_params": total_params, "trainable_params": trainable_params}
