"""ONNX export — a documented NON-GOAL of this framework (ref
python/paddle/onnx/export.py export(), which itself only delegates to the
external paddle2onnx package and raises without it).

TPU-native rationale: the portable interchange format for XLA programs is
StableHLO — ``paddle.jit.save`` / ``paddle.inference`` export and consume
it, and it is what TPU serving runs.  No StableHLO→ONNX converter exists in
jax, and bundling one is out of scope (README "Non-goals"); this module
keeps the reference's API surface and failure mode: calling ``export``
raises with guidance, exactly as the reference does without paddle2onnx.
"""
from __future__ import annotations

__all__ = []


def export(layer, path: str, input_spec=None, opset_version: int = 13,
           **configs):
    """API-parity stub (ref export.py export()): always raises.

    The reference delegates to the external ``paddle2onnx`` package and
    raises when it is missing; this framework's interchange format is
    StableHLO (``paddle.jit.save(layer, path)``, batch-polymorphic,
    loadable by ``paddle.inference``), and ONNX conversion is a documented
    non-goal (README).
    """
    raise NotImplementedError(
        "ONNX export is a documented non-goal of paddle_tpu (see README "
        "Non-goals): the XLA-native interchange format is StableHLO. "
        "Use paddle.jit.save(layer, path) to export batch-polymorphic "
        "StableHLO loadable by paddle.inference; convert externally if ONNX "
        "is required (the reference likewise needs the external paddle2onnx "
        "package)."
    )
