"""ONNX export (ref python/paddle/onnx/export.py export(), which delegates to
the external paddle2onnx package).

TPU-native: the portable interchange format for XLA programs is StableHLO —
`paddle.jit.save` / `paddle.inference` already export it, and it is what TPU
serving consumes.  ONNX export is provided for CPU/GPU interop when the
`onnx` package is installed: the traced jaxpr is converted via jax's
tf-less exporters if available, else we raise with guidance (the reference
likewise raises unless paddle2onnx is installed).
"""
from __future__ import annotations

import os

__all__ = []


def export(layer, path: str, input_spec=None, opset_version: int = 13,
           **configs):
    """Export a Layer to ``<path>.onnx`` (ref export.py export()).

    Requires the ``onnx`` package (not bundled, mirroring the reference's
    external paddle2onnx dependency).  For the TPU-native interchange path use
    ``paddle.jit.save`` (StableHLO), which needs no extra packages.
    """
    try:
        import onnx  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "paddle.onnx.export requires the 'onnx' package, which is not "
            "installed in this environment (the reference has the same "
            "external dependency on paddle2onnx). For TPU-native model "
            "interchange use paddle.jit.save(layer, path) — it exports "
            "batch-polymorphic StableHLO loadable by paddle.inference."
        ) from e

    from ..jit import _trace_to_exported  # jaxpr -> jax.export Exported

    exported, _params = _trace_to_exported(layer, input_spec or [])
    # With onnx available, go through jax's StableHLO -> ONNX conversion if
    # present in the environment; otherwise surface the gap explicitly.
    try:
        from jax.experimental import export_onnx  # not in all jax versions
    except ImportError as e:
        raise NotImplementedError(
            "this jax build has no StableHLO->ONNX converter; use "
            "paddle.jit.save for StableHLO export instead") from e
    model = export_onnx.convert(exported, opset_version=opset_version)
    out = path if path.endswith(".onnx") else path + ".onnx"
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    onnx.save(model, out)
    return out
