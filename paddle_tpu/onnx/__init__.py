"""paddle.onnx parity (ref python/paddle/onnx/export.py)."""
from .export import export  # noqa: F401

__all__ = ["export"]
