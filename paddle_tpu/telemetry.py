"""Whole-stack telemetry — metrics registry, span tracing, flight
recorders, and goodput accounting shared by serving AND training.

Promoted from ``inference/telemetry.py`` (the same promotion ``faults.py``
got when the training tier started injecting faults): PR 7 built the
serving substrate — :class:`MetricsRegistry`, :class:`SpanTracer`,
:class:`FlightRecorder` — and the training half of the repo
(``parallel/engine.py``, ``distributed/train_checkpoint.py``, the elastic
chaos harness) stayed a black box. Both tiers now live here;
``paddle_tpu.inference.telemetry`` re-exports everything, so serving
imports are unchanged.

Serving tier (facade: :class:`ServingTelemetry`, held by
``GenerationServer(telemetry=...)``):

- :class:`MetricsRegistry` — counters / gauges / bounded-bucket
  histograms, labeled (tenant, priority, phase, ...), with JSON and
  Prometheus-text exposition. The registry is ALWAYS live on a server
  (its counters are the single source of truth behind
  ``sched_metrics()``); only spans and the flight recorder gate on
  ``enabled``.
- :class:`SpanTracer` — per-request lifecycle spans (queued → prefill
  chunks → decode/spec windows → preempt/swap-out/swap-in → complete/
  cancel/expire) dumped as chrome-trace JSON, one timeline row per
  request. Completed spans are also forwarded to the host profiler's
  event recorder whenever a ``paddle_tpu.profiler.Profiler`` is
  recording, so serving timelines land in the SAME ``export()`` trace as
  the op-level ``RecordEvent`` spans.
- :class:`FlightRecorder` — fixed-size ring of per-tick records (batch
  occupancy, program key, block/swap deltas, preemptions, spec
  acceptance, backend-compile deltas, wall time) with :func:`watchdog`
  post-mortem analysis: preemption storms, pool-pressure stalls, and
  steady-state recompiles.

Training tier (facade: :class:`TrainTelemetry`, held by
``ParallelEngine(telemetry=...)`` and shared with
``TrainCheckpointer`` / ``CheckpointableDataFeed`` /
``ElasticChaosHarness``):

- per-step spans on reserved timeline row :data:`TRAIN_RID` — data_feed,
  host_to_device, dispatch, device_wait (the engine blocks on the loss
  when telemetry is attached), ckpt_save / ckpt_restore — on the SAME
  chrome-trace timeline as serving request spans when the tracer is
  shared (``TrainTelemetry(tracer=serving_tel.tracer)``);
- step-time / tokens-per-second / MFU gauges (MFU uses the 6·N·T
  dense-transformer FLOP estimate against ``peak_flops``, default from
  ``PT_PEAK_TFLOPS``);
- a training :class:`FlightRecorder` ring analysed by
  :func:`train_watchdog`: steady-state recompiles (shape wobble across
  steps), step-time regressions, data-feed stalls, and
  checkpoint-backoff storms;
- :class:`GoodputLedger` — productive step wall time vs. total wall
  time. A step index run twice (replay after an elastic restore) books
  the first run as lost work; recovery wall time (kill detection →
  rendezvous → restore) is booked by the chaos harness. The resulting
  ``train_goodput_ratio`` gauge is exactly 1.0 on a fault-free run and
  < 1.0 whenever a seeded kill forced replay — the chaos gate pins both.

Overhead contract: telemetry is HOST-side only — nothing in this module
may be called from inside a jitted program body (graftlint GL010
enforces this statically, across the whole package), and the disabled
path is allocation-free: ``enabled=False`` installs shared no-op
tracer/flight singletons whose methods take ``*args`` and return
immediately. The engine goes one further: ``telemetry=None`` (the
default) skips even the timestamp reads and the per-step
``block_until_ready``.

Determinism: registry and tracer take an injectable ``clock`` (default
``time.perf_counter`` — the same base the profiler's ``RecordEvent``
uses, so forwarded spans share its timeline), mirroring
``Scheduler(clock=)``.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "SpanTracer", "FlightRecorder", "ServingTelemetry", "watchdog",
           "DEFAULT_BUCKETS", "TRAIN_RID", "GoodputLedger",
           "TrainTelemetry", "train_watchdog"]

# generic latency-ish bucket ladder (seconds); histograms can override
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# reserved SpanTracer rid for the training loop: one timeline row, below
# every request row (thread_sort_index orders by rid), so a trace from a
# process that both trains and serves shows the step loop and the
# request lifecycles on one timeline.
TRAIN_RID = -1


def _lkey(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    """Canonical hashable key for a label set (values coerced to str —
    Prometheus labels are strings, and it keeps 1 vs 1.0 vs "1" stable)."""
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _matches(key: Tuple[Tuple[str, str], ...],
             where: Optional[Dict[str, Any]]) -> bool:
    if not where:
        return True
    d = dict(key)
    return all(d.get(k) == str(v) for k, v in where.items())


class Counter:
    """Monotonic counter over label sets. ``inc()`` with no labels uses
    the empty label set; ``total()`` sums every set."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._vals: Dict[Tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        k = _lkey(labels)
        self._vals[k] = self._vals.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        return self._vals.get(_lkey(labels), 0.0)

    def total(self, where: Optional[Dict[str, Any]] = None) -> float:
        return sum(v for k, v in self._vals.items() if _matches(k, where))

    def series(self) -> List[Tuple[Tuple, float]]:
        return sorted(self._vals.items())


class Gauge(Counter):
    """Point-in-time value over label sets (``set`` replaces; ``inc``
    still works for up/down adjustments)."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        self._vals[_lkey(labels)] = float(value)


class Histogram:
    """Bounded-bucket histogram with exact-percentile support.

    Each label set keeps cumulative-style bucket counts (le semantics),
    a running sum/count, AND the raw samples up to ``max_samples`` —
    percentiles come from ``np.percentile`` over the raw samples (exact,
    matching the pre-registry ad-hoc lists) and fall back to linear
    bucket interpolation once a series overflows its sample bound (the
    bound is what keeps a week-long server from hoarding memory).
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
                 max_samples: int = 8192):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(f"buckets must be a sorted non-empty sequence, "
                             f"got {buckets!r}")
        self.name = name
        self.help = help
        self.buckets = tuple(float(b) for b in buckets)
        self.max_samples = int(max_samples)
        self._series: Dict[Tuple, Dict[str, Any]] = {}

    def _row(self, k: Tuple) -> Dict[str, Any]:
        row = self._series.get(k)
        if row is None:
            row = {"counts": [0] * (len(self.buckets) + 1), "sum": 0.0,
                   "count": 0, "samples": [], "clipped": False}
            self._series[k] = row
        return row

    def observe(self, value: float, **labels) -> None:
        v = float(value)
        row = self._row(_lkey(labels))
        i = int(np.searchsorted(self.buckets, v, side="left"))
        row["counts"][i] += 1
        row["sum"] += v
        row["count"] += 1
        if len(row["samples"]) < self.max_samples:
            row["samples"].append(v)
        else:
            row["clipped"] = True

    # ------------------------------------------------------------- queries
    def _rows(self, where: Optional[Dict[str, Any]]):
        return [(k, r) for k, r in self._series.items() if _matches(k, where)]

    def count(self, where: Optional[Dict[str, Any]] = None) -> int:
        return sum(r["count"] for _, r in self._rows(where))

    def sum(self, where: Optional[Dict[str, Any]] = None) -> float:
        return sum(r["sum"] for _, r in self._rows(where))

    def samples(self, where: Optional[Dict[str, Any]] = None) -> List[float]:
        out: List[float] = []
        for _, r in self._rows(where):
            out.extend(r["samples"])
        return out

    def label_values(self, key: str) -> List[str]:
        out = {dict(k)[key] for k in self._series if key in dict(k)}
        return sorted(out)

    def percentile(self, q: float,
                   where: Optional[Dict[str, Any]] = None) -> Optional[float]:
        """q in [0, 100]. Exact (np.percentile over raw samples) unless a
        matching series clipped its sample list — then bucket-interpolated."""
        rows = self._rows(where)
        if not rows or not any(r["count"] for _, r in rows):
            return None
        if not any(r["clipped"] for _, r in rows):
            return float(np.percentile(
                np.concatenate([np.asarray(r["samples"]) for _, r in rows
                                if r["samples"]]), q))
        # merged bucket counts → linear interpolation inside the bucket
        counts = np.sum([r["counts"] for _, r in rows], axis=0)
        total = int(counts.sum())
        target = (q / 100.0) * (total - 1) if total > 1 else 0.0
        edges = (0.0,) + self.buckets
        cum = 0
        for i, c in enumerate(counts):
            if cum + c > target:
                lo = edges[i]
                hi = self.buckets[i] if i < len(self.buckets) \
                    else self.buckets[-1]
                frac = (target - cum) / c if c else 0.0
                return float(lo + (hi - lo) * frac)
            cum += c
        return float(self.buckets[-1])


class MetricsRegistry:
    """Get-or-create instrument store with JSON / Prometheus exposition.

    ``clock`` is injectable for deterministic tests and feeds
    :meth:`timer`. Instruments are keyed by name; asking for an existing
    name with a different kind raises (one name, one meaning).
    """

    def __init__(self, clock: Callable[[], float] = time.perf_counter,
                 max_samples: int = 8192):
        self.clock = clock
        self.max_samples = int(max_samples)
        self._instruments: Dict[str, Any] = {}

    def _get(self, cls, name: str, help: str, **kw):
        inst = self._instruments.get(name)
        if inst is None:
            inst = cls(name, help, **kw)
            self._instruments[name] = inst
        elif not isinstance(inst, cls) or inst.kind != cls.kind:
            raise TypeError(f"metric {name!r} already registered as "
                            f"{inst.kind}, requested {cls.kind}")
        return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Tuple[float, ...]] = None) -> Histogram:
        return self._get(Histogram, name, help,
                         buckets=buckets or DEFAULT_BUCKETS,
                         max_samples=self.max_samples)

    def get(self, name: str):
        return self._instruments.get(name)

    def names(self) -> List[str]:
        return sorted(self._instruments)

    def percentile(self, name: str, q: float,
                   where: Optional[Dict[str, Any]] = None) -> Optional[float]:
        h = self._instruments.get(name)
        return h.percentile(q, where) if isinstance(h, Histogram) else None

    def timer(self, name: str, **labels):
        """Context manager: observe the block's wall duration (via the
        injected clock) into histogram ``name``."""
        reg = self

        class _Timer:
            def __enter__(self):
                self.t0 = reg.clock()
                return self

            def __exit__(self, *exc):
                reg.histogram(name).observe(reg.clock() - self.t0, **labels)
                return False

        return _Timer()

    # ----------------------------------------------------------- exposition
    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"counters": {}, "gauges": {},
                               "histograms": {}}
        for name in self.names():
            inst = self._instruments[name]
            if isinstance(inst, Histogram):
                series = []
                for k, r in sorted(inst._series.items()):
                    row = {"labels": dict(k), "count": r["count"],
                           "sum": r["sum"], "clipped": r["clipped"],
                           "bucket_counts": list(r["counts"])}
                    if r["count"]:
                        row["p50"] = inst.percentile(50.0, dict(k))
                        row["p95"] = inst.percentile(95.0, dict(k))
                    series.append(row)
                entry: Dict[str, Any] = {"help": inst.help,
                                         "buckets": list(inst.buckets),
                                         "series": series}
                if inst.count():
                    entry["p50"] = inst.percentile(50.0)
                    entry["p95"] = inst.percentile(95.0)
                    entry["p99"] = inst.percentile(99.0)
                    entry["count"] = inst.count()
                    entry["sum"] = inst.sum()
                out["histograms"][name] = entry
            else:
                out[inst.kind + "s"][name] = {
                    "help": inst.help,
                    "series": [{"labels": dict(k), "value": v}
                               for k, v in inst.series()]}
        return out

    @staticmethod
    def _fmt_labels(key: Tuple[Tuple[str, str], ...],
                    extra: Optional[Tuple[Tuple[str, str], ...]] = None) \
            -> str:
        items = list(key) + list(extra or ())
        if not items:
            return ""
        def esc(v: str) -> str:
            return v.replace("\\", r"\\").replace('"', r'\"') \
                    .replace("\n", r"\n")
        return "{" + ",".join(f'{k}="{esc(v)}"' for k, v in items) + "}"

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        for name in self.names():
            inst = self._instruments[name]
            if inst.help:
                lines.append(f"# HELP {name} {inst.help}")
            lines.append(f"# TYPE {name} {inst.kind}")
            if isinstance(inst, Histogram):
                for k, r in sorted(inst._series.items()):
                    cum = 0
                    for b, c in zip(inst.buckets, r["counts"]):
                        cum += c
                        lines.append(
                            f"{name}_bucket"
                            f"{self._fmt_labels(k, (('le', repr(b)),))} "
                            f"{cum}")
                    lines.append(
                        f"{name}_bucket"
                        f"{self._fmt_labels(k, (('le', '+Inf'),))} "
                        f"{r['count']}")
                    lines.append(
                        f"{name}_sum{self._fmt_labels(k)} {r['sum']}")
                    lines.append(
                        f"{name}_count{self._fmt_labels(k)} {r['count']}")
            else:
                for k, v in inst.series():
                    lines.append(f"{name}{self._fmt_labels(k)} {v}")
        return "\n".join(lines) + "\n"

    # -------------------------------------------------------------- resets
    def reset_histograms(self) -> None:
        """Clear histogram series (counters/gauges keep their lifetime
        values) — the benchmark calls this after its warmup drain so
        percentiles cover only the measured region."""
        for inst in self._instruments.values():
            if isinstance(inst, Histogram):
                inst._series.clear()

    def reset(self) -> None:
        for inst in self._instruments.values():
            if isinstance(inst, Histogram):
                inst._series.clear()
            else:
                inst._vals.clear()


# --------------------------------------------------------------------------- #
# Span tracing
# --------------------------------------------------------------------------- #


class SpanTracer:
    """Per-request lifecycle spans with chrome-trace export.

    Spans are keyed ``(rid, name)``; at most one span of a given name is
    open per request (``begin`` on an already-open name closes it first —
    the serving lifecycle never legitimately nests a span inside itself).
    ``complete`` records a retroactive span from timestamps the caller
    captured around a compiled call — the decode/verify trip path, where
    one device program advances many requests and per-request begin/end
    would misattribute the shared wall time. The training tier records
    ALL its spans this way, on the reserved :data:`TRAIN_RID` row.
    """

    def __init__(self, clock: Callable[[], float] = time.perf_counter,
                 max_spans: int = 65536):
        self.clock = clock
        self.max_spans = int(max_spans)
        self._open: Dict[Tuple[int, str], Dict[str, Any]] = {}
        self._done: List[Dict[str, Any]] = []
        self._meta: Dict[int, Dict[str, Any]] = {}
        self.dropped = 0

    # ------------------------------------------------------------- recording
    def set_meta(self, rid: int, **meta) -> None:
        self._meta.setdefault(rid, {}).update(meta)

    def begin(self, rid: int, name: str, **args) -> None:
        key = (rid, name)
        if key in self._open:
            self.end(rid, name)
        self._open[key] = {"rid": rid, "name": name, "t0": self.clock(),
                           "args": args}

    def end(self, rid: int, name: str, **args) -> Optional[float]:
        span = self._open.pop((rid, name), None)
        if span is None:
            return None
        t1 = self.clock()
        if args:
            span["args"].update(args)
        return self._finish(span, t1)

    def complete(self, rid: int, name: str, t0: float, t1: float,
                 **args) -> None:
        self._finish({"rid": rid, "name": name, "t0": t0, "args": args}, t1)

    def instant(self, rid: int, name: str, **args) -> None:
        t = self.clock()
        self._finish({"rid": rid, "name": name, "t0": t, "args": args,
                      "instant": True}, t)

    def close(self, rid: int, outcome: Optional[str] = None) -> None:
        """End every open span of ``rid`` (preempt/cancel/complete paths
        may leave e.g. a ``preempted`` span open) and drop an ``outcome``
        marker — span trees stay well-formed on every exit path."""
        for (r, name) in [k for k in self._open if k[0] == rid]:
            self.end(r, name, outcome=outcome)
        if outcome is not None:
            self.instant(rid, outcome)

    def _finish(self, span: Dict[str, Any], t1: float) -> float:
        span["t1"] = t1
        dur = t1 - span["t0"]
        span["dur"] = dur
        if len(self._done) < self.max_spans:
            self._done.append(span)
        else:
            self.dropped += 1
        # forward into the host profiler's recorder when one is recording,
        # so serving spans land next to op-level RecordEvent spans (and
        # device traces) in Profiler.export()
        from . import profiler as _profiler

        rec = _profiler._recorder
        if rec.enabled:
            rec.add(f"serving::{span['name']}", span["t0"], dur,
                    cat="serving", tid=1_000_000 + span["rid"],
                    args=dict(span["args"], rid=span["rid"]) or None)
        return dur

    # --------------------------------------------------------------- queries
    def open_spans(self, rid: int) -> List[str]:
        return sorted(name for (r, name) in self._open if r == rid)

    def spans(self, rid: Optional[int] = None) -> List[Dict[str, Any]]:
        out = [s for s in self._done if rid is None or s["rid"] == rid]
        return sorted(out, key=lambda s: (s["t0"], s["rid"]))

    def rids(self) -> List[int]:
        return sorted({s["rid"] for s in self._done})

    # ---------------------------------------------------------- chrome trace
    def chrome_events(self) -> List[Dict[str, Any]]:
        """Chrome-trace events: one ``tid`` (= timeline row) per request,
        named via thread_name metadata — a preempted request's swap-out /
        swap-in and its decode windows share one row. A row whose meta
        carries ``name`` (the train loop's :data:`TRAIN_RID` row) uses it
        as the label instead of ``req <rid>``."""
        events: List[Dict[str, Any]] = []
        for rid in self.rids():
            meta = self._meta.get(rid, {})
            label = meta.get("name") or f"req {rid}"
            if meta.get("tenant"):
                label += f" [{meta['tenant']}]"
            events.append({"ph": "M", "name": "thread_name", "pid": 0,
                           "tid": rid, "args": {"name": label}})
            events.append({"ph": "M", "name": "thread_sort_index", "pid": 0,
                           "tid": rid, "args": {"sort_index": rid}})
        for s in self.spans():
            ev = {"name": s["name"], "pid": 0, "tid": s["rid"],
                  "ts": s["t0"] * 1e6, "cat": "serving",
                  "args": dict(s["args"], rid=s["rid"])}
            if s.get("instant"):
                ev.update({"ph": "i", "s": "t"})
            else:
                ev.update({"ph": "X", "dur": s["dur"] * 1e6})
            events.append(ev)
        return events

    def export_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump({"traceEvents": self.chrome_events(),
                       "displayTimeUnit": "ms"}, f)
        return path

    def reset(self) -> None:
        self._open.clear()
        self._done.clear()
        self._meta.clear()
        self.dropped = 0


# --------------------------------------------------------------------------- #
# Flight recorder + watchdogs
# --------------------------------------------------------------------------- #


class FlightRecorder:
    """Fixed-size ring of per-tick records for post-mortem debugging.

    ``record(**fields)`` stamps a monotonically increasing ``seq``;
    ``dump()`` returns surviving records oldest → newest. The ring never
    grows — a wedged server's last N ticks are always reconstructable at
    O(size) memory.

    ``warm_progs`` carries program keys across :meth:`reset` boundaries:
    ``reset(fold_warm=True)`` folds the surviving records' ``prog`` keys
    in before clearing, so a post-reset :func:`watchdog` pass knows which
    programs were already compiled pre-boundary (the benchmark's warmup
    drain) — a recompile of one of those is a finding even on the first
    post-boundary tick, and a warmup compile can never resurface as a
    post-warmup finding.
    """

    def __init__(self, size: int = 256):
        if size < 1:
            raise ValueError(f"size must be >= 1, got {size}")
        self.size = int(size)
        self._ring: List[Optional[Dict[str, Any]]] = [None] * self.size
        self._n = 0
        self.warm_progs: set = set()

    def record(self, **fields) -> None:
        fields["seq"] = self._n
        self._ring[self._n % self.size] = fields
        self._n += 1

    @property
    def total(self) -> int:
        """Ticks recorded over the recorder's lifetime (≥ ``len(self)``)."""
        return self._n

    def __len__(self) -> int:
        return min(self._n, self.size)

    def dump(self) -> List[Dict[str, Any]]:
        if self._n <= self.size:
            return [r for r in self._ring[:self._n]]
        head = self._n % self.size
        return self._ring[head:] + self._ring[:head]

    def reset(self, fold_warm: bool = False) -> None:
        if fold_warm:
            for r in self.dump():
                prog = r.get("prog")
                if prog is not None:
                    self.warm_progs.add(prog)
        self._ring = [None] * self.size
        self._n = 0


def _sliding_worst(recs: List[Dict[str, Any]], field: str, window: int,
                   pred=None) -> Tuple[int, int]:
    """Worst ``window``-wide sliding sum of ``field`` (or of ``pred``
    truthiness) over the records; returns (best_sum, index_of_window_end)."""
    vals = [int(bool(pred(r)) if pred else r.get(field, 0)) for r in recs]
    best, best_i = 0, 0
    run = 0
    for i, v in enumerate(vals):
        run += v
        if i >= window:
            run -= vals[i - window]
        if run > best:
            best, best_i = run, i
    return best, best_i


def watchdog(records: Iterable[Dict[str, Any]], *,
             preempt_window: int = 32, preempt_storm: int = 8,
             stall_window: int = 32, stall_frac: float = 0.5,
             thrash_window: int = 32, thrash_blocks: int = 16,
             warmup_ticks: int = 8,
             warm_progs: Optional[Iterable[str]] = None) \
        -> List[Dict[str, Any]]:
    """SLO analysis over a flight-recorder dump. Returns findings:

    - ``preemption_storm``: ≥ ``preempt_storm`` preemptions inside some
      ``preempt_window``-tick window — thrash, not load balancing.
    - ``pool_pressure_stall``: ≥ ``stall_frac`` of some
      ``stall_window``-tick window stalled on block reservation — the
      pool is undersized for the workload (or the host pool refused).
    - ``tier_thrash``: ≥ ``thrash_blocks`` demotions AND ≥
      ``thrash_blocks`` promotions inside the same
      ``thrash_window``-tick window — blocks ping-ponging across the
      HBM↔warm boundary, paying both copies without netting capacity
      (demotion alone is healthy pressure relief; promotion alone is
      healthy cache reuse; BOTH at volume means the watermarks sit on
      top of the working set).
    - ``steady_state_recompile``: a backend compile on a tick whose
      program key was ALREADY seen on an earlier tick (and past
      ``warmup_ticks``) — first use of a new program (gate flip, turbo
      tier) legitimately compiles once; the same program compiling again
      is the recompile-storm bug class ``jit_cache_guard`` exists for.
      ``warm_progs`` pre-seeds the seen set with programs compiled
      before the dump started (``FlightRecorder.warm_progs`` after a
      warmup-boundary reset); a compile on one of THOSE is a finding at
      any index — the ``warmup_ticks`` excusal only covers programs
      making their genuine first appearance inside this dump.

    One finding per kind (the worst/first window), so a gate can assert
    ``not findings`` without counting duplicates.
    """
    recs = list(records)
    findings: List[Dict[str, Any]] = []

    worst, at = _sliding_worst(recs, "preemptions", preempt_window)
    if worst >= preempt_storm:
        findings.append({
            "kind": "preemption_storm",
            "count": worst, "window": preempt_window,
            "seq": recs[at]["seq"],
            "detail": f"{worst} preemptions in {preempt_window} ticks "
                      f"(ending seq {recs[at]['seq']}) — raise the pool "
                      f"budget or lower arrival rate"})

    worst, at = _sliding_worst(recs, "stalls", stall_window,
                               pred=lambda r: r.get("stalls", 0) > 0)
    window = min(stall_window, len(recs)) or 1
    if worst / window >= stall_frac and worst > 0:
        findings.append({
            "kind": "pool_pressure_stall",
            "count": worst, "window": stall_window,
            "seq": recs[at]["seq"],
            "detail": f"{worst}/{window} ticks stalled on block "
                      f"reservation — pool (or host pool) undersized"})

    worst_d, at_d = _sliding_worst(recs, "demotions", thrash_window)
    worst_p, at_p = _sliding_worst(recs, "promotions", thrash_window)
    if worst_d >= thrash_blocks and worst_p >= thrash_blocks:
        at = max(at_d, at_p)
        findings.append({
            "kind": "tier_thrash",
            "demotions": worst_d, "promotions": worst_p,
            "window": thrash_window, "seq": recs[at]["seq"],
            "detail": f"{worst_d} demotions and {worst_p} promotions in "
                      f"{thrash_window} ticks — the warm tier is churning "
                      f"the working set; widen the watermark band "
                      f"(tier_demote_low/high) or raise the pool budget"})

    warm = set(warm_progs) if warm_progs else set()
    seen_progs: set = set(warm)
    bad: List[int] = []
    total = 0
    for i, r in enumerate(recs):
        prog = r.get("prog")
        compiles = int(r.get("recompiles", 0))
        if compiles and prog in seen_progs \
                and (prog in warm or i >= warmup_ticks):
            bad.append(r["seq"])
            total += compiles
        if prog is not None:
            seen_progs.add(prog)
    if bad:
        findings.append({
            "kind": "steady_state_recompile",
            "count": total, "seqs": bad, "seq": bad[0],
            "detail": f"{total} backend compile(s) on already-warm "
                      f"program(s) at tick seq(s) {bad[:8]} — a shape or "
                      f"static-arg wobble; see docs/static_analysis.md "
                      f"(jit-cache guard)"})
    return findings


def train_watchdog(records: Iterable[Dict[str, Any]], *,
                   warmup_steps: int = 3,
                   warm_progs: Optional[Iterable[str]] = None,
                   regress_window: int = 8, regress_factor: float = 1.5,
                   feed_stall_window: int = 16, feed_stall_frac: float = 0.5,
                   backoff_window: int = 32, backoff_storm: int = 3) \
        -> List[Dict[str, Any]]:
    """Post-mortem analysis over a TRAINING flight-recorder dump
    (records from :meth:`TrainTelemetry.record_step`). Findings:

    - ``steady_state_recompile``: same contract as the serving
      :func:`watchdog` — a compile on a step whose program key (batch
      shape signature) was already seen is a shape/static-arg wobble.
    - ``step_time_regression``: the median wall of the last
      ``regress_window`` steps is ≥ ``regress_factor`` × the median of
      the first post-warmup window — the loop got durably slower
      (fragmentation, a competing process, thermal throttle).
    - ``data_feed_stall``: ≥ ``feed_stall_frac`` of some
      ``feed_stall_window``-step window spent longer feeding data than
      stepping — the loop is input-bound, not compute-bound.
    - ``ckpt_backoff_storm``: ≥ ``backoff_storm`` checkpoint-save
      retries inside ``backoff_window`` steps — the store is flapping
      and the retry ladder is eating step time.

    One finding per kind, so gates can assert ``not findings``.
    """
    recs = list(records)
    findings = [f for f in watchdog(recs, warmup_ticks=warmup_steps,
                                    warm_progs=warm_progs)
                if f["kind"] == "steady_state_recompile"]

    walls = [float(r.get("t_wall_s", 0.0)) for r in recs]
    if len(walls) >= warmup_steps + 2 * regress_window:
        base = float(np.median(
            walls[warmup_steps:warmup_steps + regress_window]))
        recent = float(np.median(walls[-regress_window:]))
        if base > 0 and recent >= regress_factor * base:
            findings.append({
                "kind": "step_time_regression",
                "baseline_s": base, "recent_s": recent,
                "factor": recent / base, "seq": recs[-1]["seq"],
                "detail": f"median step time {recent:.4f}s over the last "
                          f"{regress_window} steps vs {base:.4f}s baseline "
                          f"({recent / base:.2f}x) — the loop got durably "
                          f"slower"})

    worst, at = _sliding_worst(
        recs, "data_feed_s", feed_stall_window,
        pred=lambda r: r.get("data_feed_s", 0.0) > r.get("t_wall_s", 0.0))
    window = min(feed_stall_window, len(recs)) or 1
    if worst / window >= feed_stall_frac and worst > 0:
        findings.append({
            "kind": "data_feed_stall",
            "count": worst, "window": feed_stall_window,
            "seq": recs[at]["seq"],
            "detail": f"{worst}/{window} steps spent longer in data_feed "
                      f"than in the step itself — input-bound; widen the "
                      f"feed (prefetch, more workers)"})

    worst, at = _sliding_worst(recs, "ckpt_backoffs", backoff_window)
    if worst >= backoff_storm:
        findings.append({
            "kind": "ckpt_backoff_storm",
            "count": worst, "window": backoff_window,
            "seq": recs[at]["seq"],
            "detail": f"{worst} checkpoint-save retries in "
                      f"{backoff_window} steps — the checkpoint store is "
                      f"flapping; step time is going to backoff sleeps"})
    return findings


# --------------------------------------------------------------------------- #
# No-op twins (the disabled path) + facades
# --------------------------------------------------------------------------- #


class _NullTracer:
    """Allocation-free stand-in: every recording method is a bare
    ``return None``. Query methods return empty containers (fresh lists —
    queries are off the hot path)."""

    __slots__ = ()
    clock = staticmethod(time.perf_counter)
    dropped = 0

    def set_meta(self, *a, **k):
        return None

    def begin(self, *a, **k):
        return None

    def end(self, *a, **k):
        return None

    def complete(self, *a, **k):
        return None

    def instant(self, *a, **k):
        return None

    def close(self, *a, **k):
        return None

    def open_spans(self, rid):
        return []

    def spans(self, rid=None):
        return []

    def rids(self):
        return []

    def chrome_events(self):
        return []

    def export_chrome_trace(self, path):
        with open(path, "w") as f:
            json.dump({"traceEvents": []}, f)
        return path

    def reset(self):
        return None


class _NullFlight:
    __slots__ = ()
    size = 0
    total = 0
    warm_progs: frozenset = frozenset()

    def record(self, *a, **k):
        return None

    def __len__(self):
        return 0

    def dump(self):
        return []

    def reset(self, *a, **k):
        return None


NULL_TRACER = _NullTracer()
NULL_FLIGHT = _NullFlight()


class ServingTelemetry:
    """The facade ``GenerationServer(telemetry=...)`` holds.

    The registry is ALWAYS real — counters behind ``sched_metrics()`` /
    TTFT-TPOT histograms cost host-dict updates and are the single source
    of truth regardless of ``enabled``. ``enabled`` gates the per-request
    span tracer and the per-tick flight recorder (swapped for shared
    no-op singletons when off, so the disabled hot path allocates
    nothing). Pass ``tracer=`` to share a timeline with another facade
    (e.g. a :class:`TrainTelemetry` in the same process — one chrome
    trace shows training steps and request lifecycles together).
    """

    def __init__(self, enabled: bool = True,
                 clock: Callable[[], float] = time.perf_counter,
                 registry: Optional[MetricsRegistry] = None,
                 flight_size: int = 256, max_samples: int = 8192,
                 max_spans: int = 65536,
                 tracer: Optional[SpanTracer] = None):
        self.clock = clock
        self.registry = registry if registry is not None else \
            MetricsRegistry(clock=clock, max_samples=max_samples)
        self.enabled = bool(enabled)
        if self.enabled:
            self.tracer: Any = tracer if tracer is not None else \
                SpanTracer(clock=clock, max_spans=max_spans)
            self.flight: Any = FlightRecorder(flight_size)
        else:
            self.tracer = NULL_TRACER
            self.flight = NULL_FLIGHT

    def watchdog(self, **kw) -> List[Dict[str, Any]]:
        kw.setdefault("warm_progs", self.flight.warm_progs)
        return watchdog(self.flight.dump(), **kw)

    def export_chrome_trace(self, path: str) -> str:
        return self.tracer.export_chrome_trace(path)

    def snapshot(self) -> Dict[str, Any]:
        """Registry JSON + watchdog findings (one post-mortem blob)."""
        return {"metrics": self.registry.to_json(),
                "watchdog": self.watchdog() if self.enabled else [],
                "flight_ticks": self.flight.total,
                "spans_dropped": getattr(self.tracer, "dropped", 0)}

    def reset(self, counters: bool = False) -> None:
        """Clear histograms, spans, and the flight ring (benchmark
        warmup boundary); surviving flight records' program keys fold
        into ``flight.warm_progs`` first, so the post-boundary watchdog
        neither excuses a warm program's recompile nor resurfaces a
        warmup compile as a finding. ``counters=True`` also zeroes
        counters/gauges — NOT the default, because ``sched_metrics()``
        counters are lifetime semantics."""
        if counters:
            self.registry.reset()
        else:
            self.registry.reset_histograms()
        self.tracer.reset()
        self.flight.reset(fold_warm=True)


# --------------------------------------------------------------------------- #
# Training tier: goodput ledger + TrainTelemetry facade
# --------------------------------------------------------------------------- #


class GoodputLedger:
    """Productive vs. total training wall time.

    ``step(index, wall_s)`` books one optimizer step; running the SAME
    index twice (replay after an elastic restore rolled the step counter
    back) books the earlier run's wall as lost work — only the last run
    of each index is productive. ``recovery(wall_s)`` books
    non-stepping wall the chaos harness attributes to a restart (kill
    detection → rendezvous → restore). The ratio is EXACTLY 1.0 on a
    fault-free run: no replayed index, no recovery segment, so
    productive == total with no float residue.
    """

    def __init__(self):
        self._step_wall: Dict[int, float] = {}
        self.total_s = 0.0
        self.lost_s = 0.0
        self.lost_steps = 0
        self.recovery_s = 0.0
        self.recoveries = 0

    def step(self, index: int, wall_s: float) -> None:
        prev = self._step_wall.get(index)
        if prev is not None:
            self.lost_steps += 1
            self.lost_s += prev
        self._step_wall[int(index)] = float(wall_s)
        self.total_s += float(wall_s)

    def recovery(self, wall_s: float) -> None:
        self.recoveries += 1
        self.recovery_s += float(wall_s)
        self.total_s += float(wall_s)

    @property
    def productive_s(self) -> float:
        return self.total_s - self.lost_s - self.recovery_s

    @property
    def steps(self) -> int:
        return len(self._step_wall)

    def ratio(self) -> float:
        if self.total_s <= 0.0:
            return 1.0
        if not self.lost_s and not self.recovery_s:
            return 1.0
        return self.productive_s / self.total_s

    def snapshot(self) -> Dict[str, Any]:
        return {"ratio": self.ratio(), "total_s": self.total_s,
                "productive_s": self.productive_s, "lost_s": self.lost_s,
                "lost_steps": self.lost_steps,
                "recovery_s": self.recovery_s,
                "recoveries": self.recoveries, "steps": self.steps}


class TrainTelemetry:
    """The facade ``ParallelEngine(telemetry=...)`` holds, shared with
    ``TrainCheckpointer(telemetry=)``, ``CheckpointableDataFeed`` and
    ``ElasticChaosHarness`` so one object accumulates the whole loop.

    Mirrors :class:`ServingTelemetry`: the registry is always real,
    ``enabled`` swaps tracer/flight for the shared null singletons. The
    engine itself applies a stronger gate — ``telemetry=None`` (its
    default) skips timestamp reads AND the per-step
    ``jax.block_until_ready`` that the ``device_wait`` span needs, so
    the un-instrumented hot path is byte-identical to before.

    ``peak_flops`` feeds the MFU gauge via the dense-transformer
    estimate ``6 · model_params · tokens`` per step; it defaults from
    ``PT_PEAK_TFLOPS`` (TFLOP/s) and the gauge is skipped when unset.
    ``model_params`` is stamped by the engine on the first recorded
    step. Pass ``tracer=`` to share a :class:`SpanTracer` with a
    :class:`ServingTelemetry` — training spans land on the reserved
    :data:`TRAIN_RID` row of the same chrome-trace timeline.
    """

    def __init__(self, enabled: bool = True,
                 clock: Callable[[], float] = time.perf_counter,
                 registry: Optional[MetricsRegistry] = None,
                 flight_size: int = 512, max_samples: int = 8192,
                 max_spans: int = 65536,
                 tracer: Optional[SpanTracer] = None,
                 peak_flops: Optional[float] = None):
        self.clock = clock
        self.registry = registry if registry is not None else \
            MetricsRegistry(clock=clock, max_samples=max_samples)
        self.enabled = bool(enabled)
        if self.enabled:
            self.tracer: Any = tracer if tracer is not None else \
                SpanTracer(clock=clock, max_spans=max_spans)
            self.flight: Any = FlightRecorder(flight_size)
            self.tracer.set_meta(TRAIN_RID, name="train loop")
        else:
            self.tracer = NULL_TRACER
            self.flight = NULL_FLIGHT
        self.goodput = GoodputLedger()
        self.model_params = 0
        if peak_flops is None:
            peak_flops = float(os.environ.get("PT_PEAK_TFLOPS", "0")) * 1e12
        self.peak_flops = float(peak_flops)
        self._pending_feed_s = 0.0
        self._pending_ckpt_backoffs = 0
        # per-step instruments resolved once — record_step rides the train
        # hot path and registry get-or-create per call is measurable on
        # small models; reset() clears instruments in place, so cached
        # references stay valid across warmup-boundary resets
        r = self.registry
        self._h_step = r.histogram(
            "train_step_time_s", "wall per optimizer step (feed excluded)")
        self._c_steps = r.counter("train_steps", "optimizer steps recorded")
        self._c_tokens = r.counter("train_tokens_total", "tokens consumed")
        self._g_tps = r.gauge("train_tokens_per_s",
                              "throughput of the last recorded step")
        self._g_mfu = r.gauge("train_mfu",
                              "model FLOP utilization (6·N·T estimate)")
        self._g_goodput = r.gauge(
            "train_goodput_ratio",
            "productive step wall / total wall (1.0 = fault-free)")

    # -------------------------------------------------------------- hooks
    def record_data_feed(self, t0: float, t1: float, **args) -> None:
        """CheckpointableDataFeed hook: one ``data_feed`` span per batch;
        the duration also folds into the NEXT step's flight record so
        :func:`train_watchdog` can spot input-bound windows."""
        self.tracer.complete(TRAIN_RID, "data_feed", t0, t1, **args)
        self.registry.histogram(
            "train_data_feed_s", "host data-feed wall per batch") \
            .observe(t1 - t0)
        self._pending_feed_s += (t1 - t0)

    def record_ckpt(self, name: str, t0: float, t1: float, **args) -> None:
        """TrainCheckpointer hook: ``name`` is ``ckpt_save`` or
        ``ckpt_restore``; spans share the train timeline row."""
        self.tracer.complete(TRAIN_RID, name, t0, t1, **args)
        self.registry.histogram(
            f"train_{name}_s", f"{name} wall (synchronous portion)") \
            .observe(t1 - t0)

    def note_ckpt_backoff(self, **args) -> None:
        """TrainCheckpointer retry hook: counts toward the next flight
        record so ``ckpt_backoff_storm`` is detectable from the ring."""
        self._pending_ckpt_backoffs += 1
        self.tracer.instant(TRAIN_RID, "ckpt_backoff", **args)

    def record_step(self, *, step: int, prog: Optional[str], tokens: int,
                    t0: float, t_h2d: float, t_dispatch: float,
                    t_wait: float, compiles: int = 0) -> None:
        """Engine hook: one optimizer step's phase timestamps. Emits the
        nested spans, the step gauges/histograms, the flight record, and
        the goodput booking (replayed ``step`` indices become lost work)."""
        wall = t_wait - t0
        tr = self.tracer
        tr.complete(TRAIN_RID, "train_step", t0, t_wait,
                    step=step, tokens=tokens)
        tr.complete(TRAIN_RID, "host_to_device", t0, t_h2d, step=step)
        tr.complete(TRAIN_RID, "dispatch", t_h2d, t_dispatch, step=step)
        tr.complete(TRAIN_RID, "device_wait", t_dispatch, t_wait, step=step)

        self._h_step.observe(wall)
        self._c_steps.inc()
        self._c_tokens.inc(tokens)
        if wall > 0:
            self._g_tps.set(tokens / wall)
            if self.peak_flops and self.model_params:
                mfu = (6.0 * self.model_params * tokens / wall) \
                    / self.peak_flops
                self._g_mfu.set(mfu)

        feed_s = self._pending_feed_s
        self._pending_feed_s = 0.0
        backoffs = self._pending_ckpt_backoffs
        self._pending_ckpt_backoffs = 0
        self.flight.record(step=step, prog=prog, t_wall_s=wall,
                           h2d_s=t_h2d - t0, dispatch_s=t_dispatch - t_h2d,
                           wait_s=t_wait - t_dispatch, data_feed_s=feed_s,
                           tokens=tokens, recompiles=compiles,
                           ckpt_backoffs=backoffs)

        self.goodput.step(step, wall)
        self._g_goodput.set(self.goodput.ratio())

    def record_recovery(self, t0: float, t1: float, **args) -> None:
        """ElasticChaosHarness hook: one restart's non-stepping wall
        (kill detection → rendezvous → restore), booked against goodput."""
        self.tracer.complete(TRAIN_RID, "recovery", t0, t1, **args)
        self.goodput.recovery(t1 - t0)
        r = self.registry
        r.counter("train_recoveries", "elastic restarts recovered").inc()
        r.histogram("train_recovery_s", "restart recovery wall") \
            .observe(t1 - t0)
        self._g_goodput.set(self.goodput.ratio())

    # ------------------------------------------------------------ queries
    def watchdog(self, **kw) -> List[Dict[str, Any]]:
        kw.setdefault("warm_progs", self.flight.warm_progs)
        return train_watchdog(self.flight.dump(), **kw)

    def export_chrome_trace(self, path: str) -> str:
        return self.tracer.export_chrome_trace(path)

    def snapshot(self) -> Dict[str, Any]:
        return {"metrics": self.registry.to_json(),
                "watchdog": self.watchdog() if self.enabled else [],
                "goodput": self.goodput.snapshot(),
                "flight_ticks": self.flight.total,
                "spans_dropped": getattr(self.tracer, "dropped", 0)}

    def reset(self, counters: bool = False) -> None:
        """Warmup-boundary reset, mirroring
        :meth:`ServingTelemetry.reset` (warm program keys fold into the
        flight ring). The goodput ledger also restarts — goodput is a
        per-measured-run statistic."""
        if counters:
            self.registry.reset()
        else:
            self.registry.reset_histograms()
        self.tracer.reset()
        self.flight.reset(fold_warm=True)
        self.goodput = GoodputLedger()
        self._pending_feed_s = 0.0
        self._pending_ckpt_backoffs = 0
