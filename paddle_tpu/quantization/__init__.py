"""Quantization (ref: python/paddle/quantization/ QAT/PTQ + nn/quant/).

TPU-native: int8 is MXU-native; fake-quant ops use the straight-through
estimator, PTQ observes abs-max ranges. The compiled path lowers fake-quant
to real int8 dots where XLA supports it.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op
from ..nn.layer_base import Layer


def quantize_absmax(x, bits=8, axis=None):
    """Symmetric abs-max quantization → (q_int, scale)."""

    def f(v):
        qmax = 2.0 ** (bits - 1) - 1
        amax = jnp.max(jnp.abs(v), axis=axis, keepdims=axis is not None)
        scale = jnp.maximum(amax, 1e-8) / qmax
        q = jnp.clip(jnp.round(v / scale), -qmax - 1, qmax).astype(jnp.int8)
        return q, scale.astype(jnp.float32)

    return apply_op(f, x)


def dequantize(q, scale):
    return apply_op(lambda qq, s: qq.astype(jnp.float32) * s, q, scale)


def fake_quant(x, bits=8, axis=None):
    """Quantize-dequantize with straight-through gradient (QAT core op,
    ref fake_quantize_op)."""

    @jax.custom_vjp
    def _fq(v):
        qmax = 2.0 ** (bits - 1) - 1
        amax = jnp.max(jnp.abs(v), axis=axis, keepdims=axis is not None)
        scale = jnp.maximum(amax, 1e-8) / qmax
        return jnp.clip(jnp.round(v / scale), -qmax - 1, qmax) * scale

    def _fwd(v):
        return _fq(v), None

    def _bwd(res, g):
        return (g,)  # STE

    _fq.defvjp(_fwd, _bwd)
    return apply_op(_fq, x)


class FakeQuanterWithAbsMax(Layer):
    def __init__(self, bits=8, axis=None, name=None):
        super().__init__()
        self.bits = bits
        self.axis = axis

    def forward(self, x):
        return fake_quant(x, self.bits, self.axis)


class QuantedLinear(Layer):
    """Linear with weight+activation fake-quant (QAT wrapper,
    ref nn/quant/ QuantizedLinear)."""

    def __init__(self, linear, bits=8):
        super().__init__()
        self.inner = linear
        self.bits = bits

    def forward(self, x):
        from ..nn import functional as F

        wq = fake_quant(self.inner.weight, self.bits, axis=None)
        xq = fake_quant(x, self.bits)
        return F.linear(xq, wq, self.inner.bias)


class QAT:
    """Quantization-aware training transform (ref quantization/qat.py).
    Accepts a QuantConfig (reference API) or a simple {"bits": n} dict."""

    def __init__(self, config=None):
        self.config = config if config is not None else {"bits": 8}

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        if not inplace:
            import copy

            if not isinstance(self.config, dict):
                _pin_layer_rules(self.config, model)
            model = copy.deepcopy(model)
        if not isinstance(self.config, dict):
            return QATv2(self.config).quantize(model, inplace=True)
        from ..nn.layer.common import Linear

        bits = self.config.get("bits", 8)
        for name, sub in list(model._sub_layers.items()):
            if isinstance(sub, Linear):
                model._sub_layers[name] = QuantedLinear(sub, bits)
            else:
                self.quantize(sub, inplace=True)
        return model


class PTQ:
    """Post-training quantization: observe abs-max over calibration data
    (ref quantization/ptq.py)."""

    def __init__(self, config: Optional[dict] = None):
        self.config = config or {"bits": 8}
        self.ranges: Dict[str, float] = {}

    def observe(self, model: Layer, data_iter, n_batches: int = 8):
        hooks = []
        ranges = self.ranges

        def make_hook(name):
            def hook(layer, inputs, output):
                val = float(jnp.max(jnp.abs(to_array(output))))
                ranges[name] = max(ranges.get(name, 0.0), val)

            return hook

        for name, sub in model.named_sublayers(include_self=False):
            hooks.append(sub.register_forward_post_hook(make_hook(name)))
        from ..framework.core import no_grad_ctx

        with no_grad_ctx():
            for i, batch in enumerate(data_iter):
                if i >= n_batches:
                    break
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                model(x)
        for h in hooks:
            h.remove()
        return self.ranges

    def quantize_weights(self, model: Layer) -> Dict[str, tuple]:
        out = {}
        for name, p in model.named_parameters():
            if p.ndim >= 2:
                q, s = quantize_absmax(p, self.config.get("bits", 8))
                out[name] = (q, s)
        return out


# --------------------------------------------------------------------------
# Reference-shaped config/quanter architecture (ref quantization/config.py,
# base_quanter.py, factory.py, quanters/abs_max.py)

class BaseQuanter(Layer):
    """A quanter is a Layer that simulates quantization in forward and
    exposes its scales (ref base_quanter.py)."""

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return None


class QuanterFactory:
    """Partial holding quanter kwargs; instantiated per wrapped layer
    (ref factory.py ObserverFactory/QuanterFactory)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def _get_class(self):
        raise NotImplementedError

    def _instance(self, layer):
        return self._get_class()(layer, **self._kwargs)


def quanter(class_name):
    """Decorator: register a BaseQuanter subclass and synthesize its factory
    under ``class_name`` (ref factory.py:quanter)."""

    def wrapper(cls):
        class _Factory(QuanterFactory):
            def _get_class(self):
                return cls

        _Factory.__name__ = class_name
        import sys

        setattr(sys.modules[cls.__module__], class_name, _Factory)
        return cls

    return wrapper


class FakeQuanterWithAbsMaxObserverLayer(BaseQuanter):
    """Moving-average abs-max fake quanter (ref quanters/abs_max.py:94):
        state = rate * state + 1;  accum = rate * accum + max|x|
        scale = accum / state;  out = round(x/scale*range)*scale/range (STE)
    """

    def __init__(self, layer=None, name=None, moving_rate=0.9, bit_length=8,
                 dtype="float32"):
        super().__init__()
        self._moving_rate = moving_rate
        self._bit_length = bit_length
        self.register_buffer("_scale", Tensor(jnp.ones([], jnp.float32)))
        self.register_buffer("_state", Tensor(jnp.zeros([], jnp.float32)))
        self.register_buffer("_accum", Tensor(jnp.zeros([], jnp.float32)))

    def forward(self, x):
        qrange = 2.0 ** (self._bit_length - 1) - 1
        if self.training:
            amax = float(jnp.max(jnp.abs(to_array(x))))
            state = self._moving_rate * float(self._state.item()) + 1.0
            accum = self._moving_rate * float(self._accum.item()) + amax
            self._buffers["_state"] = Tensor(jnp.asarray(state, jnp.float32))
            self._buffers["_accum"] = Tensor(jnp.asarray(accum, jnp.float32))
            scale = accum / state
            self._buffers["_scale"] = Tensor(jnp.asarray(scale, jnp.float32))
        else:
            scale = float(self._scale.item())
        scale = max(scale, 1e-8)

        @jax.custom_vjp
        def _fq(v):
            return jnp.round(jnp.clip(v / scale, -1.0, 1.0) * qrange) * scale / qrange

        def _fwd(v):
            return _fq(v), None

        def _bwd(res, g):
            return (g,)  # straight-through

        _fq.defvjp(_fwd, _bwd)
        return apply_op(_fq, x)

    def scales(self):
        return self._scale

    def bit_length(self):
        return self._bit_length


class FakeQuanterWithAbsMaxObserver(QuanterFactory):
    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32", name=None):
        super().__init__(moving_rate=moving_rate, bit_length=bit_length, dtype=dtype)

    def _get_class(self):
        return FakeQuanterWithAbsMaxObserverLayer


class SingleLayerConfig:
    def __init__(self, activation, weight):
        self.activation = activation
        self.weight = weight


class QuantConfig:
    """Maps layers → quanter settings (ref quantization/config.py:59)."""

    def __init__(self, activation=None, weight=None):
        self._global_config = (SingleLayerConfig(activation, weight)
                               if activation is not None or weight is not None else None)
        self._layer2config = {}
        self._prefix2config = {}
        self._type2config = {}

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer2config[id(l)] = SingleLayerConfig(activation, weight)

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = layer_name if isinstance(layer_name, (list, tuple)) else [layer_name]
        for n in names:
            self._prefix2config[n] = SingleLayerConfig(activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) else [layer_type]
        for t in types:
            self._type2config[t] = SingleLayerConfig(activation, weight)

    def _config_for(self, layer, full_name):
        if id(layer) in self._layer2config:
            return self._layer2config[id(layer)]
        for prefix, cfg in self._prefix2config.items():
            if full_name.startswith(prefix):
                return cfg
        for t, cfg in self._type2config.items():
            if isinstance(layer, t):
                return cfg
        return self._global_config


class _QuantedModule(Layer):
    """Shared quanter wiring for QAT layer wrappers (ref nn/quant/qat/)."""

    def __init__(self, inner, cfg: SingleLayerConfig):
        super().__init__()
        self.inner = inner
        self.weight_quanter = (cfg.weight._instance(inner) if cfg.weight else None)
        self.activation_quanter = (cfg.activation._instance(inner)
                                   if cfg.activation else None)

    def _quantized(self, x):
        w = self.inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        return x, w


class QuantedConv2D(_QuantedModule):
    """Conv2D with weight+activation fake-quant (ref nn/quant/qat/conv.py)."""

    def forward(self, x):
        from ..nn import functional as F

        x, w = self._quantized(x)
        return F.conv2d(x, w, self.inner.bias, stride=self.inner._stride,
                        padding=self.inner._padding, dilation=self.inner._dilation,
                        groups=self.inner._groups,
                        data_format=self.inner._data_format)


class QuantedLinearV2(_QuantedModule):
    """Linear wrapped with configured quanters (ref nn/quant/qat/linear.py)."""

    def forward(self, x):
        from ..nn import functional as F

        x, w = self._quantized(x)
        return F.linear(x, w, self.inner.bias)


def _pin_layer_rules(config: "QuantConfig", model: Layer):
    """id-keyed layer rules would dangle after deepcopy: pin them to the
    layer's name path first."""
    if config._layer2config:
        for full, sub in model.named_sublayers(include_self=False):
            if not full:
                continue
            cfg = config._layer2config.get(id(sub))
            if cfg is not None:
                config._prefix2config[full] = cfg


class QATv2:
    """Config-driven QAT (ref quantization/qat.py QAT). Usage:
        q = QAT(QuantConfig(activation=quanter, weight=quanter))
        qmodel = q.quantize(model)
    """

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace: bool = False):
        if not inplace:
            import copy

            _pin_layer_rules(self.config, model)
            model = copy.deepcopy(model)
        from ..nn.layer.common import Linear
        from ..nn.layer.conv import Conv2D

        def walk(layer, prefix=""):
            for name, sub in list(layer._sub_layers.items()):
                full = f"{prefix}.{name}" if prefix else name
                cfg = self.config._config_for(sub, full)
                if cfg is not None and isinstance(sub, Linear):
                    layer._sub_layers[name] = QuantedLinearV2(sub, cfg)
                elif cfg is not None and isinstance(sub, Conv2D):
                    layer._sub_layers[name] = QuantedConv2D(sub, cfg)
                else:
                    walk(sub, full)

        walk(model)
        return model

    def convert(self, model: Layer, inplace: bool = False):
        """Freeze observers for inference (scales stop updating)."""
        model.eval()
        return model


QAT.convert = QATv2.convert
