"""Quantization (ref: python/paddle/quantization/ QAT/PTQ + nn/quant/).

TPU-native: int8 is MXU-native; fake-quant ops use the straight-through
estimator, PTQ observes abs-max ranges. The compiled path lowers fake-quant
to real int8 dots where XLA supports it.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op
from ..nn.layer_base import Layer


def quantize_absmax(x, bits=8, axis=None):
    """Symmetric abs-max quantization → (q_int, scale)."""

    def f(v):
        qmax = 2.0 ** (bits - 1) - 1
        amax = jnp.max(jnp.abs(v), axis=axis, keepdims=axis is not None)
        scale = jnp.maximum(amax, 1e-8) / qmax
        q = jnp.clip(jnp.round(v / scale), -qmax - 1, qmax).astype(jnp.int8)
        return q, scale.astype(jnp.float32)

    return apply_op(f, x)


def dequantize(q, scale):
    return apply_op(lambda qq, s: qq.astype(jnp.float32) * s, q, scale)


def fake_quant(x, bits=8, axis=None):
    """Quantize-dequantize with straight-through gradient (QAT core op,
    ref fake_quantize_op)."""

    @jax.custom_vjp
    def _fq(v):
        qmax = 2.0 ** (bits - 1) - 1
        amax = jnp.max(jnp.abs(v), axis=axis, keepdims=axis is not None)
        scale = jnp.maximum(amax, 1e-8) / qmax
        return jnp.clip(jnp.round(v / scale), -qmax - 1, qmax) * scale

    def _fwd(v):
        return _fq(v), None

    def _bwd(res, g):
        return (g,)  # STE

    _fq.defvjp(_fwd, _bwd)
    return apply_op(_fq, x)


class FakeQuanterWithAbsMax(Layer):
    def __init__(self, bits=8, axis=None, name=None):
        super().__init__()
        self.bits = bits
        self.axis = axis

    def forward(self, x):
        return fake_quant(x, self.bits, self.axis)


class QuantedLinear(Layer):
    """Linear with weight+activation fake-quant (QAT wrapper,
    ref nn/quant/ QuantizedLinear)."""

    def __init__(self, linear, bits=8):
        super().__init__()
        self.inner = linear
        self.bits = bits

    def forward(self, x):
        from ..nn import functional as F

        wq = fake_quant(self.inner.weight, self.bits, axis=None)
        xq = fake_quant(x, self.bits)
        return F.linear(xq, wq, self.inner.bias)


class QAT:
    """Quantization-aware training transform (ref quantization/qat.py)."""

    def __init__(self, config: Optional[dict] = None):
        self.config = config or {"bits": 8}

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        from ..nn.layer.common import Linear

        bits = self.config.get("bits", 8)
        for name, sub in list(model._sub_layers.items()):
            if isinstance(sub, Linear):
                model._sub_layers[name] = QuantedLinear(sub, bits)
            else:
                self.quantize(sub, inplace=True)
        return model


class PTQ:
    """Post-training quantization: observe abs-max over calibration data
    (ref quantization/ptq.py)."""

    def __init__(self, config: Optional[dict] = None):
        self.config = config or {"bits": 8}
        self.ranges: Dict[str, float] = {}

    def observe(self, model: Layer, data_iter, n_batches: int = 8):
        hooks = []
        ranges = self.ranges

        def make_hook(name):
            def hook(layer, inputs, output):
                val = float(jnp.max(jnp.abs(to_array(output))))
                ranges[name] = max(ranges.get(name, 0.0), val)

            return hook

        for name, sub in model.named_sublayers(include_self=False):
            hooks.append(sub.register_forward_post_hook(make_hook(name)))
        from ..framework.core import no_grad_ctx

        with no_grad_ctx():
            for i, batch in enumerate(data_iter):
                if i >= n_batches:
                    break
                x = batch[0] if isinstance(batch, (list, tuple)) else batch
                model(x)
        for h in hooks:
            h.remove()
        return self.ranges

    def quantize_weights(self, model: Layer) -> Dict[str, tuple]:
        out = {}
        for name, p in model.named_parameters():
            if p.ndim >= 2:
                q, s = quantize_absmax(p, self.config.get("bits", 8))
                out[name] = (q, s)
        return out
