"""Remaining top-level paddle API names (parity sweep vs reference
python/paddle/__init__.py __all__)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .framework.core import Parameter, Tensor, to_array
from .framework.dispatch import apply_op
from .framework.dtype import convert_dtype, is_complex as _is_complex_d, \
    is_floating_point as _is_fp_d, is_integer as _is_int_d
from .framework.random import get_rng_state, set_rng_state


def dtype(d):
    return convert_dtype(d)


class iinfo:
    def __init__(self, d):
        info = np.iinfo(np.dtype(convert_dtype(d)))
        self.min = int(info.min)
        self.max = int(info.max)
        self.bits = info.bits
        self.dtype = str(np.dtype(convert_dtype(d)))


class finfo:
    def __init__(self, d):
        info = np.finfo(np.dtype(convert_dtype(d)) if convert_dtype(d) != jnp.bfloat16
                        else np.float32)
        self.min = float(info.min)
        self.max = float(info.max)
        self.eps = float(info.eps)
        self.bits = info.bits
        self.dtype = str(d)


def is_floating_point(x):
    return _is_fp_d(x.dtype)


def is_integer(x):
    return _is_int_d(x.dtype)


def is_complex(x):
    return _is_complex_d(x.dtype)


def cast(x, dtype):
    return x.astype(dtype)


def mv(x, vec, name=None):
    return apply_op(lambda a, b: a @ b, x, vec)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    def f(v):
        if axis is None:
            v = v.reshape(-1)
            ax = 0
        else:
            ax = axis
        m = jax.lax.cummax(v, axis=ax)
        return jnp.log(jnp.cumsum(jnp.exp(v - m), axis=ax)) + m

    return apply_op(f, x)


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    out = inputs[0]
    for t in inputs[1:]:
        out = out + t
    return out


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2),
                    x)


def sgn(x, name=None):
    def f(v):
        if jnp.issubdtype(v.dtype, jnp.complexfloating):
            mag = jnp.abs(v)
            return jnp.where(mag == 0, 0, v / jnp.maximum(mag, 1e-30))
        return jnp.sign(v)

    return apply_op(f, x)


def frexp(x, name=None):
    outs = apply_op(lambda v: tuple(jnp.frexp(v)), x)
    return outs[0], outs[1]


def reverse(x, axis, name=None):
    from .tensor.manipulation import flip

    return flip(x, axis)


def vsplit(x, num_or_indices, name=None):
    from .tensor.manipulation import tensor_split

    return tensor_split(x, num_or_indices, axis=0)


def hsplit(x, num_or_indices, name=None):
    from .tensor.manipulation import tensor_split

    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def dsplit(x, num_or_indices, name=None):
    from .tensor.manipulation import tensor_split

    return tensor_split(x, num_or_indices, axis=2)


def tolist(x):
    return x.tolist()


# ---- in-place aliases (functional under the hood) -------------------------

def squeeze_(x, axis=None, name=None):
    from .tensor.manipulation import squeeze

    x._value = squeeze(x, axis).value
    return x


def unsqueeze_(x, axis, name=None):
    from .tensor.manipulation import unsqueeze

    x._value = unsqueeze(x, axis).value
    return x


def tanh_(x, name=None):
    x._value = jnp.tanh(x.value)
    return x


def index_add_(x, index, axis, value, name=None):
    from .tensor.manipulation import index_add

    x._value = index_add(x, index, axis, value).value
    return x


# ---- RNG aliases (no CUDA on TPU; global generator state) ------------------

def get_cuda_rng_state():
    return [get_rng_state()]


def set_cuda_rng_state(state):
    set_rng_state(state[0] if isinstance(state, (list, tuple)) else state)


# ---- places ----------------------------------------------------------------


class Place:
    def __init__(self, kind, device_id=0):
        self._kind = kind
        self._id = device_id

    def __repr__(self):
        return f"Place({self._kind}:{self._id})"

    def is_gpu_place(self):
        return self._kind == "gpu"

    def is_cpu_place(self):
        return self._kind == "cpu"


class CPUPlace(Place):
    def __init__(self):
        super().__init__("cpu")


class CUDAPlace(Place):
    def __init__(self, device_id=0):
        super().__init__("gpu", device_id)


class CUDAPinnedPlace(Place):
    def __init__(self):
        super().__init__("cuda_pinned")


class NPUPlace(Place):
    def __init__(self, device_id=0):
        super().__init__("npu", device_id)


class TPUPlace(Place):
    def __init__(self, device_id=0):
        super().__init__("tpu", device_id)


class XPUPlace(Place):
    def __init__(self, device_id=0):
        super().__init__("xpu", device_id)


class IPUPlace(Place):
    def __init__(self, device_id=0):
        super().__init__("ipu", device_id)


class MLUPlace(Place):
    def __init__(self, device_id=0):
        super().__init__("mlu", device_id)


class CustomPlace(Place):
    def __init__(self, dev_type="custom", device_id=0):
        super().__init__(dev_type, device_id)


# ---- misc ------------------------------------------------------------------


class LazyGuard:
    """Ref lazy init: delay parameter materialization. Eager JAX init is cheap
    so this is a transparent context manager."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def set_grad_enabled(mode: bool):
    import contextlib

    from .framework.core import _grad_state

    @contextlib.contextmanager
    def ctx():
        prev = _grad_state.enabled
        _grad_state.enabled = bool(mode)
        try:
            yield
        finally:
            _grad_state.enabled = prev

    return ctx()


def set_printoptions(precision=None, threshold=None, edgeitems=None, sci_mode=None,
                     linewidth=None):
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def disable_signal_handler():
    pass


def check_shape(x):
    return list(x.shape)


from .batch import batch  # noqa: F401  (ref python/paddle/batch.py)


def create_parameter(shape, dtype="float32", name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from .nn.initializer import Constant, XavierUniform

    init = default_initializer or (Constant(0.0) if is_bias else XavierUniform())
    return Parameter(init(shape, convert_dtype(dtype)), name=name or "")
