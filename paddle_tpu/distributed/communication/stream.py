"""Explicit-stream collective variants (ref: communication/stream/*.py).
On TPU, XLA schedules collective streams; these alias the sync API with the
use_calc_stream flag accepted and ignored."""
from ..collective import (all_gather as _ag, all_reduce as _ar, all_to_all as _a2a,
                          broadcast as _bc, reduce as _rd, reduce_scatter as _rs,
                          scatter as _sc)


def all_reduce(tensor, op=None, group=None, sync_op=True, use_calc_stream=False):
    from ..collective import ReduceOp

    return _ar(tensor, op if op is not None else ReduceOp.SUM, group, sync_op)


def all_gather(tensor_or_list, tensor, group=None, sync_op=True, use_calc_stream=False):
    return _ag(tensor_or_list, tensor, group, sync_op)


def reduce(tensor, dst=0, op=None, group=None, sync_op=True, use_calc_stream=False):
    from ..collective import ReduceOp

    return _rd(tensor, dst, op if op is not None else ReduceOp.SUM, group, sync_op)


def broadcast(tensor, src=0, group=None, sync_op=True, use_calc_stream=False):
    return _bc(tensor, src, group, sync_op)


def reduce_scatter(tensor, tensor_list, op=None, group=None, sync_op=True,
                   use_calc_stream=False):
    from ..collective import ReduceOp

    return _rs(tensor, tensor_list, op if op is not None else ReduceOp.SUM, group,
               sync_op)


def alltoall(out_list, in_list, group=None, sync_op=True, use_calc_stream=False):
    return _a2a(out_list, in_list, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True,
            use_calc_stream=False):
    return _sc(tensor, tensor_list, src, group, sync_op)
