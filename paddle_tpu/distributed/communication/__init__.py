"""paddle.distributed.communication parity — re-exports the collectives;
`stream` submodule keeps the explicit-stream API importable (XLA owns stream
scheduling on TPU, ref SURVEY §5.8)."""
from ..collective import (all_gather, all_reduce, all_to_all, barrier, broadcast,
                          reduce, reduce_scatter, scatter)
from . import stream  # noqa: F401


def batch_isend_irecv(p2p_op_list):
    """Ref communication/batch_isend_irecv.py. Host-driven p2p is not a TPU
    primitive — pipeline comm lives inside compiled programs (ppermute)."""
    raise NotImplementedError(
        "batch_isend_irecv: use the compiled pipeline path "
        "(paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel) — "
        "host-driven NCCL-style p2p has no TPU analogue.")


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
