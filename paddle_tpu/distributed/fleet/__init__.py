"""Fleet facade (ref: python/paddle/distributed/fleet/fleet.py:169 init,
model.py:30 distributed_model, fleet.py:1044 distributed_optimizer).
"""
from .base import (DistributedStrategy, Fleet, PaddleCloudRoleMaker, UserDefinedRoleMaker,
                   fleet_instance)
from . import meta_parallel  # noqa: F401
from . import meta_optimizers  # noqa: F401
from .recompute import recompute, recompute_sequential  # noqa: F401
from . import data_generator  # noqa: F401
from .dataset import InMemoryDataset, QueueDataset  # noqa: F401

_fleet = fleet_instance


def init(role_maker=None, is_collective=False, strategy=None, log_level="INFO"):
    return _fleet.init(role_maker=role_maker, is_collective=is_collective,
                       strategy=strategy)


def distributed_model(model):
    return _fleet.distributed_model(model)


def distributed_optimizer(optimizer, strategy=None):
    return _fleet.distributed_optimizer(optimizer, strategy)


def get_hybrid_communicate_group():
    return _fleet.hcg


def get_mesh():
    return _fleet.mesh


def worker_index():
    return _fleet.worker_index()


def worker_num():
    return _fleet.worker_num()


def is_first_worker():
    return _fleet.worker_index() == 0


def barrier_worker():
    from ..collective import barrier

    barrier()
