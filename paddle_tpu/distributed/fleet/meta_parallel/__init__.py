"""Hybrid-parallel building blocks (ref: fleet/meta_parallel/)."""
from .mp_layers import (ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
                        VocabParallelEmbedding)
from .parallel_model import ShardedDataParallel, TensorParallel
from .pipeline_parallel import PipelineParallel
from .pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc
from .random import RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed
from .hybrid_optimizer import HybridParallelClipGrad, HybridParallelOptimizer
from .sharding_optimizer import DygraphShardingOptimizer, GroupShardedOptimizerStage2

__all__ = [n for n in dir() if not n.startswith("_")]
