"""Pipeline-parallel execution (ref: fleet/meta_parallel/pipeline_parallel.py —
PipelineParallel:31, 1F1B forward_backward_pipeline:117, interleaved :461;
p2p_communication.py SendRecvMeta/partial p2p).

TPU-native design: two execution paths.

1. **Eager microbatch loop** — ``PipelineParallel.forward_backward_pipeline``
   below runs fwd+bwd per microbatch with all stages co-resident. This is
   gradient accumulation: it matches 1F1B's *numerics* exactly but has NONE
   of its scheduling/memory semantics (no stage-sharded params, no bubble).
   It exists for API parity and single-host debugging only.

2. **Compiled pipeline TRAINING** — ``parallel.pipeline_engine.PipelineEngine``
   is the real PP path: stage-sharded params P("pipe") under a pipe-manual
   shard_map, with two schedules:
   - GPipe (``spmd_pipeline_fn``): fill/drain scan differentiated
     end-to-end, so activation grads ppermute backward stage→stage-1;
     remat on the stage body.  Residuals: one boundary activation per tick
     (O(num_micro) microbatch-sized buffers per stage).
   - true 1F1B (``spmd_1f1b_train_fn``): loss computed AT the last stage
     inside the pipe region, backward hand-driven by per-stage ``jax.vjp``
     in the same scan — each microbatch's backward starts one tick after
     its forward finishes, and live residuals are bounded by the ring
     capacity min(2S-1, M) independent of the microbatch count (the
     reference 1F1B memory property, asserted via compiled
     memory_analysis in tests/test_engine_parity.py).
   Both verified weight-parity vs single-device in
   tests/test_engine_parity.py; exercised by
   ``__graft_entry__.dryrun_multichip``.
"""
from __future__ import annotations

from typing import Callable, List, Optional

import jax
import jax.numpy as jnp

from ....framework.core import Tensor
from ....tensor.manipulation import split as tensor_split
from .pp_layers import PipelineLayer


class PipelineParallel:
    """Ref pipeline_parallel.py:31."""

    def __init__(self, layers: PipelineLayer, hcg, strategy):
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = strategy.pipeline_configs if strategy is not None else {}
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.micro_batch_size = cfg.get("micro_batch_size", 1)
        self.num_stages = layers.get_num_stages()
        self.stage_id = hcg.get_stage_id() if hcg is not None else 0
        self.total_loss = None

    def parameters(self):
        return self._layers.parameters()

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def train(self):
        self._layers.train()
        return self

    def eval(self):
        self._layers.eval()
        return self

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _split_micro(self, data):
        n = self.accumulate_steps
        if isinstance(data, (tuple, list)):
            parts = [tensor_split(d, n, axis=0) for d in data]
            return list(zip(*parts))
        return [(mb,) for mb in tensor_split(data, n, axis=0)]

    def forward_backward_pipeline(self, data, scaler=None):
        """Microbatch loop with 1F1B-equivalent NUMERICS (ref :117) — this
        eager path is gradient accumulation with all stages co-resident; it
        does not reproduce 1F1B's scheduling or memory behavior. Use
        ``parallel.PipelineEngine`` for true stage-sharded pipeline
        training."""
        inputs, labels = data
        micro_inputs = self._split_micro(inputs)
        micro_labels = self._split_micro(labels)

        num_micro = self.accumulate_steps
        losses = []
        # Startup + steady + cooldown collapses to fwd-then-bwd per microbatch
        # when all stages are co-resident: schedule order matches 1F1B's
        # per-microbatch dataflow exactly.
        for mb_in, mb_lb in zip(micro_inputs, micro_labels):
            out = self._layers(*mb_in)
            if self._layers._loss_fn is not None:
                loss = self._layers._loss_fn(out, *mb_lb)
            else:
                loss = out
            loss = loss / num_micro
            if scaler is not None:
                scaled = scaler.scale(loss)
                scaled.backward()
            else:
                loss.backward()
            losses.append(loss)
        total = losses[0]
        for l in losses[1:]:
            total = total + l
        self.total_loss = total.detach()
        return self.total_loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Ref pipeline_parallel.py:228."""
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        from ....framework.core import no_grad_ctx

        inputs, labels = data
        with no_grad_ctx():
            out = self._layers(*self._split_micro(inputs)[0])
            if compute_loss and self._layers._loss_fn is not None:
                return self._layers._loss_fn(out, *self._split_micro(labels)[0])
        return out


class PipelineParallelWithInterleave(PipelineParallel):
    """Ref pipeline_parallel.py:461 — virtual pipeline stages. The eager path
    collapses to the same per-microbatch dataflow (single-controller SPMD);
    the compiled path is `spmd_interleaved_pipeline_fn`, the virtual-stage
    ring schedule (lockstep rendering — see its bubble note)."""

    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        cfg = strategy.pipeline_configs if strategy is not None else {}
        self.num_model_chunks = cfg.get("num_model_chunks",
                                        getattr(layers, "_num_virtual_pipeline_stages", 1))


# ---------------------------------------------------------------------------
# Compiled SPMD pipeline step (path 2)
# ---------------------------------------------------------------------------


def spmd_pipeline_fn(stage_fn: Callable, num_stages: int, num_micro: int,
                     axis_name: str = "pipe"):
    """Build a shard_map-compatible per-shard function running a GPipe
    fill/drain schedule with ppermute along `axis_name`.

    stage_fn(stage_id, carry_activation, microbatch) -> activation
    Each shard holds ONE stage's params; activations rotate stage→stage+1.
    Returns per-shard final outputs for the microbatches that finished on the
    last stage (other shards return zeros) — caller psums/selects.
    """

    def per_shard(params_shard, micro_batches):
        # mark replicated inputs as varying over the pipe axis so scan/cond
        # type-check against the ppermute-produced (varying) activations
        micro_batches = jax.tree_util.tree_map(
            lambda x: jax.lax.pcast(x, (axis_name,), to="varying"), micro_batches)
        stage = jax.lax.axis_index(axis_name)
        T = num_micro + num_stages - 1  # fill + drain ticks

        def tick(carry, t):
            act, outputs = carry
            mb_idx = t - stage
            valid = (mb_idx >= 0) & (mb_idx < num_micro)
            mb = jax.tree_util.tree_map(
                lambda x: x[jnp.clip(mb_idx, 0, num_micro - 1)], micro_batches)
            inp = jax.lax.cond(stage == 0,
                               lambda: mb,
                               lambda: act)
            out = stage_fn(stage, params_shard, inp)
            out = jax.tree_util.tree_map(
                lambda o, a: jnp.where(valid, o, a), out, act)
            # rotate to next stage
            nxt = jax.lax.ppermute(
                out, axis_name,
                [(i, (i + 1) % num_stages) for i in range(num_stages)])
            done = (stage == num_stages - 1) & valid
            outputs = jax.tree_util.tree_map(
                lambda os, o: os.at[jnp.clip(mb_idx, 0, num_micro - 1)].set(
                    jnp.where(done, o, os[jnp.clip(mb_idx, 0, num_micro - 1)])),
                outputs, out)
            return (nxt, outputs), None

        act0 = jax.tree_util.tree_map(lambda x: jnp.zeros_like(x[0]), micro_batches)
        # run one stage fwd to get output shape
        out_shape = jax.eval_shape(lambda a: stage_fn(0, params_shard, a), act0)
        outputs0 = jax.tree_util.tree_map(
            lambda s: jax.lax.pcast(jnp.zeros((num_micro,) + tuple(s.shape), s.dtype),
                                    (axis_name,), to="varying"), out_shape)
        (act, outputs), _ = jax.lax.scan(tick, (act0, outputs0), jnp.arange(T))
        # only the last stage wrote real values; psum replicates them ring-wide
        return jax.tree_util.tree_map(lambda o: jax.lax.psum(o, axis_name), outputs)

    return per_shard


def spmd_1f1b_train_fn(stage_fn: Callable, post_loss_fn: Callable,
                       num_stages: int, num_micro: int,
                       axis_name: str = "pipe"):
    """Compiled 1F1B pipeline TRAINING schedule (ref
    pipeline_parallel.py:117 ``forward_backward_pipeline`` — warmup /
    steady 1F1B / cooldown).

    Unlike ``spmd_pipeline_fn`` (GPipe order, differentiated end-to-end by
    ``jax.grad`` through the scan, which stores one boundary activation per
    tick = O(num_micro) residuals per stage), this schedule computes the
    loss AT the last stage inside the pipe region and hand-drives the
    backward with per-stage ``jax.vjp`` inside the same scan, so each
    microbatch's backward starts one tick after its forward reaches the
    last stage.  Live residuals per stage are bounded by the ring capacity
    ``min(2*num_stages - 1, num_micro)`` — independent of ``num_micro``,
    which is 1F1B's defining memory property.

    Tick chart (S = num_stages, M = num_micro, T = M + 2S - 1 ticks):
      stage s runs fwd(m)  at tick  t = m + s
      stage s runs bwd(m)  at tick  t = m + 2S - 1 - s
    so the last stage (s = S-1) runs bwd(m) exactly one tick after fwd(m),
    and in steady state every stage does one fwd and one bwd per tick —
    the lockstep-SPMD rendering of the reference's alternating 1F1B order.
    Activations ppermute s→s+1, cotangents ppermute s→s-1, each once per
    tick.

    stage_fn(stage_id, params_shard, x) -> y             (one stage fwd)
    post_loss_fn(post_params, y, labels_mb) -> scalar    (head + loss,
        MEAN over the microbatch; the 1/M total-loss scaling is applied
        here so accumulated grads are grads of the full-batch mean loss)

    per_shard(params_shard, post_params, micro, micro_labels) returns
      (loss, d_params_shard, d_post_params, d_micro):
      - loss: full-batch mean loss, replicated
      - d_params_shard: this stage's param grads (out_specs P(axis) →
        reassembles the stacked [S, ...] grads)
      - d_post_params: grads of the post/head params (replicated)
      - d_micro: grads w.r.t. the microbatched input activations [M, ...]
        (replicated; caller backpropagates them through the embedding)
    """

    def per_shard(params_shard, post_params, micro, micro_labels):
        to_varying = lambda tree: jax.tree_util.tree_map(
            lambda x: jax.lax.pcast(x, (axis_name,), to="varying"), tree)
        micro = to_varying(micro)
        micro_labels = to_varying(micro_labels)
        post_params = to_varying(post_params)
        dev = jax.lax.axis_index(axis_name)
        S, M = num_stages, num_micro
        K = min(2 * S - 1, M)  # residual ring capacity — the 1F1B bound
        T = M + 2 * S - 1

        def fwd_of(p, x):
            return stage_fn(dev, p, x)

        def scaled_post(pp, y, lb):
            loss = post_loss_fn(pp, y, lb)
            return loss / M

        zeros_like_t = lambda tree: jax.tree_util.tree_map(jnp.zeros_like, tree)

        def select(pred, a, b):
            return jax.tree_util.tree_map(
                lambda x, y: jnp.where(pred, x, y), a, b)

        def tick(carry, t):
            (fwd_act, bwd_grad, pending_ct, resid, g_stk, g_post,
             d_micro, loss_acc) = carry

            # ---- backward half: consumes last tick's pending cotangent /
            # the cotangent ppermuted from stage s+1
            mb_b = t - (2 * S - 1 - dev)
            valid_b = (mb_b >= 0) & (mb_b < M)
            slot_b = jnp.clip(mb_b, 0, M - 1) % K
            x_in = jax.tree_util.tree_map(lambda r: r[slot_b], resid)
            ct_in = select(dev == S - 1, pending_ct, bwd_grad)
            _, vjp_fn = jax.vjp(fwd_of, params_shard, x_in)
            dp, dx = vjp_fn(ct_in)
            g_stk = jax.tree_util.tree_map(
                lambda g, d: g + jnp.where(valid_b, d, 0), g_stk, dp)
            write0 = valid_b & (dev == 0)
            mb_c = jnp.clip(mb_b, 0, M - 1)
            d_micro = jax.tree_util.tree_map(
                lambda buf, d: buf.at[mb_c].set(
                    jnp.where(write0, d, buf[mb_c])), d_micro, dx)
            dx_send = select(valid_b, dx, zeros_like_t(dx))

            # ---- forward half
            mb_f = t - dev
            valid_f = (mb_f >= 0) & (mb_f < M)
            mb_cf = jnp.clip(mb_f, 0, M - 1)
            mb = jax.tree_util.tree_map(lambda x: x[mb_cf], micro)
            lb = jax.tree_util.tree_map(lambda x: x[mb_cf], micro_labels)
            x = select(dev == 0, mb, fwd_act)
            y = fwd_of(params_shard, x)
            slot_f = mb_cf % K
            resid = jax.tree_util.tree_map(
                lambda r, v: r.at[slot_f].set(
                    jnp.where(valid_f, v, r[slot_f])), resid, x)
            # head + loss at the last stage; its value_and_grad seeds the
            # backward pipeline one tick later via pending_ct
            take = (dev == S - 1) & valid_f
            loss_m, (gp, gy) = jax.value_and_grad(
                scaled_post, argnums=(0, 1))(post_params, y, lb)
            loss_acc = loss_acc + jnp.where(take, loss_m, 0.0)
            g_post = jax.tree_util.tree_map(
                lambda g, d: g + jnp.where(take, d, 0), g_post, gp)
            pending_ct = select(take, gy, pending_ct)
            y_send = select(valid_f, y, zeros_like_t(y))

            # ---- one rotation each way
            fwd_act = jax.lax.ppermute(
                y_send, axis_name,
                [(i, (i + 1) % S) for i in range(S)])
            bwd_grad = jax.lax.ppermute(
                dx_send, axis_name,
                [(i, (i - 1) % S) for i in range(S)])
            return (fwd_act, bwd_grad, pending_ct, resid, g_stk, g_post,
                    d_micro, loss_acc), None

        act_proto = jax.tree_util.tree_map(lambda x: jnp.zeros_like(x[0]),
                                           micro)
        y_shape = jax.eval_shape(lambda a: stage_fn(0, params_shard, a),
                                 act_proto)
        zvary = lambda s: jax.lax.pcast(
            jnp.zeros(tuple(s.shape), s.dtype), (axis_name,), to="varying")
        y0 = jax.tree_util.tree_map(zvary, y_shape)
        carry0 = (
            act_proto,                                   # fwd_act
            zeros_like_t(act_proto),                     # bwd_grad (dx ~ x)
            y0,                                          # pending_ct (~ y)
            jax.tree_util.tree_map(                      # residual ring [K]
                lambda x: jax.lax.pcast(
                    jnp.zeros((K,) + tuple(x.shape), x.dtype),
                    (axis_name,), to="varying"), act_proto),
            zeros_like_t(params_shard),                  # g_stk
            zeros_like_t(post_params),                   # g_post
            jax.tree_util.tree_map(                      # d_micro [M, ...]
                lambda x: jnp.zeros_like(x), micro),
            jax.lax.pcast(jnp.float32(0.0), (axis_name,), to="varying"),
        )
        (fwd_act, bwd_grad, pending_ct, resid, g_stk, g_post, d_micro,
         loss_acc), _ = jax.lax.scan(tick, carry0, jnp.arange(T))
        # loss / post grads / input grads live on one stage only; psum
        # replicates the sums ring-wide.  Stage param grads stay per-shard.
        loss = jax.lax.psum(loss_acc, axis_name)
        g_post = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, axis_name), g_post)
        d_micro = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, axis_name), d_micro)
        return loss, g_stk, g_post, d_micro

    return per_shard


def spmd_staggered_interleaved_1f1b(stage_fn: Callable,
                                    post_loss_fn: Callable,
                                    num_stages: int, num_micro: int,
                                    num_chunks: int,
                                    axis_name: str = "pipe"):
    """Interleaved 1F1B with ONE chunk-op per device per tick — the
    staggered tick chart that makes virtual stages actually shrink the
    bubble (ref PipelineParallelWithInterleave pipeline_parallel.py:461;
    Megatron-style grouped order):

      fwd(m) at logical stage L = c*S + d:  t = d + c*S + (m mod S)
                                                + C*S*(m div S)
      bwd(m):                               t = C*S + (S-1-d) + (C-1-c)*S
                                                + (m mod S) + C*S*(m div S)

    For each (t, d) the decomposition is unique, so every device runs
    exactly one fwd and one bwd slot per tick with a TRACED chunk index
    (params are gathered by chunk inside the vjp, whose transpose
    scatter-adds the grads back into the right chunk).  Total ticks
    ~ C*M + (C+1)*S versus the plain schedule's M + 2S per C-times-larger
    stage: normalized bubble drops from 2S/M to (1+1/C)*S/M.  The chart
    also makes routing trivial: the single ppermuted activation arriving
    each tick is exactly the operand of the receiver's scheduled op
    (chunk advance on the S-1→0 wrap falls out of the +1-tick property).

    Residual rings: [C, K] with K = min(3S+1, M) boundary activations per
    chunk — O(stages), independent of M.
    Returns (loss, d_params_shard [1, C, ...], d_post_params, d_micro)
    like the plain schedule.
    """

    def per_shard(params_shard, post_params, micro, micro_labels):
        to_varying = lambda tree: jax.tree_util.tree_map(
            lambda x: jax.lax.pcast(x, (axis_name,), to="varying"), tree)
        micro = to_varying(micro)
        micro_labels = to_varying(micro_labels)
        post_params = to_varying(post_params)
        dev = jax.lax.axis_index(axis_name)
        S, M, C = num_stages, num_micro, num_chunks
        CS = C * S
        K = min(3 * S + 1, M)
        T = (CS + (S - 1) + (C - 1) * S + ((M - 1) % S)
             + CS * ((M - 1) // S) + 1)

        def fwd_c(pfull, x, c):
            # c is TRACED here (one op per tick, chunk chosen by the
            # chart): stage_fn receives it as a tracer — chunk-dependent
            # behavior must branch with lax.switch, not Python `if`
            pc = jax.tree_util.tree_map(lambda p: p[0][c], pfull)
            return stage_fn(c, pc, x)

        def scaled_post(pp, y, lb):
            return post_loss_fn(pp, y, lb) / M

        zeros_like_t = lambda tree: jax.tree_util.tree_map(jnp.zeros_like,
                                                           tree)

        def select(pred, a, b):
            return jax.tree_util.tree_map(
                lambda x, y: jnp.where(pred, x, y), a, b)

        def decode_fwd(t):
            u = t - dev
            uc = jnp.maximum(u, 0)
            g = uc // CS
            rem = uc % CS
            c = rem // S
            m = g * S + (rem % S)
            valid = (u >= 0) & (m < M)
            return c, jnp.clip(m, 0, M - 1), valid

        def decode_bwd(t):
            u = t - CS - (S - 1 - dev)
            uc = jnp.maximum(u, 0)
            g = uc // CS
            rem = uc % CS
            c = (C - 1) - rem // S
            m = g * S + (rem % S)
            valid = (u >= 0) & (m < M)
            return c, jnp.clip(m, 0, M - 1), valid

        def tick(carry, t):
            (fwd_act, bwd_grad, pending_ct, resid, g_stk, g_post,
             d_micro, loss_acc) = carry

            # ---- backward slot (consumes last tick's cotangent)
            c_b, m_b, valid_b = decode_bwd(t)
            slot_b = m_b % K
            x_in = jax.tree_util.tree_map(lambda r: r[c_b, slot_b], resid)
            last_b = (dev == S - 1) & (c_b == C - 1)
            ct_in = select(last_b, pending_ct, bwd_grad)
            _, vjp_fn = jax.vjp(
                lambda p, x: fwd_c(p, x, c_b), params_shard, x_in)
            dp_full, dx = vjp_fn(ct_in)
            # gather's transpose already scattered dp into chunk c_b
            g_stk = jax.tree_util.tree_map(
                lambda g, d: g + jnp.where(valid_b, d, 0), g_stk, dp_full)
            first_b = valid_b & (dev == 0) & (c_b == 0)
            d_micro = jax.tree_util.tree_map(
                lambda buf, d: buf.at[m_b].set(
                    jnp.where(first_b, d, buf[m_b])), d_micro, dx)
            dx_send = select(valid_b, dx, zeros_like_t(dx))

            # ---- forward slot
            c_f, m_f, valid_f = decode_fwd(t)
            mb = jax.tree_util.tree_map(lambda x: x[m_f], micro)
            lb = jax.tree_util.tree_map(lambda x: x[m_f], micro_labels)
            first_f = (dev == 0) & (c_f == 0)
            x = select(first_f, mb, fwd_act)
            y = fwd_c(params_shard, x, c_f)
            slot_f = m_f % K
            resid = jax.tree_util.tree_map(
                lambda r, v: r.at[c_f, slot_f].set(
                    jnp.where(valid_f, v, r[c_f, slot_f])), resid, x)
            take = (dev == S - 1) & (c_f == C - 1) & valid_f
            loss_m, (gp, gy) = jax.value_and_grad(
                scaled_post, argnums=(0, 1))(post_params, y, lb)
            loss_acc = loss_acc + jnp.where(take, loss_m, 0.0)
            g_post = jax.tree_util.tree_map(
                lambda g, d: g + jnp.where(take, d, 0), g_post, gp)
            pending_ct = select(take, gy, pending_ct)
            y_send = select(valid_f, y, zeros_like_t(y))

            # ---- one rotation each way; the arriving value is exactly the
            # receiver's next scheduled operand (chart +1-tick property)
            fwd_act = jax.lax.ppermute(
                y_send, axis_name, [(i, (i + 1) % S) for i in range(S)])
            bwd_grad = jax.lax.ppermute(
                dx_send, axis_name, [(i, (i - 1) % S) for i in range(S)])
            return (fwd_act, bwd_grad, pending_ct, resid, g_stk, g_post,
                    d_micro, loss_acc), None

        act_proto = jax.tree_util.tree_map(lambda x: jnp.zeros_like(x[0]),
                                           micro)
        y_shape = jax.eval_shape(
            lambda a: fwd_c(params_shard, a, 0), act_proto)
        zvary = lambda shape, dtype: jax.lax.pcast(
            jnp.zeros(shape, dtype), (axis_name,), to="varying")
        carry0 = (
            jax.tree_util.tree_map(
                lambda x: zvary(tuple(x.shape), x.dtype), act_proto),
            jax.tree_util.tree_map(
                lambda x: zvary(tuple(x.shape), x.dtype), act_proto),
            jax.tree_util.tree_map(
                lambda s: zvary(tuple(s.shape), s.dtype), y_shape),
            jax.tree_util.tree_map(
                lambda x: zvary((C, K) + tuple(x.shape), x.dtype),
                act_proto),
            zeros_like_t(params_shard),
            zeros_like_t(post_params),
            jax.tree_util.tree_map(jnp.zeros_like, micro),
            jax.lax.pcast(jnp.float32(0.0), (axis_name,), to="varying"),
        )
        (fwd_act, bwd_grad, pending_ct, resid, g_stk, g_post, d_micro,
         loss_acc), _ = jax.lax.scan(tick, carry0, jnp.arange(T))
        loss = jax.lax.psum(loss_acc, axis_name)
        g_post = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, axis_name), g_post)
        d_micro = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, axis_name), d_micro)
        return loss, g_stk, g_post, d_micro

    return per_shard



def spmd_interleaved_pipeline_fn(stage_fn: Callable, num_stages: int, num_micro: int,
                                 num_chunks: int, axis_name: str = "pipe"):
    """Compiled INTERLEAVED pipeline (virtual stages, ref
    PipelineParallelWithInterleave pipeline_parallel.py:461,:535).

    Each device holds ``num_chunks`` model chunks; logical stage
    L = chunk * num_stages + device, S = num_stages*num_chunks logical stages.
    Per tick every device runs all of its resident chunks (at most one
    microbatch each); activations ring-rotate via a single ppermute, and on
    wrap-around (device N-1 → device 0) they advance to the next chunk.
    Lockstep bubble note: every tick executes all C chunks per device, so
    the tick count grows to M + N*C - 1 at constant per-tick cost — the
    bubble is LARGER than num_chunks=1, not (N-1)/(M*C); the reference's
    interleave shrink needs one chunk-op per time slot (see
    spmd_interleaved_1f1b_train_fn's note).  chunks>1 here buys stage
    granularity (layer counts not divisible by the device count), not
    throughput.

    stage_fn(chunk_id, params_chunk, activation) -> activation
    params_shard: per-shard pytree whose leaves are [1, num_chunks, ...] —
    axis 0 is the size-1 pipe-shard dim shard_map leaves in place (pass the
    global leaves as [num_stages, num_chunks, ...] with in_specs P("pipe")).
    Returns the final outputs for all microbatches, replicated ring-wide.
    """

    def per_shard(params_shard, micro_batches):
        micro_batches = jax.tree_util.tree_map(
            lambda x: jax.lax.pcast(x, (axis_name,), to="varying"), micro_batches)
        dev = jax.lax.axis_index(axis_name)
        S = num_stages * num_chunks
        T = num_micro + S - 1

        def chunk_params(c):
            # leaves arrive as [1 (pipe shard), num_chunks, ...] under shard_map
            return jax.tree_util.tree_map(lambda p: p[0][c], params_shard)

        def tick(carry, t):
            acts, outputs = carry  # acts: [num_chunks] pytree-of-stacked slots

            def run_chunk(c, acts, outputs):
                L = c * num_stages + dev
                mb_idx = t - L
                valid = (mb_idx >= 0) & (mb_idx < num_micro)
                mb = jax.tree_util.tree_map(
                    lambda x: x[jnp.clip(mb_idx, 0, num_micro - 1)], micro_batches)
                act_c = jax.tree_util.tree_map(lambda a: a[c], acts)
                first = (L == 0)
                inp = jax.tree_util.tree_map(
                    lambda m, a: jnp.where(first, m, a), mb, act_c)
                out = stage_fn(c, chunk_params(c), inp)  # c is static (unrolled)
                out = jax.tree_util.tree_map(
                    lambda o, a: jnp.where(valid, o, a), out, act_c)
                done = (L == S - 1) & valid
                outputs = jax.tree_util.tree_map(
                    lambda os, o: os.at[jnp.clip(mb_idx, 0, num_micro - 1)].set(
                        jnp.where(done, o,
                                  os[jnp.clip(mb_idx, 0, num_micro - 1)])),
                    outputs, out)
                return out, outputs

            outs = []
            for c in range(num_chunks):
                o, outputs = run_chunk(c, acts, outputs)
                outs.append(o)
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs, axis=0), *outs)
            # one ring rotation for all chunks
            rotated = jax.lax.ppermute(
                stacked, axis_name,
                [(i, (i + 1) % num_stages) for i in range(num_stages)])
            # device 0 receives from device N-1: that activation advances to
            # the NEXT chunk; other devices stay within the same chunk
            def reroute(r):
                shifted = jnp.concatenate(
                    [jnp.zeros_like(r[:1]), r[:-1]], axis=0)  # chunk c ← c-1
                return jnp.where(dev == 0, shifted, r)

            acts_new = jax.tree_util.tree_map(reroute, rotated)
            return (acts_new, outputs), None

        act0 = jax.tree_util.tree_map(
            lambda x: jnp.zeros((num_chunks,) + tuple(x.shape[1:]), x.dtype) +
            jnp.zeros_like(x[0]),  # inherit vma (pipe-varying) from the input
            micro_batches)
        out_shape = jax.eval_shape(
            lambda a: stage_fn(0, chunk_params(0), a),
            jax.tree_util.tree_map(lambda x: x[0], micro_batches))
        outputs0 = jax.tree_util.tree_map(
            lambda s: jax.lax.pcast(
                jnp.zeros((num_micro,) + tuple(s.shape), s.dtype), (axis_name,),
                to="varying"), out_shape)
        (acts, outputs), _ = jax.lax.scan(tick, (act0, outputs0), jnp.arange(T))
        return jax.tree_util.tree_map(lambda o: jax.lax.psum(o, axis_name), outputs)

    return per_shard
