"""Model wrappers per parallel mode (ref fleet/meta_parallel/model wrappers
chosen in fleet/model.py:125-172)."""
from __future__ import annotations

from ....nn.layer_base import Layer


class _MetaParallelBase(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self._hcg = hcg
        self._strategy = strategy

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)


class TensorParallel(_MetaParallelBase):
    """TP wrapper (ref meta_parallel/tensor_parallel.py). On TPU the TP
    collectives come from the mp layers' shardings under pjit; this wrapper
    only marks the model and syncs non-distributed params at init (the
    reference broadcasts them over the mp group — replication under GSPMD)."""


class ShardedDataParallel(_MetaParallelBase):
    """ZeRO wrapper (ref sharding_parallel.py + group_sharded_*). Param/opt
    sharding over the 'sharding' mesh axis is applied by the ParallelEngine
    (fsdp=True); eager behavior is identical to DataParallel."""
