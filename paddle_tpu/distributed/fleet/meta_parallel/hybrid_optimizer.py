"""Hybrid-parallel optimizer wrapper (ref: fleet/meta_parallel/
dygraph_optimizer/hybrid_parallel_optimizer.py — HybridParallelOptimizer:186,
HybridParallelClipGrad:45; hybrid_parallel_util.py fused_allreduce_gradients:206).

TPU-native: under pjit, DP grad reduction and cross-group norm reduction are
GSPMD-inserted; eagerly (multi-process) we reduce via the collectives API.
"""
from __future__ import annotations

import jax.numpy as jnp

from ....framework.core import Tensor
from ....nn.clip import ClipGradByGlobalNorm
from ...collective import ReduceOp, all_reduce
from ...env import get_world_size


class HybridParallelClipGrad:
    """Global-norm clip with the norm allreduced across mp/pp/sharding groups
    (ref hybrid_parallel_optimizer.py:45). On a single-controller mesh all
    params are visible, so the global norm is already global; multi-process
    eager adds the cross-process reduction."""

    def __init__(self, clip, hcg):
        self._clip = clip
        self._hcg = hcg

    def __call__(self, params_grads):
        grads = [g for _, g in params_grads if g is not None]
        if not grads:
            return params_grads
        # single traced reduction (see nn/clip.py ClipGradByGlobalNorm):
        # the squared norm and the scale stay 0-d device scalars — the only
        # cross-process hop is the all_reduce itself
        sq = sum(jnp.sum(jnp.square(g.value.astype(jnp.float32)))
                 for g in grads)
        if get_world_size() > 1:
            t = Tensor(sq)
            all_reduce(t, op=ReduceOp.SUM)
            sq = t.value
        clip_norm = getattr(self._clip, "clip_norm", 1.0)
        scale = jnp.minimum(clip_norm / jnp.maximum(jnp.sqrt(sq), 1e-12), 1.0)
        return [(p, None if g is None else
                 Tensor((g.value * scale).astype(g.value.dtype)))
                for p, g in params_grads]


class HybridParallelOptimizer:
    """Ref hybrid_parallel_optimizer.py:186."""

    def __init__(self, optimizer, hcg, strategy):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        if optimizer._grad_clip is not None and isinstance(
                optimizer._grad_clip, ClipGradByGlobalNorm):
            optimizer._grad_clip = HybridParallelClipGrad(optimizer._grad_clip, hcg)

    def _dp_sync(self):
        """fused_allreduce_gradients parity (hybrid_parallel_util.py:206)."""
        if get_world_size() <= 1:
            return
        for p in self._inner_opt._get_params():
            if p.grad is not None:
                all_reduce(p.grad, op=ReduceOp.AVG)

    def step(self):
        self._dp_sync()
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad(*a, **k)

    clear_gradients = clear_grad

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    def get_lr(self):
        return self._inner_opt.get_lr()

    def set_lr(self, v):
        return self._inner_opt.set_lr(v)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)
