"""Pipeline model description (ref: fleet/meta_parallel/parallel_layers/
pp_layers.py — PipelineLayer:209, LayerDesc/SharedLayerDesc, SegmentLayers:93).

PipelineLayer holds the full layer list plus a segmentation into stages.
TPU twist: every process can see all stages (single-controller SPMD), so the
"local stage" concept is a *slice view* used by the 1F1B host schedule and by
the compiled stage-scan path; there is no per-rank module surgery.
"""
from __future__ import annotations

import math
import re
from typing import Callable, List, Optional

import numpy as np

from ....nn.layer_base import Layer
from ....nn.layer.container import LayerList


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_cls, Layer):
            raise TypeError("LayerDesc expects a Layer subclass")

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """Tied layers across stages (ref pp_layers.py SharedLayerDesc — e.g.
    tied embeddings). On TPU the weight is simply the same Parameter object
    in both stages; gradient summation happens naturally in jax.grad, which
    replaces allreduce_shared_weight_gradients."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Ref pp_layers.py:93 — split N layers into M stages uniformly or by
    parameter count."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.layers_desc = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self) -> List[int]:
        n = len(self.layers_desc)
        if self.method == "uniform":
            return self.uniform(n, self.num_parts)
        if self.method.startswith("layer:"):
            pat = self.method.split(":", 1)[1]
            matches = [i for i, d in enumerate(self.layers_desc)
                       if re.search(pat, getattr(d, "layer_cls", type(d)).__name__
                                    if isinstance(d, LayerDesc) else type(d).__name__)]
            assert len(matches) >= self.num_parts
            per = len(matches) // self.num_parts
            result = [0]
            for i in range(1, self.num_parts):
                result.append(matches[i * per])
            result.append(n)
            return result
        raise ValueError(f"unknown segment method {self.method}")

    @staticmethod
    def uniform(num_items, num_parts):
        result = [0] * (num_parts + 1)
        part_size = math.floor(num_items / num_parts)
        extra = num_items % num_parts
        for i in range(1, num_parts + 1):
            result[i] = result[i - 1] + part_size + (1 if i <= extra else 0)
        return result


class PipelineLayer(Layer):
    """Ref pp_layers.py:209."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, recompute_ctx=None,
                 num_virtual_pipeline_stages=None):
        super().__init__()
        self._layers_desc = list(layers)
        self._loss_fn = loss_fn
        self._topo = topology
        self._num_stages = num_stages or 1
        self._seg_method = seg_method
        self._recompute_interval = recompute_interval
        self.segment_parts = SegmentLayers(self._layers_desc, self._num_stages,
                                           seg_method).do_segment()
        # build ALL layers (single-controller SPMD: no per-rank pruning)
        built = []
        self._shared_layers = {}
        for d in self._layers_desc:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared_layers:
                    built.append(_SharedView(self._shared_layers[d.layer_name],
                                             d.forward_func))
                else:
                    layer = d.build_layer()
                    self._shared_layers[d.layer_name] = layer
                    built.append(layer)
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            elif callable(d):
                built.append(_FuncLayer(d))
            else:
                raise TypeError(f"unsupported pipeline item {d!r}")
        self.run_function = LayerList(built)

    def get_num_stages(self):
        return self._num_stages

    def stage_layers(self, stage_id: int) -> List[Layer]:
        lo, hi = self.segment_parts[stage_id], self.segment_parts[stage_id + 1]
        return list(self.run_function)[lo:hi]

    def forward_stage(self, x, stage_id: int):
        for layer in self.stage_layers(stage_id):
            x = layer(x) if not isinstance(x, tuple) else layer(*x)
        return x

    def forward(self, x):
        for layer in self.run_function:
            x = layer(x) if not isinstance(x, tuple) else layer(*x)
        return x


class _FuncLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *args):
        return self._fn(*args)


class _SharedView(Layer):
    def __init__(self, shared: Layer, forward_func: Optional[Callable]):
        super().__init__()
        self.add_sublayer("shared", shared)
        self._forward_func = forward_func

    def forward(self, *args):
        if self._forward_func is not None:
            return self._forward_func(self.shared, *args)
        return self.shared(*args)
