"""TP-aware RNG (ref: fleet/meta_parallel/parallel_layers/random.py:35
RNGStatesTracker — per-mode seeds so TP ranks agree on replicated dropout and
differ on sharded dropout)."""
from __future__ import annotations

import contextlib

import jax

from ....framework.random import Generator


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        self.seeds_.add(seed)
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.states_[name] = Generator(seed)

    def get_states_tracker(self):
        return dict(self.states_)

    def set_states_tracker(self, states):
        self.states_ = states

    @contextlib.contextmanager
    def rng_state(self, name="model_parallel_rng"):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        from ....framework import random as global_random

        orig = global_random._default_generator
        global_random._default_generator = self.states_[name]
        try:
            yield
        finally:
            global_random._default_generator = orig


RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    import random as pyrandom

    from ...fleet import get_hybrid_communicate_group

    try:
        hcg = get_hybrid_communicate_group()
        rank = hcg.get_model_parallel_rank() if hcg else 0
    except Exception:
        rank = 0
    seed = seed or (pyrandom.randint(0, 100000) + 100)
    global_seed = seed
    local_seed = seed + 1024 + rank
    RNG_STATE_TRACKER.reset()
    RNG_STATE_TRACKER.add("global_seed", global_seed)
    RNG_STATE_TRACKER.add("local_seed", local_seed)
