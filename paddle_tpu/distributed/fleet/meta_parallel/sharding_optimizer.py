"""ZeRO sharding optimizers (ref: dygraph_optimizer/
dygraph_sharding_optimizer.py:29 stage-1; sharding/group_sharded_stage2.py:46,
group_sharded_stage3.py:60).

TPU-native: true ZeRO lives in the compiled path — ParallelEngine(fsdp=True)
shards params + optimizer slots over the 'sharding' mesh axis and GSPMD
inserts the stage-3 allgather/reduce-scatter pattern. These classes keep the
eager API: stage-1 semantics (each rank owns a param subset's optimizer
state) degrade gracefully to the plain optimizer in single-process eager.
"""
from __future__ import annotations

from ...env import get_world_size


class DygraphShardingOptimizer:
    """Ref dygraph_sharding_optimizer.py:29."""

    def __init__(self, hcg=None, user_defined_strategy=None, params=None,
                 inner_optimizer_class=None, **inner_kw):
        if inner_optimizer_class is not None:
            self._inner_opt = inner_optimizer_class(parameters=params, **inner_kw)
        else:
            self._inner_opt = inner_kw.get("optimizer")
        self._hcg = hcg

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, *a, **k):
        self._inner_opt.clear_grad()

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)


class GroupShardedOptimizerStage2(DygraphShardingOptimizer):
    """Ref group_sharded_stage2.py:46 — grads+opt-state sharded. Compiled
    path: ParallelEngine(fsdp=True)."""

    def __init__(self, params, optim, group=None, offload=False, device="tpu", **kw):
        self._inner_opt = optim
        self._params = params
        self._offload = offload


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm=False):
    """Ref python/paddle/distributed/sharding/group_sharded.py entry; real
    implementation in paddle_tpu.distributed.sharding."""
    from ...sharding import group_sharded_parallel as _impl

    return _impl(model, optimizer, level=level, scaler=scaler, group=group,
                 offload=offload, sync_buffers=sync_buffers,
                 buffer_max_size=buffer_max_size, segment_size=segment_size,
                 sync_comm=sync_comm)
