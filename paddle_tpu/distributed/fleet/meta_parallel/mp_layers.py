"""Tensor-parallel layers (ref: fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding:35, ColumnParallelLinear:173, RowParallelLinear:332,
ParallelCrossEntropy:498; mp_ops.py _c_identity/_c_concat/_mp_allreduce).

TPU-native redesign: these layers do NOT slice weights per rank. They carry
full logical shapes + a GSPMD PartitionSpec on each parameter; under pjit the
compiler assigns each chip its shard and inserts the same collectives the
reference issues by hand (allreduce after RowParallel ≈ psum XLA inserts;
identity-with-allreduce-backward of ColumnParallel ≈ GSPMD's reverse-mode
resharding). Eagerly (one process) they behave exactly like dense layers, so
numerics match the single-device reference — the parallelism appears when
the surrounding train step is pjit-ed over a mesh with a "tensor" axis.

For the explicit shard_map variant (needed by e.g. ParallelCrossEntropy's
vocab-sharded softmax), ``paddle_tpu.parallel.api`` provides psum/all_gather
helpers that are no-ops off-mesh.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ....nn import functional as F
from ....nn.initializer import Constant, Normal, XavierUniform
from ....nn.layer_base import Layer
from ....framework.core import Tensor
from ....framework.dispatch import apply_op
from ....parallel.api import shard_constraint


class VocabParallelEmbedding(Layer):
    """Embedding sharded over the vocab dim (ref mp_layers.py:35)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None,
                 name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=XavierUniform())
        self.weight.pspec = P("tensor", None)
        self.weight.is_distributed = True

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return shard_constraint(out, P("data", None, None))


class ColumnParallelLinear(Layer):
    """Weight sharded on output dim (ref mp_layers.py:173)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=None,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr)
        self.weight.pspec = P(None, "tensor")
        self.weight.is_distributed = True
        self.bias = None
        if has_bias or has_bias is None:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.pspec = P("tensor")
            self.bias.is_distributed = True

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            # replicate activations (GSPMD all-gathers the tensor-dim shards)
            return shard_constraint(out, P("data"))
        return shard_constraint(out, P("data", None, "tensor"))


class RowParallelLinear(Layer):
    """Weight sharded on input dim; output psum (ref mp_layers.py:332)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter([in_features, out_features], attr=weight_attr)
        self.weight.pspec = P("tensor", None)
        self.weight.is_distributed = True
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter([out_features], is_bias=True)
            self.bias.pspec = P()

    def forward(self, x):
        if self.input_is_parallel:
            x = shard_constraint(x, P("data", None, "tensor"))
        out = F.linear(x, self.weight, self.bias)
        # contraction over the sharded dim → XLA inserts the psum the
        # reference does via _mp_allreduce (mp_ops.py:219)
        return shard_constraint(out, P("data"))


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax CE (ref mp_layers.py:498,
    mp_ops.py:_c_softmax_with_cross_entropy:375).

    Under pjit with logits sharded on the vocab axis, the log-softmax's
    reduction over vocab becomes an XLA cross-shard reduction automatically;
    eager single-process path is plain CE.
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
