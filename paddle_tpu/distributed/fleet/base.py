"""Fleet core (ref: python/paddle/distributed/fleet/fleet.py,
base/distributed_strategy.py:111 DistributedStrategy over
framework/distributed_strategy.proto).

TPU-native: fleet.init builds the global jax Mesh from
strategy.hybrid_configs (= CommunicateTopology dims) and registers it; the
"distributed model/optimizer" wrappers select the parallel engine
(DP sharding / TP layers / PP schedule) exactly like model.py:125-172 picks
wrappers by parallel mode.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional

import jax

from ..collective import set_global_mesh
from ..env import ParallelEnv, init_parallel_env
from ..topology import CommunicateTopology, HybridCommunicateGroup, build_mesh


@dataclasses.dataclass
class HybridConfig:
    dp_degree: int = 1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sep_degree: int = 1
    ep_degree: int = 1
    cp_degree: int = 1  # context parallel (NEW — absent in reference, SURVEY §5.7)


class DistributedStrategy:
    """Ref base/distributed_strategy.py:111 — typed config; proto replaced by
    plain dataclass fields + dict round-trip."""

    def __init__(self):
        self.hybrid_configs_ = HybridConfig()
        self.amp = False
        self.amp_configs: Dict[str, Any] = {"init_loss_scaling": 32768.0,
                                            "use_pure_fp16": False, "use_bf16": True}
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {"checkpoints": []}
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = {"stage": 1, "degree": 1}
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = {"accumulate_steps": 1,
                                                 "micro_batch_size": 1,
                                                 "schedule_mode": "1F1B"}
        self.gradient_merge = False
        self.gradient_merge_configs: Dict[str, Any] = {"k_steps": 1, "avg": True}
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.localsgd = False
        self.heter_ccl_mode = False
        self.find_unused_parameters = False
        self.fuse_grad_size_in_MB = 32
        self.last_comm_group_size_MB = 1
        self.tensor_parallel = False
        self.tensor_parallel_configs: Dict[str, Any] = {"tensor_parallel_degree": 1}

    @property
    def hybrid_configs(self):
        return dataclasses.asdict(self.hybrid_configs_)

    @hybrid_configs.setter
    def hybrid_configs(self, cfg: Dict[str, int]):
        for k, v in cfg.items():
            if hasattr(self.hybrid_configs_, k):
                setattr(self.hybrid_configs_, k, v)

    def to_dict(self):
        return {k: (dataclasses.asdict(v) if dataclasses.is_dataclass(v) else v)
                for k, v in self.__dict__.items()}


class PaddleCloudRoleMaker:
    def __init__(self, is_collective=False, **kwargs):
        self._is_collective = is_collective

    def _generate_role(self):
        pass


UserDefinedRoleMaker = PaddleCloudRoleMaker


class Fleet:
    """Ref fleet.py Fleet. Singleton via fleet_instance."""

    def __init__(self):
        self._is_initialized = False
        self.strategy: Optional[DistributedStrategy] = None
        self.mesh = None
        self.hcg: Optional[HybridCommunicateGroup] = None
        self._topology: Optional[CommunicateTopology] = None
        self._env = None

    def init(self, role_maker=None, is_collective=False, strategy=None):
        """Ref fleet.py:169 + _init_hybrid_parallel_env :385."""
        self.strategy = strategy or DistributedStrategy()
        self._env = init_parallel_env()
        hc = self.strategy.hybrid_configs_
        n_dev = jax.device_count()
        declared = (hc.dp_degree * hc.mp_degree * hc.pp_degree * hc.sharding_degree *
                    hc.sep_degree * hc.ep_degree * hc.cp_degree)
        if declared <= 1 and n_dev > 1:
            # default: pure data parallel over all devices
            hc.dp_degree = n_dev
        elif hc.dp_degree == -1 or hc.dp_degree == 0:
            rest = (hc.mp_degree * hc.pp_degree * hc.sharding_degree * hc.sep_degree *
                    hc.ep_degree * hc.cp_degree)
            hc.dp_degree = max(n_dev // rest, 1)
        self.mesh = build_mesh(dp=hc.dp_degree, mp=hc.mp_degree, pp=hc.pp_degree,
                               sharding=hc.sharding_degree, sep=hc.sep_degree,
                               ep=hc.ep_degree, cp=hc.cp_degree)
        set_global_mesh(self.mesh)
        self._topology = CommunicateTopology(
            hybrid_group_names=["data", "pipe", "sharding", "model"],
            dims=[hc.dp_degree, hc.pp_degree, hc.sharding_degree, hc.mp_degree])
        self.hcg = HybridCommunicateGroup(self._topology, self._env.rank
                                          if self._env.rank < self._topology.world_size()
                                          else 0)
        self._is_initialized = True
        return self

    def distributed_model(self, model):
        """Ref model.py:30, wrap-by-mode logic :125-172."""
        if not self._is_initialized:
            self.init()
        hc = self.strategy.hybrid_configs_
        from .meta_parallel.parallel_model import TensorParallel, ShardedDataParallel
        from .meta_parallel.pipeline_parallel import PipelineParallel
        from .meta_parallel.pp_layers import PipelineLayer

        if hc.pp_degree > 1:
            assert isinstance(model, PipelineLayer), \
                "pp_degree > 1 requires the model be a PipelineLayer"
            return PipelineParallel(model, self.hcg, self.strategy)
        if hc.mp_degree > 1:
            return TensorParallel(model, self.hcg, strategy=self.strategy)
        from ..parallel import DataParallel

        if hc.sharding_degree > 1:
            return ShardedDataParallel(model, self.hcg, strategy=self.strategy)
        return DataParallel(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        """Ref fleet.py:1044. Static mode returns the meta-optimizer stack
        (amp/recompute/sharding/gradient-merge program passes); dygraph wraps
        with HybridParallelOptimizer."""
        if not self._is_initialized:
            self.init()
        if strategy is not None:
            self.strategy = strategy
        from .meta_optimizers import rewrite_inner_optimizer

        optimizer = rewrite_inner_optimizer(optimizer, self.strategy)
        from ...static.graph import in_static_mode

        if in_static_mode():
            from .meta_optimizers import StaticFleetOptimizer

            return StaticFleetOptimizer(optimizer, self.strategy)
        from .meta_parallel.hybrid_optimizer import HybridParallelOptimizer

        return HybridParallelOptimizer(optimizer, self.hcg, self.strategy)

    def worker_index(self):
        return self._env.rank if self._env else 0

    def worker_num(self):
        return self._env.world_size if self._env else 1

    def stop_worker(self):
        pass


fleet_instance = Fleet()
