"""Static-graph meta-optimizers (ref python/paddle/distributed/fleet/
meta_optimizers/ — strategy-driven program rewriters applied by priority:
amp_optimizer.py, recompute_optimizer.py, gradient_merge_optimizer.py,
sharding_optimizer.py, ...).

TPU-native: each reference meta-optimizer rewrites ProgramDesc ops by hand;
here they are thin adapters that select passes from
paddle_tpu.distributed.passes (which rewrite the recorded-op Program) in the
same priority order, driven by the same DistributedStrategy flags.  The
comm-injection meta-optimizers (raw_program_optimizer's allreduce insertion)
have no adapter: GSPMD emits gradient collectives inside the jitted train
step.
"""
from __future__ import annotations

from typing import List, Optional

from ..passes import new_pass

__all__ = ["MetaOptimizerBase", "AMPOptimizer", "RecomputeOptimizer",
           "GradientMergeOptimizer", "ShardingOptimizer", "LambOptimizer",
           "LarsOptimizer", "LocalSGDOptimizer",
           "apply_meta_optimizers", "StaticFleetOptimizer"]


class MetaOptimizerBase:
    """ref meta_optimizer_base.py — can_apply gating + priority ordering."""

    priority = 0
    name = "base"

    def __init__(self, strategy):
        self.strategy = strategy

    def can_apply(self) -> bool:
        return False

    def passes(self) -> List:
        return []


class AMPOptimizer(MetaOptimizerBase):
    """ref amp_optimizer.py → list-based low-precision compute."""

    priority = 10
    name = "amp"

    def can_apply(self):
        return bool(getattr(self.strategy, "amp", False))

    def passes(self):
        cfg = getattr(self.strategy, "amp_configs", {}) or {}
        use_bf16 = cfg.get("use_bf16", True)
        return [new_pass("auto_parallel_bf16" if use_bf16
                         else "auto_parallel_fp16",
                         {"custom_white_list":
                          cfg.get("custom_white_list")})]


class RecomputeOptimizer(MetaOptimizerBase):
    """ref recompute_optimizer.py → remat via jax.checkpoint."""

    priority = 20
    name = "recompute"

    def can_apply(self):
        return bool(getattr(self.strategy, "recompute", False))

    def passes(self):
        cfg = getattr(self.strategy, "recompute_configs", {}) or {}
        ckpts = cfg.get("checkpoints") or None
        return [new_pass("auto_parallel_recompute",
                         {"checkpoints": set(ckpts) if ckpts else None})]


class ShardingOptimizer(MetaOptimizerBase):
    """ref sharding_optimizer.py (static ZeRO) → GSPMD sharding annotation."""

    priority = 30
    name = "sharding"

    def can_apply(self):
        return bool(getattr(self.strategy, "sharding", False))

    def passes(self):
        cfg = getattr(self.strategy, "sharding_configs", {}) or {}
        return [new_pass("auto_parallel_sharding",
                         {"stage": cfg.get("stage", 1)})]


class GradientMergeOptimizer(MetaOptimizerBase):
    """ref gradient_merge_optimizer.py → pure k-step accumulation. Last so it
    wraps the optimizer the earlier phases configured."""

    priority = 40
    name = "gradient_merge"

    def can_apply(self):
        s = self.strategy
        return bool(getattr(s, "gradient_merge", False)) and \
            int((getattr(s, "gradient_merge_configs", {}) or {}).get("k_steps", 1)) > 1

    def passes(self):
        cfg = getattr(self.strategy, "gradient_merge_configs", {}) or {}
        return [new_pass("auto_parallel_gradient_merge",
                         {"k_steps": cfg.get("k_steps", 1),
                          "avg": cfg.get("avg", True)})]


class LambOptimizer(MetaOptimizerBase):
    """ref lamb_optimizer.py — strategy.lamb swaps the inner optimizer for
    LAMB (layer-adaptive moments for large-batch training)."""

    priority = 5
    name = "lamb"

    def can_apply(self):
        return bool(getattr(self.strategy, "lamb", False))

    def rewrite_optimizer(self, inner):
        from ...optimizer import Lamb

        cfg = getattr(self.strategy, "lamb_configs", {}) or {}
        import re

        exclude = [re.compile(pat) for pat in cfg.get("exclude_from_weight_decay", [])]
        # carry the scheduler object (not a frozen float) and the grad clip;
        # parameters may be unbound in static mode (minimize binds them)
        return Lamb(learning_rate=inner._learning_rate,
                    lamb_weight_decay=cfg.get("lamb_weight_decay", 0.01),
                    exclude_from_weight_decay_fn=(
                        (lambda p: any(r.search(getattr(p, "name", "") or "")
                                       for r in exclude)) if exclude else None),
                    grad_clip=inner._grad_clip,
                    parameters=inner._parameter_list)


class LarsOptimizer(MetaOptimizerBase):
    """ref lars_optimizer.py — strategy.lars swaps Momentum for LARS."""

    priority = 5
    name = "lars"

    def can_apply(self):
        return bool(getattr(self.strategy, "lars", False))

    def rewrite_optimizer(self, inner):
        from ...optimizer import Lars

        cfg = getattr(self.strategy, "lars_configs", {}) or {}
        return Lars(learning_rate=inner._learning_rate,
                    momentum=cfg.get("momentum", 0.9),
                    lars_coeff=cfg.get("lars_coeff", 0.001),
                    lars_weight_decay=cfg.get("lars_weight_decay", 0.0005),
                    grad_clip=inner._grad_clip,
                    parameters=inner._parameter_list)


class LocalSGDOptimizer(MetaOptimizerBase):
    """ref localsgd_optimizer.py — skip per-step gradient allreduce; average
    PARAMETERS across data-parallel workers every k_steps. TPU-native form:
    the wrapper steps the inner optimizer on purely local grads and every
    k_steps runs an eager all_reduce(param)/world_size over the default
    group (the eager DP path; the GSPMD engine's per-step psum is already
    the k=1 case)."""

    priority = 45
    name = "localsgd"

    def can_apply(self):
        return bool(getattr(self.strategy, "localsgd", False))

    def rewrite_optimizer(self, inner):
        cfg = getattr(self.strategy, "localsgd_configs", {}) or {}
        return _LocalSGDWrapper(inner, int(cfg.get("k_steps", 1)))


class _LocalSGDWrapper:
    _OWN = ("_inner", "_k", "_t")

    def __init__(self, inner, k_steps):
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_k", max(1, k_steps))
        object.__setattr__(self, "_t", 0)

    def step(self):
        self._inner.step()
        object.__setattr__(self, "_t", self._t + 1)
        if self._t % self._k == 0:
            self._average_params()

    def _average_params(self):
        # average over the axis all_reduce actually reduces (the dp group),
        # NOT the global world size — and only when a reduction happened
        from ...distributed.collective import _axis_size, all_reduce

        n = _axis_size("data")
        if n <= 1:
            return
        for p in self._inner._get_params():
            all_reduce(p)
            p.set_value(p.value / n)

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def __setattr__(self, item, value):
        # attribute writes meant for the optimizer (e.g. HybridParallel's
        # _grad_clip replacement) must land on the inner, not the proxy
        if item in self._OWN:
            object.__setattr__(self, item, value)
        else:
            setattr(self._inner, item, value)


# DGC (deep gradient compression, ref dgc_optimizer.py / dgc_op.cc) is a
# documented NON-GOAL: it sparsifies the NCCL allreduce payload with top-k
# gradient selection, which has no profitable mapping onto XLA's dense
# ICI collectives (a sparse allgather of (idx, val) pairs is slower than the
# fused dense psum on TPU interconnect). The strategy flag is accepted and
# ignored with a warning for migration compatibility.
_META_OPTIMIZERS = [LambOptimizer, LarsOptimizer, AMPOptimizer,
                    RecomputeOptimizer, ShardingOptimizer,
                    GradientMergeOptimizer, LocalSGDOptimizer]


def rewrite_inner_optimizer(inner, strategy):
    """Apply the optimizer-swapping meta-optimizers (lamb/lars/localsgd —
    ref meta_optimizers that replace the inner optimizer rather than rewrite
    the program). DGC is accepted-but-ignored with a warning (non-goal: top-k
    sparsified allreduce loses to dense XLA collectives on ICI)."""
    if getattr(strategy, "dgc", False):
        import warnings

        warnings.warn(
            "strategy.dgc is a documented non-goal on TPU (dense XLA "
            "collectives over ICI outperform top-k sparsified allreduce); "
            "training proceeds without gradient compression", UserWarning)
    for cls in sorted(_META_OPTIMIZERS, key=lambda c: c.priority):
        mo = cls(strategy)
        if hasattr(mo, "rewrite_optimizer") and mo.can_apply():
            inner = mo.rewrite_optimizer(inner)
    return inner


def apply_meta_optimizers(main_program, startup_program, strategy):
    """Apply every applicable meta-optimizer's passes in priority order
    (the analogue of fleet's meta-optimizer selection loop in
    ref fleet/base/strategy_compiler.py)."""
    applied = []
    for cls in sorted(_META_OPTIMIZERS, key=lambda c: c.priority):
        mo = cls(strategy)
        if mo.can_apply():
            for p in mo.passes():
                p.apply([main_program], [startup_program])
            applied.append(mo.name)
    return applied


class StaticFleetOptimizer:
    """fleet.distributed_optimizer(...) in static mode (ref fleet.py:1044 →
    minimize applies the meta-optimizer stack then the inner optimizer)."""

    def __init__(self, inner_opt, strategy):
        self._inner = inner_opt
        self._strategy = strategy
        self.applied_meta_optimizers: List[str] = []

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        result = self._inner.minimize(loss, startup_program, parameters,
                                      no_grad_set)
        prog = loss.program
        self.applied_meta_optimizers = apply_meta_optimizers(
            prog, startup_program, self._strategy)
        return result

    def __getattr__(self, item):
        return getattr(self._inner, item)
