"""Static-graph meta-optimizers (ref python/paddle/distributed/fleet/
meta_optimizers/ — strategy-driven program rewriters applied by priority:
amp_optimizer.py, recompute_optimizer.py, gradient_merge_optimizer.py,
sharding_optimizer.py, ...).

TPU-native: each reference meta-optimizer rewrites ProgramDesc ops by hand;
here they are thin adapters that select passes from
paddle_tpu.distributed.passes (which rewrite the recorded-op Program) in the
same priority order, driven by the same DistributedStrategy flags.  The
comm-injection meta-optimizers (raw_program_optimizer's allreduce insertion)
have no adapter: GSPMD emits gradient collectives inside the jitted train
step.
"""
from __future__ import annotations

from typing import List, Optional

from ..passes import new_pass

__all__ = ["MetaOptimizerBase", "AMPOptimizer", "RecomputeOptimizer",
           "GradientMergeOptimizer", "ShardingOptimizer",
           "apply_meta_optimizers", "StaticFleetOptimizer"]


class MetaOptimizerBase:
    """ref meta_optimizer_base.py — can_apply gating + priority ordering."""

    priority = 0
    name = "base"

    def __init__(self, strategy):
        self.strategy = strategy

    def can_apply(self) -> bool:
        return False

    def passes(self) -> List:
        return []


class AMPOptimizer(MetaOptimizerBase):
    """ref amp_optimizer.py → list-based low-precision compute."""

    priority = 10
    name = "amp"

    def can_apply(self):
        return bool(getattr(self.strategy, "amp", False))

    def passes(self):
        cfg = getattr(self.strategy, "amp_configs", {}) or {}
        use_bf16 = cfg.get("use_bf16", True)
        return [new_pass("auto_parallel_bf16" if use_bf16
                         else "auto_parallel_fp16",
                         {"custom_white_list":
                          cfg.get("custom_white_list")})]


class RecomputeOptimizer(MetaOptimizerBase):
    """ref recompute_optimizer.py → remat via jax.checkpoint."""

    priority = 20
    name = "recompute"

    def can_apply(self):
        return bool(getattr(self.strategy, "recompute", False))

    def passes(self):
        cfg = getattr(self.strategy, "recompute_configs", {}) or {}
        ckpts = cfg.get("checkpoints") or None
        return [new_pass("auto_parallel_recompute",
                         {"checkpoints": set(ckpts) if ckpts else None})]


class ShardingOptimizer(MetaOptimizerBase):
    """ref sharding_optimizer.py (static ZeRO) → GSPMD sharding annotation."""

    priority = 30
    name = "sharding"

    def can_apply(self):
        return bool(getattr(self.strategy, "sharding", False))

    def passes(self):
        cfg = getattr(self.strategy, "sharding_configs", {}) or {}
        return [new_pass("auto_parallel_sharding",
                         {"stage": cfg.get("stage", 1)})]


class GradientMergeOptimizer(MetaOptimizerBase):
    """ref gradient_merge_optimizer.py → pure k-step accumulation. Last so it
    wraps the optimizer the earlier phases configured."""

    priority = 40
    name = "gradient_merge"

    def can_apply(self):
        s = self.strategy
        return bool(getattr(s, "gradient_merge", False)) and \
            int((getattr(s, "gradient_merge_configs", {}) or {}).get("k_steps", 1)) > 1

    def passes(self):
        cfg = getattr(self.strategy, "gradient_merge_configs", {}) or {}
        return [new_pass("auto_parallel_gradient_merge",
                         {"k_steps": cfg.get("k_steps", 1),
                          "avg": cfg.get("avg", True)})]


_META_OPTIMIZERS = [AMPOptimizer, RecomputeOptimizer, ShardingOptimizer,
                    GradientMergeOptimizer]


def apply_meta_optimizers(main_program, startup_program, strategy):
    """Apply every applicable meta-optimizer's passes in priority order
    (the analogue of fleet's meta-optimizer selection loop in
    ref fleet/base/strategy_compiler.py)."""
    applied = []
    for cls in sorted(_META_OPTIMIZERS, key=lambda c: c.priority):
        mo = cls(strategy)
        if mo.can_apply():
            for p in mo.passes():
                p.apply([main_program], [startup_program])
            applied.append(mo.name)
    return applied


class StaticFleetOptimizer:
    """fleet.distributed_optimizer(...) in static mode (ref fleet.py:1044 →
    minimize applies the meta-optimizer stack then the inner optimizer)."""

    def __init__(self, inner_opt, strategy):
        self._inner = inner_opt
        self._strategy = strategy
        self.applied_meta_optimizers: List[str] = []

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        result = self._inner.minimize(loss, startup_program, parameters,
                                      no_grad_set)
        prog = loss.program
        self.applied_meta_optimizers = apply_meta_optimizers(
            prog, startup_program, self._strategy)
        return result

    def __getattr__(self, item):
        return getattr(self._inner, item)
