"""Elastic-restart chaos harness: seeded kill → rendezvous →
restore-latest-valid → continue.

The training twin of the serving fleet's chaos drill
(``inference/fleet.py`` + ``FaultPlan.fleet_chaos``): a scripted
:class:`~paddle_tpu.faults.FaultPlan` (``FaultPlan.train_chaos``) drives
an in-process incarnation loop through the full crash-recovery cycle the
launch CLI's ``--max_restart`` path performs across processes:

1. a ``kill`` fault raises :class:`SimulatedKill` in the step loop — the
   in-process SIGKILL: the incarnation's heartbeat stops cold;
2. a monitor :class:`ElasticManager` observes the lease expire
   (``health_check() → RESTART``) — failure *detection*, not assumption;
3. a fresh incarnation is built from scratch (the process-restart
   analogue), rendezvouses (``wait_for_np`` + ``update_endpoints``), and
   restores the latest *manifest-valid* checkpoint generation — torn
   writes and bit-flipped reads injected by the same plan have already
   been absorbed by the :class:`TrainCheckpointer` degradation ladder;
4. the step loop continues from the restored step.

Because every fault is scripted from one seed and the checkpoint carries
complete state (params, moments, scaler, LR, data cursor, RNG), the
post-restart trajectory must be **bit-exact** against an unkilled twin —
``tests/test_train_checkpoint.py`` pins that, and suite stage 8 gates it.

The harness is domain-agnostic: it owns membership, kill/restart
bookkeeping and transient-fault retries, while the caller's ``build``
factory returns a run object exposing ``restore() -> int``,
``step(i) -> float``, ``save(i)`` and optionally ``close()``.
"""
from __future__ import annotations

import socket
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from ...faults import (DataFeedFault, FaultInjector, FaultPlan,
                       SimulatedKill, StepFault)
from ...telemetry import TRAIN_RID as _TRAIN_RID
from ..launch.rendezvous import KVServer
from .elastic import ElasticManager, ElasticStatus

__all__ = ["ChaosReport", "ElasticChaosHarness", "free_port"]


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class ChaosReport:
    """What a chaos run actually did — the evidence the gate asserts on."""

    restarts: int = 0
    detected_kills: int = 0
    steps_run: int = 0
    transient_retries: int = 0
    losses: Dict[int, float] = field(default_factory=dict)
    fault_stats: Dict[str, Any] = field(default_factory=dict)
    completed: bool = False


class ElasticChaosHarness:
    """Run ``build(injector)`` incarnations under a scripted fault plan
    until ``total_steps`` complete or ``max_restarts`` is exhausted.

    ``build`` is called once per incarnation (fresh process analogue) and
    must return an object with:

    - ``restore() -> int`` — load the latest valid checkpoint into the
      fresh state, returning the first step index still to run (0 for a
      fresh start);
    - ``step(i) -> float`` — run train step ``i``, returning the host
      loss (may raise :class:`StepFault` / :class:`DataFeedFault` from
      injected sites — the harness retries those in place);
    - ``save(i)`` — checkpoint after step ``i`` (the run object decides
      cadence internally if it prefers; the harness calls it every step
      and expects it to be cheap when it declines);
    - ``close()`` — optional teardown.

    The ``kill`` site fires once per completed step, *before* ``save``:
    a kill therefore always loses the tail since the last committed
    generation, which is exactly the replay the bit-exact guarantee
    covers.
    """

    def __init__(self, build: Callable[[FaultInjector], Any], *,
                 total_steps: int, plan: Optional[FaultPlan] = None,
                 injector: Optional[FaultInjector] = None,
                 max_restarts: int = 4, job_id: str = "chaos",
                 heartbeat_interval: float = 0.1, lease_ttl: float = 0.5,
                 step_retries: int = 3, detect_timeout: float = 10.0,
                 telemetry=None):
        self.build = build
        self.total_steps = int(total_steps)
        self.injector = injector or FaultInjector(plan)
        self.max_restarts = int(max_restarts)
        self.job_id = job_id
        self.heartbeat_interval = heartbeat_interval
        self.lease_ttl = lease_ttl
        self.step_retries = int(step_retries)
        self.detect_timeout = detect_timeout
        # optional TrainTelemetry shared with the run's engine: the
        # harness attributes each kill→detection→rendezvous→restore
        # segment to the goodput ledger as recovery (non-productive)
        # wall, which is what pushes train_goodput_ratio below 1.0
        self.telemetry = telemetry

    def _manager(self, endpoint: str) -> ElasticManager:
        return ElasticManager(endpoint, job_id=self.job_id, np=1,
                              heartbeat_interval=self.heartbeat_interval,
                              lease_ttl=self.lease_ttl, is_master=False)

    def _await_detection(self, monitor: ElasticManager) -> bool:
        """Block until the dead incarnation's lease expires and the
        monitor votes RESTART — the harness may not assume the kill, it
        must observe it the way a real launcher watcher would."""
        t0 = time.time()
        while time.time() - t0 < self.detect_timeout:
            if monitor.health_check() == ElasticStatus.RESTART:
                return True
            time.sleep(self.heartbeat_interval / 2)
        return False

    def run(self) -> ChaosReport:
        report = ChaosReport()
        tel = self.telemetry
        t_recovery: Optional[float] = None
        port = free_port()
        endpoint = f"127.0.0.1:{port}"
        server = KVServer(port)
        monitor = self._manager(endpoint)
        try:
            while report.restarts <= self.max_restarts:
                mgr = self._manager(endpoint)
                mgr.my_host = f"incarnation-{report.restarts}"
                mgr.start_heartbeat()
                if not mgr.wait_for_np(timeout=self.detect_timeout):
                    raise RuntimeError("chaos rendezvous never reached np")
                mgr.update_endpoints()
                run = self.build(self.injector)
                try:
                    start = int(run.restore())
                    if tel is not None and t_recovery is not None:
                        # lost work (replayed steps) books itself when the
                        # engine re-records the rolled-back step indices;
                        # this segment is the rest of the outage
                        tel.record_recovery(t_recovery, tel.clock(),
                                            restart=report.restarts,
                                            resume_step=start)
                        t_recovery = None
                    step = start
                    while step < self.total_steps:
                        loss = self._step_with_retry(run, step, report)
                        report.losses[step] = float(loss)
                        report.steps_run += 1
                        spec = self.injector.fire("kill")
                        if spec is not None:
                            raise SimulatedKill(f"injected kill after step {step}")
                        run.save(step)
                        step += 1
                    report.completed = True
                    return report
                except SimulatedKill:
                    if tel is not None:
                        t_recovery = tel.clock()
                    report.detected_kills += 1
                    mgr.stop()  # heartbeat dies with the incarnation
                    if not self._await_detection(monitor):
                        raise RuntimeError(
                            "kill was never detected by the elastic monitor")
                    if tel is not None:
                        tel.tracer.instant(
                            _TRAIN_RID, "kill_detected",
                            restart=report.restarts + 1)
                    report.restarts += 1
                finally:
                    if hasattr(run, "close"):
                        run.close()
                    if not mgr._stop.is_set():
                        mgr.stop()
            raise RuntimeError(
                f"chaos run exhausted max_restarts={self.max_restarts}")
        finally:
            report.fault_stats = self.injector.stats()
            monitor.stop()
            server.stop()

    def _step_with_retry(self, run, step: int, report: ChaosReport) -> float:
        for attempt in range(self.step_retries + 1):
            try:
                return run.step(step)
            except (StepFault, DataFeedFault):
                # injected BEFORE dispatch / cursor advance by contract,
                # so a verbatim retry is deterministic
                if attempt == self.step_retries:
                    raise
                report.transient_retries += 1
        raise AssertionError("unreachable")
