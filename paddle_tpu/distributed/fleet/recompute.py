"""Activation recompute (ref: fleet/recompute/recompute.py —
RecomputeFunction:69 PyLayer-based with RNG-state restore :57).

TPU-native: in compiled training, recompute == jax.checkpoint (XLA remat) —
strictly better than the reference's PyLayer replay because the compiler
schedules the recomputation. Eagerly we provide the same API: forward runs
under no_grad, backward replays with grad via a PyLayer.
"""
from __future__ import annotations

import jax

from ...autograd import PyLayer
from ...framework.core import Tensor, enable_grad, no_grad_ctx


class _RecomputeFunction(PyLayer):
    @staticmethod
    def forward(ctx, run_function, preserve_rng_state, *args):
        ctx.run_function = run_function
        ctx.inputs = args
        from ...framework.random import get_rng_state

        ctx.rng_state = get_rng_state() if preserve_rng_state else None
        with no_grad_ctx():
            out = run_function(*args)
        return out

    @staticmethod
    def backward(ctx, *grads):
        from ...framework.random import set_rng_state

        if ctx.rng_state is not None:
            saved = ctx.rng_state
            set_rng_state(saved)
        detached = [a.detach() if isinstance(a, Tensor) else a for a in ctx.inputs]
        for d, orig in zip(detached, ctx.inputs):
            if isinstance(orig, Tensor) and not orig.stop_gradient:
                d.stop_gradient = False
        with enable_grad():
            out = ctx.run_function(*detached)
        outs = out if isinstance(out, (tuple, list)) else [out]
        from ...framework.core import backward as run_backward

        diff_outs = [o for o in outs if isinstance(o, Tensor) and not o.stop_gradient]
        gs = list(grads)[: len(diff_outs)]
        run_backward(diff_outs, gs)
        return tuple(d.grad if isinstance(d, Tensor) and d.grad is not None else None
                     for d in detached)


def recompute(function, *args, **kwargs):
    """Ref recompute.py recompute(). kwargs: use_reentrant, preserve_rng_state."""
    preserve = kwargs.pop("preserve_rng_state", True)
    kwargs.pop("use_reentrant", None)
    if kwargs:
        raise ValueError(f"unsupported kwargs {list(kwargs)}")
    return _RecomputeFunction.apply(function, preserve, *args)


def recompute_sequential(ctx, functions, *args):
    """Ref recompute_sequential — chunk a Sequential into recompute segments."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions)
    n = len(layers)
    per = max(n // segments, 1)
    out = args
    for i in range(0, n, per):
        seg = layers[i:i + per]

        def run_seg(*xs, _seg=seg):
            y = xs
            for l in _seg:
                y = (l(*y),) if isinstance(y, tuple) else (l(y),)
            return y[0] if len(y) == 1 else y

        out = (recompute(run_seg, *out),) if isinstance(out, tuple) else \
            (recompute(run_seg, out),)
    return out[0] if isinstance(out, tuple) and len(out) == 1 else out
