"""paddle.distributed.fleet.data_generator (ref fleet/data_generator/
data_generator.py:20 DataGenerator — user subclasses implement
generate_sample(line); the PS data pipeline shells out to run_from_stdin).

TPU-native: same user contract (generate_sample yielding (slot_name, values)
pairs; MultiSlotDataGenerator string protocol), consumed by
fleet.InMemoryDataset/QueueDataset (dataset.py) which feed host numpy batches
instead of the C++ data_feed.
"""
from __future__ import annotations

import sys
from typing import Callable, Iterable, List, Optional, Tuple

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]

Sample = List[Tuple[str, List]]


class DataGenerator:
    """ref data_generator.py:20."""

    def __init__(self):
        self.batch_size_ = 32
        self._line_limit = None

    def set_batch(self, batch_size: int):
        """ref :32"""
        self.batch_size_ = int(batch_size)

    # ------------------------------------------------------------- user hooks
    def generate_sample(self, line: Optional[str]) -> Callable[[], Iterable[Sample]]:
        """Return a local iterator over samples for one input line (ref :153).
        Must be overridden."""
        raise NotImplementedError(
            "subclass DataGenerator and implement generate_sample(line)")

    def generate_batch(self, samples: List[Sample]) -> Callable[[], Iterable]:
        """Optional batch post-processing (ref :195); default yields samples
        unchanged."""

        def local_iter():
            for s in samples:
                yield s

        return local_iter

    # ----------------------------------------------------------------- drive
    def _iter_samples(self, lines: Iterable[Optional[str]]):
        for line in lines:
            it = self.generate_sample(line)
            if it is None:
                continue
            for sample in it():
                if sample is None:
                    continue
                yield sample

    def _batched(self, lines):
        buf = []
        for sample in self._iter_samples(lines):
            buf.append(sample)
            if len(buf) >= self.batch_size_:
                yield from self.generate_batch(buf)()
                buf = []
        if buf:
            yield from self.generate_batch(buf)()

    def run_from_memory(self):
        """ref :60 — generate from self alone (generate_sample(None)),
        printing the serialized protocol to stdout."""
        for s in self._batched([None]):
            sys.stdout.write(self._gen_str(s))

    def run_from_stdin(self):
        """ref :95 — one sample stream per stdin line."""
        for s in self._batched(sys.stdin):
            sys.stdout.write(self._gen_str(s))

    def iter_samples(self, lines: Iterable[str]):
        """In-process hook used by fleet.InMemoryDataset (no subprocess/stdout
        hop needed on the TPU host pipeline)."""
        yield from self._batched(lines)

    def _gen_str(self, line: Sample) -> str:
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator")


class MultiSlotDataGenerator(DataGenerator):
    """Serializes 'slot:count v0 v1 ...' per sample (ref _gen_str of
    MultiSlotDataGenerator)."""

    def _gen_str(self, line: Sample) -> str:
        parts = []
        for name, values in line:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    def _gen_str(self, line: Sample) -> str:
        parts = []
        for name, values in line:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"
