"""fleet.utils parity (ref: fleet/utils/ — fs.py HDFS client,
hybrid_parallel_util.py, recompute re-export)."""
from ..recompute import recompute  # noqa: F401
from .fs import HDFSClient, LocalFS  # noqa: F401


def fused_allreduce_gradients(parameter_list, hcg=None):
    """Ref hybrid_parallel_util.py:206 — average grads across DP workers."""
    from ...collective import ReduceOp, all_reduce
    from ...env import get_world_size

    if get_world_size() <= 1:
        return
    for p in parameter_list:
        if p.grad is not None:
            all_reduce(p.grad, op=ReduceOp.AVG)
