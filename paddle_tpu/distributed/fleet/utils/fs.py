"""Filesystem abstraction (ref: fleet/utils/fs.py — LocalFS + HDFSClient).
HDFS requires an external hadoop client binary; LocalFS covers the
checkpointing paths in this environment."""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Optional, Tuple


class FS:
    def ls_dir(self, path) -> Tuple[List[str], List[str]]:
        raise NotImplementedError

    def is_dir(self, path) -> bool:
        raise NotImplementedError

    def is_file(self, path) -> bool:
        raise NotImplementedError

    def is_exist(self, path) -> bool:
        raise NotImplementedError

    def mkdirs(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError


class LocalFS(FS):
    def ls_dir(self, path):
        if not os.path.isdir(path):
            return [], []
        dirs, files = [], []
        for e in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, e)) else files).append(e)
        return dirs, files

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def is_exist(self, path):
        return os.path.exists(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def touch(self, path, exist_ok=True):
        open(path, "a").close()

    def mv(self, src, dst, overwrite=False):
        if overwrite and os.path.exists(dst):
            self.delete(dst)
        shutil.move(src, dst)

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)


class HDFSClient(FS):
    """Ref fs.py HDFSClient — shells out to `hadoop fs`."""

    def __init__(self, hadoop_home: str, configs: Optional[dict] = None,
                 time_out=5 * 60 * 1000, sleep_inter=1000):
        self.hadoop_bin = os.path.join(hadoop_home, "bin", "hadoop")
        self.configs = configs or {}
        self._pre = [self.hadoop_bin, "fs"]
        for k, v in self.configs.items():
            self._pre += [f"-D{k}={v}"]

    def _run(self, *args) -> Tuple[int, str]:
        try:
            out = subprocess.run(self._pre + list(args), capture_output=True,
                                 text=True, timeout=300)
            return out.returncode, out.stdout
        except (OSError, subprocess.SubprocessError) as e:
            return 1, str(e)

    def is_exist(self, path):
        code, _ = self._run("-test", "-e", path)
        return code == 0

    def is_dir(self, path):
        code, _ = self._run("-test", "-d", path)
        return code == 0

    def is_file(self, path):
        return self.is_exist(path) and not self.is_dir(path)

    def ls_dir(self, path):
        code, out = self._run("-ls", path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def mkdirs(self, path):
        self._run("-mkdir", "-p", path)

    def delete(self, path):
        self._run("-rm", "-r", path)

    def upload(self, local_path, fs_path, multi_processes=1, overwrite=False):
        self._run("-put", "-f" if overwrite else "", local_path, fs_path)

    def download(self, fs_path, local_path, multi_processes=1, overwrite=False):
        self._run("-get", fs_path, local_path)
