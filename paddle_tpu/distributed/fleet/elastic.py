"""Elastic / fault tolerance (ref: fleet/elastic/manager.py:126
ElasticManager — etcd3 lease heartbeats :260, node watch, ElasticLevel :41,
scale in/out :498/:521 + endpoint rewrite and relaunch).

TPU-native reality (SURVEY §2.3): TPU pods can't change slice size in-job, so
ELASTIC-level scale in/out is replaced by job-level restart + checkpoint
resume. What survives from the reference design:
- heartbeat + failure detection (KV-store leases instead of etcd3),
- endpoint registry + rank rewrite on restart,
- the LauncherInterface watch/stop/relaunch loop (the launch CLI's
  --max_restart path is the actuator).
"""
from __future__ import annotations

import enum
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from ..launch.rendezvous import KVClient, KVServer


class ElasticLevel(enum.IntEnum):  # ref manager.py:41
    FAULT_TOLERANCE = 1
    ELASTIC = 2


class ElasticStatus(enum.Enum):
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    def __init__(self, endpoint: str, job_id: str = "default", np: int = 1,
                 heartbeat_interval: float = 2.0, lease_ttl: float = 10.0,
                 is_master: bool = False):
        self.job_id = job_id
        self.np = np
        self.heartbeat_interval = heartbeat_interval
        self.lease_ttl = lease_ttl
        self.server = KVServer(int(endpoint.rsplit(":", 1)[1])) if is_master else None
        self.kv = KVClient(endpoint)
        self.my_host = os.environ.get("PADDLE_CURRENT_ENDPOINT",
                                      f"node-{os.getpid()}")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.enabled = True

    # -- heartbeats (ref lease_heartbeat :260) ------------------------------
    def start_heartbeat(self):
        def beat():
            while not self._stop.is_set():
                self.kv.set(f"beat/{self.job_id}/{self.my_host}", str(time.time()))
                self._stop.wait(self.heartbeat_interval)

        self._thread = threading.Thread(target=beat, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        if self.server:
            self.server.stop()

    # -- membership ---------------------------------------------------------
    def alive_nodes(self) -> List[str]:
        beats: Dict[str, str] = self.kv.list(f"beat/{self.job_id}/")
        now = time.time()
        return sorted(k.rsplit("/", 1)[1] for k, v in beats.items()
                      if now - float(v) < self.lease_ttl)

    def health_check(self) -> ElasticStatus:
        """Ref _match/_update loop: all registered nodes beating → HOLD (run);
        any lease expired → RESTART (checkpoint-resume relaunch)."""
        alive = self.alive_nodes()
        if len(alive) >= self.np:
            return ElasticStatus.HOLD
        return ElasticStatus.RESTART

    def wait_for_np(self, timeout: float = 120.0) -> bool:
        t0 = time.time()
        while time.time() - t0 < timeout:
            if len(self.alive_nodes()) >= self.np:
                return True
            time.sleep(0.5)
        return False

    def update_endpoints(self) -> List[str]:
        """Rank rewrite on restart (ref _update_fault_tolrance :469): new
        sorted membership becomes PADDLE_TRAINER_ENDPOINTS."""
        eps = self.alive_nodes()
        os.environ["DISTRIBUTED_TRAINER_ENDPOINTS"] = ",".join(eps)
        os.environ["PADDLE_TRAINER_ENDPOINTS"] = ",".join(eps)
        if self.my_host in eps:
            os.environ["PADDLE_TRAINER_ID"] = str(eps.index(self.my_host))
        return eps


def run_with_fault_tolerance(train_fn: Callable[[int], None], checkpoint,
                             max_restarts: int = 3):
    """Convenience loop: run train_fn(resume_step); on failure, resume from
    the latest AutoCheckpoint snapshot (the recovery story, SURVEY §5.3/5.4)."""
    attempts = 0
    while True:
        try:
            step = checkpoint.resume() if hasattr(checkpoint, "resume") else 0
            train_fn(step)
            return
        except Exception:
            attempts += 1
            if attempts > max_restarts:
                raise


_beat_state = {"thread_stop": None, "last_pulse": 0.0}


def start_file_heartbeat(path: Optional[str] = None,
                         interval: Optional[float] = None):
    """Touch the launcher-assigned heartbeat file periodically so the
    launcher's watcher (launch/main.py Pod.join) can detect a HUNG rank —
    not just an exited one — and restart the pod (ref manager.py:260 lease
    heartbeat, realized as file mtimes on the shared log dir).

    Two phases:
    - STARTUP (this thread): a free-running beat covers imports, rendezvous
      and data loading, where no training progress exists yet.
    - TRAINING: the first :func:`pulse_heartbeat` (called per train step by
      the engines and ``AutoCheckpoint.step``) STOPS the thread — from then
      on the file only advances with real training progress, so a rank
      wedged inside a collective (thread would happily keep beating) goes
      stale and is detected.

    Auto-started by ``init_parallel_env`` when ``PADDLE_HEARTBEAT_FILE`` is
    set (i.e. the job was launched with ``--elastic_timeout``). Returns the
    stop Event, or None when no heartbeat file is configured."""
    path = path or os.environ.get("PADDLE_HEARTBEAT_FILE")
    if not path:
        return None
    interval = float(interval or
                     os.environ.get("PADDLE_HEARTBEAT_INTERVAL", "1.0"))
    stop = threading.Event()
    _beat_state["thread_stop"] = stop

    def beat():
        while not stop.is_set():
            _touch(path)
            stop.wait(interval)

    threading.Thread(target=beat, daemon=True).start()
    return stop


def _touch(path):
    try:
        with open(path, "a"):
            os.utime(path, None)
    except OSError:
        pass


def pulse_heartbeat():
    """Per-train-step heartbeat pulse. Throttled to ~5 Hz. The first pulse
    hands ownership of the heartbeat file from the startup thread to the
    training loop (see start_file_heartbeat)."""
    path = os.environ.get("PADDLE_HEARTBEAT_FILE")
    if not path:
        return
    stop = _beat_state.get("thread_stop")
    if stop is not None:
        stop.set()
        _beat_state["thread_stop"] = None
    now = time.time()
    if now - _beat_state["last_pulse"] >= 0.2:
        _beat_state["last_pulse"] = now
        _touch(path)
