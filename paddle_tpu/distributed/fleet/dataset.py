"""paddle.distributed.fleet dataset classes (ref fleet/dataset/dataset.py —
DatasetBase :39 init/set_filelist, InMemoryDataset :350 load_into_memory /
local_shuffle / global_shuffle / release_memory, QueueDataset :1274).

TPU-native: the reference backs these with the C++ data_feed/Dataset stack
(paddle/fluid/framework/data_feed.cc) pumping LoDTensors into PS trainers.
Here the host pipeline is Python+numpy: slot-format text files are parsed by
a fleet.data_generator (in-process, no stdin hop), records live in host RAM
(InMemoryDataset) or stream lazily (QueueDataset), and batches come out as
name→numpy dicts ready for jit feeds.  global_shuffle exchanges record
ownership by rank hash — the same record→rank contract as the reference's
gloo-coordinated shuffle — implemented locally since each TPU host reads its
own shard.
"""
from __future__ import annotations

import random
from typing import Dict, Iterable, List, Optional

import numpy as np

__all__ = ["DatasetBase", "InMemoryDataset", "QueueDataset"]


class DatasetBase:
    """ref dataset.py:39."""

    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.filelist: List[str] = []
        self.use_var_names: List[str] = []
        self.pipe_command = ""
        self._generator = None
        self.fs_name = ""
        self.fs_ugi = ""

    def init(self, batch_size=1, thread_num=1, use_var=None, pipe_command="",
             input_type=0, fs_name="", fs_ugi="", **kwargs):
        """ref :39 — use_var takes static Variables or names."""
        self.batch_size = int(batch_size)
        self.thread_num = int(thread_num)
        self.pipe_command = pipe_command
        self.fs_name = fs_name
        self.fs_ugi = fs_ugi
        if use_var:
            self.use_var_names = [
                getattr(v, "var_name", getattr(v, "name", v)) for v in use_var]
        return self

    def set_filelist(self, filelist: List[str]):
        """ref :126"""
        self.filelist = list(filelist)

    def set_generator(self, generator):
        """TPU-native replacement for pipe_command subprocesses: a
        fleet.data_generator.DataGenerator parsed in-process."""
        self._generator = generator

    # ------------------------------------------------------------ internals
    def _iter_lines(self) -> Iterable[str]:
        for fn in self.filelist:
            with open(fn) as f:
                yield from f

    def _parse_records(self) -> Iterable[Dict[str, np.ndarray]]:
        if self._generator is not None:
            for sample in self._generator.iter_samples(self._iter_lines()):
                yield {name: np.asarray(vals) for name, vals in sample}
        else:
            # default slot-format: whitespace floats, one sample per line,
            # split evenly over use_var_names
            n = max(len(self.use_var_names), 1)
            for line in self._iter_lines():
                vals = [float(x) for x in line.split()]
                if not vals:
                    continue
                per = len(vals) // n
                rec = {}
                for i, name in enumerate(self.use_var_names or ["slot0"]):
                    rec[name] = np.asarray(vals[i * per:(i + 1) * per])
                yield rec

    def _batch_records(self, records) -> Iterable[Dict[str, np.ndarray]]:
        buf: List[Dict[str, np.ndarray]] = []
        for r in records:
            buf.append(r)
            if len(buf) >= self.batch_size:
                yield self._stack(buf)
                buf = []
        if buf:
            yield self._stack(buf)

    @staticmethod
    def _stack(buf: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
        return {k: np.stack([b[k] for b in buf]) for k in buf[0]}


class InMemoryDataset(DatasetBase):
    """ref dataset.py:350 — materialize all records in host RAM, shuffle,
    iterate batches."""

    def __init__(self):
        super().__init__()
        self._records: List[Dict[str, np.ndarray]] = []
        self._loaded = False

    def load_into_memory(self, is_shuffle: bool = False):
        """ref :857"""
        self._records = list(self._parse_records())
        self._loaded = True
        if is_shuffle:
            self.local_shuffle()

    def preload_into_memory(self, file_num: Optional[int] = None):
        self.load_into_memory()

    def wait_preload_done(self):
        pass

    def local_shuffle(self, seed: Optional[int] = None):
        """ref :969"""
        rng = random.Random(seed)
        rng.shuffle(self._records)

    def global_shuffle(self, fleet=None, thread_num: int = 12,
                       seed: Optional[int] = None):
        """ref :1001 — cross-rank shuffle. Each TPU host reads its own file
        shard, so ownership exchange reduces to keeping records hashed to this
        rank, then shuffling locally."""
        rank, world = 0, 1
        if fleet is not None:
            rank = fleet.worker_index()
            world = max(fleet.worker_num(), 1)
        if world > 1:
            self._records = [r for i, r in enumerate(self._records)
                             if (hash((i, len(self._records))) % world) == rank]
        self.local_shuffle(seed)

    def release_memory(self):
        """ref :1061"""
        self._records = []
        self._loaded = False

    def get_memory_data_size(self, fleet=None) -> int:
        """ref :1100 — record count (all ranks see their local count; with a
        fleet handle the reference allreduces — local count is the per-host
        contribution)."""
        return len(self._records)

    def get_shuffle_data_size(self, fleet=None) -> int:
        return len(self._records)

    def __iter__(self):
        if not self._loaded:
            self.load_into_memory()
        yield from self._batch_records(iter(self._records))


class QueueDataset(DatasetBase):
    """ref dataset.py:1274 — single-pass streaming (no in-RAM materialize)."""

    def __iter__(self):
        yield from self._batch_records(self._parse_records())
