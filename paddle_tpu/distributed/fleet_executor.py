"""FleetExecutor — native actor pipeline runtime (ref
paddle/fluid/distributed/fleet_executor/: Carrier carrier.h:49, Interceptor
interceptor.h:46, TaskNode DAG, fleet_executor.cc; Python bindings
pybind/bind_fleet_executor.cc).

TPU-native role: host-side orchestration of per-stage callbacks — microbatch
pipeline schedules, async IO, checkpoint writers — running concurrently with
device compute (the accelerator data plane itself is XLA collectives inside
jitted programs). Single-host DAGs run on C++ mailbox threads; DAGs spanning
hosts use ``DistributedFleetExecutor``, whose cross-rank edges ride the
``paddle.distributed.rpc`` transport (the brpc MessageBus role).
Backed by csrc/fleet_executor.cpp via ctypes; scheduling semantics follow the
reference ComputeInterceptor: a task runs step s when every upstream finished
s and downstream credit (buffer_size) is available — with buffer_size=1 a
linear chain executes in the classic pipelined (1F1B-shaped) order.
"""
from __future__ import annotations

import ctypes
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = ["TaskNode", "FleetExecutor", "DistributedFleetExecutor"]

_TASK_FN = ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_int64, ctypes.c_int64)
_EGRESS_FN = ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
                              ctypes.c_int64, ctypes.c_int64)

_LIB = None
_LIB_LOCK = threading.Lock()


def _lib():
    global _LIB
    with _LIB_LOCK:
        if _LIB is None:
            from ..utils.native_build import ensure_lib

            so = ensure_lib("fleet_executor")
            if so is None:
                from ..utils import native_build

                raise RuntimeError(
                    "could not build csrc/fleet_executor.cpp: "
                    f"{native_build.LAST_BUILD_ERROR or 'g++ not found'}")
            lib = ctypes.CDLL(so)
            lib.pt_carrier_create.restype = ctypes.c_int64
            lib.pt_carrier_add_task.restype = ctypes.c_int64
            lib.pt_carrier_add_task.argtypes = [
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, _TASK_FN]
            lib.pt_carrier_run.restype = ctypes.c_int64
            lib.pt_carrier_run.argtypes = [ctypes.c_int64]
            lib.pt_carrier_destroy.argtypes = [ctypes.c_int64]
            lib.pt_carrier_set_egress.argtypes = [ctypes.c_int64, _EGRESS_FN]
            lib.pt_carrier_notify.restype = ctypes.c_int64
            lib.pt_carrier_notify.argtypes = [
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
                ctypes.c_int64, ctypes.c_int64]
            lib.pt_carrier_abort.restype = ctypes.c_int64
            lib.pt_carrier_abort.argtypes = [ctypes.c_int64, ctypes.c_int64]
            _LIB = lib
    return _LIB


class TaskNode:
    """One node of the pipeline DAG (ref task_node.h; role kinds ref
    task_node.cc — here role is an opaque label)."""

    def __init__(self, task_id: int, fn: Callable[[int, int], None],
                 max_run_times: int = 1, role: int = 0, buffer_size: int = 1):
        self.task_id = int(task_id)
        self.fn = fn
        self.max_run_times = int(max_run_times)
        self.role = int(role)
        self.buffer_size = int(buffer_size)
        self.upstream: List[int] = []
        self.downstream: List[int] = []

    def add_upstream_task(self, task_id: int, buff_size: int = 1):
        self.upstream.append(int(task_id))

    def add_downstream_task(self, task_id: int, buff_size: int = 1):
        self.downstream.append(int(task_id))


class FleetExecutor:
    """Carrier facade (ref fleet_executor.cc Init/Run). Tasks' Python
    callbacks run on C++ interceptor threads (ctypes re-acquires the GIL per
    call); exceptions abort the whole run and re-raise on the caller."""

    def __init__(self):
        self._nodes: Dict[int, TaskNode] = {}

    def add_task_node(self, node: TaskNode) -> TaskNode:
        self._nodes[node.task_id] = node
        return node

    def task_chain(self, fns: Sequence[Callable[[int, int], None]],
                   max_run_times: int, buffer_size: int = 1) -> List[TaskNode]:
        """Convenience: wire fns[0] -> fns[1] -> ... as a pipeline."""
        nodes = [self.add_task_node(TaskNode(i, fn, max_run_times,
                                             buffer_size=buffer_size))
                 for i, fn in enumerate(fns)]
        for a, b in zip(nodes, nodes[1:]):
            a.add_downstream_task(b.task_id)
            b.add_upstream_task(a.task_id)
        return nodes

    @classmethod
    def from_program(cls, program, feeds: Sequence[Dict[str, Any]],
                     fetch_list: Sequence[str], num_segments: int = 2,
                     buffer_size: int = 1) -> "FleetExecutor":
        """Build the TaskNode DAG FROM a recorded static Program (ref
        fleet_executor/task_node.cc TaskNode(program, rank, ...) +
        dist_model.cc: the program is sliced into contiguous op segments,
        one TaskNode per segment, chained with credit-based buffers; each
        run step processes one microbatch flowing through the segment
        pipeline on the C++ interceptor threads).

        ``feeds``: one feed dict per microbatch. After ``run()``, fetched
        values per microbatch are in ``.results``."""
        import jax.numpy as jnp

        from ..framework.core import Tensor
        from ..static.graph import exec_ops, global_scope

        exe = cls()
        ops = list(program.ops)
        num_segments = max(1, min(num_segments, len(ops) or 1))
        bounds = [i * len(ops) // num_segments
                  for i in range(num_segments + 1)]
        segments = [ops[bounds[i]:bounds[i + 1]] for i in range(num_segments)]
        # trained values live in the executor scope; fall back to init values
        # (same pattern as save_inference_model, static/graph.py)
        store = global_scope().store
        params = {name: store.get(name, p.value)
                  for name, p in program.params.items()}
        envs = [{k: jnp.asarray(v.value if isinstance(v, Tensor) else v)
                 for k, v in f.items()} for f in feeds]
        results: List[Any] = [None] * len(feeds)

        def make_fn(seg, last: bool):
            def fn(task_id, step):
                env = envs[step]
                exec_ops(seg, env, params, program)
                if last:
                    results[step] = [env[n] for n in fetch_list]

            return fn

        fns = [make_fn(seg, i == num_segments - 1)
               for i, seg in enumerate(segments)]
        exe.task_chain(fns, max_run_times=len(feeds),
                       buffer_size=buffer_size)
        exe.results = results
        return exe

    def _register_tasks(self, lib, h, errors, keepalive, predicate=None,
                        on_error=None):
        """Wrap + register every node passing ``predicate``; shared by the
        single-host and distributed run paths."""
        for node in self._nodes.values():
            if predicate is not None and not predicate(node):
                continue

            def make_cb(n: TaskNode):
                def cb(task_id, step):
                    try:
                        n.fn(int(task_id), int(step))
                        return 0
                    except BaseException as e:  # surface to caller
                        errors[int(task_id)] = e
                        if on_error is not None:
                            on_error()
                        return 1
                return _TASK_FN(cb)

            cfn = make_cb(node)
            keepalive.append(cfn)
            up = (ctypes.c_int64 * max(len(node.upstream), 1))(
                *node.upstream)
            down = (ctypes.c_int64 * max(len(node.downstream), 1))(
                *node.downstream)
            lib.pt_carrier_add_task(
                h, node.task_id, node.role, node.max_run_times,
                node.buffer_size, up, len(node.upstream), down,
                len(node.downstream), cfn)

    def run(self) -> None:
        lib = _lib()
        h = lib.pt_carrier_create()
        errors: Dict[int, BaseException] = {}
        keepalive = []  # CFUNCTYPE objects must outlive the run
        try:
            self._register_tasks(lib, h, errors, keepalive)
            rc = lib.pt_carrier_run(h)
            if rc != 0:
                if errors:
                    raise next(iter(errors.values()))
                raise RuntimeError(f"FleetExecutor run failed with status {rc}")
        finally:
            lib.pt_carrier_destroy(h)


# --------------------------------------------------------------------------
# Cross-host message bus (the brpc MessageBus role, ref
# fleet_executor/message_bus.cc): edges between TaskNodes placed on different
# RPC workers ride paddle.distributed.rpc; the C++ carrier forwards messages
# for non-local tasks through its egress callback and accepts remote
# deliveries via pt_carrier_notify.
# --------------------------------------------------------------------------

_DIST_EXECUTORS: Dict[str, "DistributedFleetExecutor"] = {}


def _bus_abort(job: str, code: int) -> int:
    """RPC endpoint: a peer's task failed — abort the local carrier."""
    exe = _DIST_EXECUTORS.get(job)
    if exe is None or exe._handle is None:
        return -1
    return int(_lib().pt_carrier_abort(exe._handle, code))


def _bus_deliver(job: str, dst: int, mtype: int, src: int, step: int) -> int:
    """RPC endpoint: runs on the destination worker, injects the message
    into its live carrier. Waits briefly for the carrier if the sender's
    run() raced ahead of ours (messages must not be lost).

    Wait budgets (seconds, env-tunable): an executor that EXISTS but hasn't
    entered run() gets ``PADDLE_TPU_BUS_WAIT`` (default 60); a job id with
    no executor registered at all gets only ``PADDLE_TPU_BUS_GRACE``
    (default 20 — covers first-use .so compile + import skew) for the
    construction race, then fails fast with -2 so a
    misconfigured placement doesn't pin an RPC worker thread for a minute
    per message."""
    import time as _t

    wait = float(os.environ.get("PADDLE_TPU_BUS_WAIT", "60"))
    grace = float(os.environ.get("PADDLE_TPU_BUS_GRACE", "20"))
    t0 = _t.monotonic()
    while True:
        exe = _DIST_EXECUTORS.get(job)
        if exe is not None and exe._handle is not None:
            return int(_lib().pt_carrier_notify(exe._handle, dst, mtype,
                                                src, step))
        if exe is not None and exe._completed:
            return 0  # stale message after completion: drop cleanly
        elapsed = _t.monotonic() - t0
        if exe is None and elapsed > grace:
            return -2  # unknown job here: placement mismatch, fail fast
        if elapsed > wait:
            return -1
        _t.sleep(0.1)


class DistributedFleetExecutor(FleetExecutor):
    """TaskNode DAG spanning RPC workers: each worker runs the local carrier
    for ITS tasks; cross-worker edges are forwarded over the RPC transport.
    ``placement``: task_id → rpc worker name (every worker passes the same
    full map and full DAG topology; only locally-placed nodes get callbacks).
    Call inside an initialized ``paddle.distributed.rpc`` world."""

    def __init__(self, job_id: str, placement: Dict[int, str]):
        super().__init__()
        from .rpc import rpc as _rpc

        self._rpc = _rpc
        self.job_id = job_id
        self.placement = dict(placement)
        self.my_name = _rpc.get_current_worker_info().name
        self._handle = None
        self._completed = False
        _DIST_EXECUTORS[job_id] = self

    def is_local(self, task_id: int) -> bool:
        return self.placement.get(task_id) == self.my_name

    def _remote_workers(self):
        return sorted({w for w in self.placement.values()
                       if w != self.my_name})

    def _propagate_abort(self):
        """A local task failed: abort every peer's carrier too (the
        reference MessageBus broadcasts STOP on failure)."""
        for w in self._remote_workers():
            try:
                self._rpc.rpc_async(w, _bus_abort, args=(self.job_id, 1))
            except BaseException:
                pass

    def run(self) -> None:
        lib = _lib()
        h = lib.pt_carrier_create()
        _DIST_EXECUTORS[self.job_id] = self  # re-register on every run
        self._completed = False
        self._handle = h
        errors: Dict[int, BaseException] = {}
        keepalive = []
        job = self.job_id

        def egress(dst, mtype, src, step):
            owner = self.placement.get(int(dst))
            if owner is None or owner == self.my_name:
                return -1
            try:
                # async: the interceptor thread must not block the network;
                # a failed send aborts this carrier (a silently dropped
                # message would deadlock the whole DAG)
                fut = self._rpc.rpc_async(owner, _bus_deliver,
                                          args=(job, int(dst), int(mtype),
                                                int(src), int(step)))
                if int(mtype) == 0:  # kDataIsReady: loss would deadlock
                    fut._fut.add_done_callback(
                        lambda f: (f.exception() is not None or
                                   f.result() != 0) and
                        lib.pt_carrier_abort(h, 3))
                # credits (kDataIsUseless) may race peer shutdown: a lost
                # credit cannot stall a finished consumer — best effort
                return 0
            except BaseException:
                return -1

        c_egress = _EGRESS_FN(egress)
        keepalive.append(c_egress)
        lib.pt_carrier_set_egress(h, c_egress)
        try:
            self._register_tasks(lib, h, errors, keepalive,
                                 predicate=lambda n: self.is_local(n.task_id),
                                 on_error=self._propagate_abort)
            rc = lib.pt_carrier_run(h)
            if rc != 0:
                if errors:
                    raise next(iter(errors.values()))
                raise RuntimeError(f"DistributedFleetExecutor rc={rc}")
        finally:
            self._handle = None
            self._completed = True
            lib.pt_carrier_destroy(h)
