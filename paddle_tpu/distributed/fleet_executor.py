"""FleetExecutor — native actor pipeline runtime (ref
paddle/fluid/distributed/fleet_executor/: Carrier carrier.h:49, Interceptor
interceptor.h:46, TaskNode DAG, fleet_executor.cc; Python bindings
pybind/bind_fleet_executor.cc).

TPU-native role: host-side orchestration of per-stage callbacks — microbatch
pipeline schedules, async IO, checkpoint writers — running concurrently with
device compute (the accelerator data plane itself is XLA collectives inside
jitted programs, so the brpc cross-rank MessageBus is replaced by single-host
C++ mailbox threads; multi-host control traffic uses the launch KV store).
Backed by csrc/fleet_executor.cpp via ctypes; scheduling semantics follow the
reference ComputeInterceptor: a task runs step s when every upstream finished
s and downstream credit (buffer_size) is available — with buffer_size=1 a
linear chain executes in the classic pipelined (1F1B-shaped) order.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

__all__ = ["TaskNode", "FleetExecutor"]

_TASK_FN = ctypes.CFUNCTYPE(ctypes.c_int64, ctypes.c_int64, ctypes.c_int64)

_LIB = None
_LIB_LOCK = threading.Lock()


def _lib():
    global _LIB
    with _LIB_LOCK:
        if _LIB is None:
            root = os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))), "csrc")
            so = os.path.join(root, "libfleet_executor.so")
            if not os.path.exists(so):
                subprocess.check_call(
                    ["g++", "-O3", "-shared", "-fPIC", "-o", so,
                     os.path.join(root, "fleet_executor.cpp"), "-lpthread"])
            lib = ctypes.CDLL(so)
            lib.pt_carrier_create.restype = ctypes.c_int64
            lib.pt_carrier_add_task.restype = ctypes.c_int64
            lib.pt_carrier_add_task.argtypes = [
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, _TASK_FN]
            lib.pt_carrier_run.restype = ctypes.c_int64
            lib.pt_carrier_run.argtypes = [ctypes.c_int64]
            lib.pt_carrier_destroy.argtypes = [ctypes.c_int64]
            _LIB = lib
    return _LIB


class TaskNode:
    """One node of the pipeline DAG (ref task_node.h; role kinds ref
    task_node.cc — here role is an opaque label)."""

    def __init__(self, task_id: int, fn: Callable[[int, int], None],
                 max_run_times: int = 1, role: int = 0, buffer_size: int = 1):
        self.task_id = int(task_id)
        self.fn = fn
        self.max_run_times = int(max_run_times)
        self.role = int(role)
        self.buffer_size = int(buffer_size)
        self.upstream: List[int] = []
        self.downstream: List[int] = []

    def add_upstream_task(self, task_id: int, buff_size: int = 1):
        self.upstream.append(int(task_id))

    def add_downstream_task(self, task_id: int, buff_size: int = 1):
        self.downstream.append(int(task_id))


class FleetExecutor:
    """Carrier facade (ref fleet_executor.cc Init/Run). Tasks' Python
    callbacks run on C++ interceptor threads (ctypes re-acquires the GIL per
    call); exceptions abort the whole run and re-raise on the caller."""

    def __init__(self):
        self._nodes: Dict[int, TaskNode] = {}

    def add_task_node(self, node: TaskNode) -> TaskNode:
        self._nodes[node.task_id] = node
        return node

    def task_chain(self, fns: Sequence[Callable[[int, int], None]],
                   max_run_times: int, buffer_size: int = 1) -> List[TaskNode]:
        """Convenience: wire fns[0] -> fns[1] -> ... as a pipeline."""
        nodes = [self.add_task_node(TaskNode(i, fn, max_run_times,
                                             buffer_size=buffer_size))
                 for i, fn in enumerate(fns)]
        for a, b in zip(nodes, nodes[1:]):
            a.add_downstream_task(b.task_id)
            b.add_upstream_task(a.task_id)
        return nodes

    @classmethod
    def from_program(cls, program, feeds: Sequence[Dict[str, Any]],
                     fetch_list: Sequence[str], num_segments: int = 2,
                     buffer_size: int = 1) -> "FleetExecutor":
        """Build the TaskNode DAG FROM a recorded static Program (ref
        fleet_executor/task_node.cc TaskNode(program, rank, ...) +
        dist_model.cc: the program is sliced into contiguous op segments,
        one TaskNode per segment, chained with credit-based buffers; each
        run step processes one microbatch flowing through the segment
        pipeline on the C++ interceptor threads).

        ``feeds``: one feed dict per microbatch. After ``run()``, fetched
        values per microbatch are in ``.results``."""
        import jax.numpy as jnp

        from ..framework.core import Tensor
        from ..static.graph import exec_ops, global_scope

        exe = cls()
        ops = list(program.ops)
        num_segments = max(1, min(num_segments, len(ops) or 1))
        bounds = [i * len(ops) // num_segments
                  for i in range(num_segments + 1)]
        segments = [ops[bounds[i]:bounds[i + 1]] for i in range(num_segments)]
        # trained values live in the executor scope; fall back to init values
        # (same pattern as save_inference_model, static/graph.py)
        store = global_scope().store
        params = {name: store.get(name, p.value)
                  for name, p in program.params.items()}
        envs = [{k: jnp.asarray(v.value if isinstance(v, Tensor) else v)
                 for k, v in f.items()} for f in feeds]
        results: List[Any] = [None] * len(feeds)

        def make_fn(seg, last: bool):
            def fn(task_id, step):
                env = envs[step]
                exec_ops(seg, env, params, program)
                if last:
                    results[step] = [env[n] for n in fetch_list]

            return fn

        fns = [make_fn(seg, i == num_segments - 1)
               for i, seg in enumerate(segments)]
        exe.task_chain(fns, max_run_times=len(feeds),
                       buffer_size=buffer_size)
        exe.results = results
        return exe

    def run(self) -> None:
        lib = _lib()
        h = lib.pt_carrier_create()
        errors: Dict[int, BaseException] = {}
        keepalive = []  # CFUNCTYPE objects must outlive the run
        try:
            for node in self._nodes.values():
                def make_cb(n: TaskNode):
                    def cb(task_id, step):
                        try:
                            n.fn(int(task_id), int(step))
                            return 0
                        except BaseException as e:  # surface to caller
                            errors[int(task_id)] = e
                            return 1
                    return _TASK_FN(cb)

                cfn = make_cb(node)
                keepalive.append(cfn)
                up = (ctypes.c_int64 * max(len(node.upstream), 1))(
                    *node.upstream)
                down = (ctypes.c_int64 * max(len(node.downstream), 1))(
                    *node.downstream)
                lib.pt_carrier_add_task(
                    h, node.task_id, node.role, node.max_run_times,
                    node.buffer_size, up, len(node.upstream), down,
                    len(node.downstream), cfn)
            rc = lib.pt_carrier_run(h)
            if rc != 0:
                if errors:
                    raise next(iter(errors.values()))
                raise RuntimeError(f"FleetExecutor run failed with status {rc}")
        finally:
            lib.pt_carrier_destroy(h)
