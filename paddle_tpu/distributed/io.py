"""paddle.distributed.io — persistable save/load for distributed programs
(ref python/paddle/distributed/io.py:190 is_persistable, :221
save_persistables, :293 load_inference_model_distributed).

TPU-native: the reference splits PS-hosted remote params from local ones and
writes LoDTensor files; here params live in the static Scope as jax arrays —
save writes one pickle per program (or a single combined file), load restores
into the scope.  Sharded-across-mesh params are fetched with their GSPMD
layout intact (fully replicated on save, same policy as
distributed/checkpoint.py's orbax path for the dygraph side).
"""
from __future__ import annotations

import os
import pickle
from typing import Optional

import numpy as np

from ..static.graph import (Program, default_main_program, global_scope,
                            load_inference_model)

__all__ = ["is_persistable", "save_persistables", "load_persistables",
           "load_inference_model_distributed"]


def is_persistable(var) -> bool:
    """ref io.py:190 — feeds/fetches are not persistable; Parameters and
    vars flagged persistable are."""
    from ..framework.core import Parameter

    if isinstance(var, Parameter):
        return True
    return bool(getattr(var, "persistable", False)) and not getattr(
        var, "is_feed", False)


def save_persistables(executor=None, dirname: str = "",
                      main_program: Optional[Program] = None,
                      filename: Optional[str] = None):
    """Save every persistable param of the program (ref io.py:221)."""
    program = main_program or default_main_program()
    scope = global_scope()
    state = {}
    for name, p in program.params.items():
        val = scope.store.get(name)
        state[name] = np.asarray(val if val is not None else p.value)
    os.makedirs(dirname or ".", exist_ok=True)
    if filename:
        with open(os.path.join(dirname, filename), "wb") as f:
            pickle.dump(state, f)
    else:
        for name, arr in state.items():
            with open(os.path.join(dirname, name), "wb") as f:
                pickle.dump({name: arr}, f)


def load_persistables(executor=None, dirname: str = "",
                      main_program: Optional[Program] = None,
                      filename: Optional[str] = None):
    """Inverse of save_persistables; loads into the global scope and the
    program's Parameter objects."""
    import jax.numpy as jnp

    program = main_program or default_main_program()
    scope = global_scope()
    if filename:
        with open(os.path.join(dirname, filename), "rb") as f:
            state = pickle.load(f)
    else:
        state = {}
        for name in program.params:
            path = os.path.join(dirname, name)
            if os.path.exists(path):
                with open(path, "rb") as f:
                    state.update(pickle.load(f))
    for name, arr in state.items():
        if name in program.params:
            scope.store[name] = jnp.asarray(arr)
            program.params[name].set_value(arr)


def load_inference_model_distributed(dirname: str, executor=None,
                                     model_filename: Optional[str] = None,
                                     params_filename: Optional[str] = None):
    """ref io.py:293 — distributed variant of load_inference_model; with the
    single-backend TPU runtime it is the same StableHLO load."""
    prefix = os.path.join(dirname, (model_filename or "model").replace(
        ".pdmodel", ""))
    return load_inference_model(prefix, executor)
