"""paddle.distributed.models.moe — re-export of the expert-parallel MoE stack
(ref python/paddle/distributed/models/moe/ wraps the incubate implementation;
ours lives at paddle_tpu/incubate/distributed/models/moe)."""
from ...incubate.distributed.models.moe import (  # noqa: F401
    ExpertMLP,
    MoELayer,
)
from ...incubate.distributed.models.moe.gate import (  # noqa: F401
    GShardGate,
    NaiveGate,
    SwitchGate,
)
from ..utils.moe_utils import global_gather, global_scatter  # noqa: F401

__all__ = ["MoELayer", "ExpertMLP", "NaiveGate", "GShardGate", "SwitchGate",
           "global_scatter", "global_gather"]
