"""paddle.distributed.models (ref python/paddle/distributed/models/)."""
from . import moe  # noqa: F401

__all__ = []
