"""paddle.distributed.entry_attr (ref python/paddle/distributed/entry_attr.py
— sparse-table feature-admission policies for the parameter server).

The brpc PS itself is a documented non-goal (SURVEY §7: TPU embedding tables
live as sharded dense params), but the admission-policy config objects are
kept: they serialize to the same "policy:arg" strings and are consumed by
sparse-embedding layers that want train-time feature filtering.
"""
from __future__ import annotations

__all__ = []


class EntryAttr:
    """ref entry_attr.py:18"""

    def __init__(self):
        self._name = None

    def _to_attr(self) -> str:
        raise NotImplementedError("use a concrete Entry subclass")


class ProbabilityEntry(EntryAttr):
    """Admit a feature with fixed probability (ref :57)."""

    def __init__(self, probability: float):
        super().__init__()
        if not 0 < probability <= 1:
            raise ValueError(f"probability must be in (0, 1], got {probability}")
        self._name = "probability_entry"
        self._probability = float(probability)

    def _to_attr(self):
        return f"{self._name}:{self._probability}"


class CountFilterEntry(EntryAttr):
    """Admit a feature once seen >= count times (ref :98)."""

    def __init__(self, count_filter: int):
        super().__init__()
        if count_filter < 0:
            raise ValueError(f"count_filter must be >= 0, got {count_filter}")
        self._name = "count_filter_entry"
        self._count_filter = int(count_filter)

    def _to_attr(self):
        return f"{self._name}:{self._count_filter}"


class ShowClickEntry(EntryAttr):
    """Weight features by show/click stats (ref :142)."""

    def __init__(self, show_name: str, click_name: str):
        super().__init__()
        if not isinstance(show_name, str) or not isinstance(click_name, str):
            raise ValueError("show_name/click_name must be variable names")
        self._name = "show_click_entry"
        self._show_name = show_name
        self._click_name = click_name

    def _to_attr(self):
        return f"{self._name}:{self._show_name}:{self._click_name}"
