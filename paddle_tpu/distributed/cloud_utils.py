"""paddle.distributed.cloud_utils (ref cloud_utils.py:27 get_cloud_cluster —
build the Cluster/Pod topology from cloud-scheduler env vars).

TPU note: on TPU pods the runtime publishes topology via its own env
(TPU_WORKER_HOSTNAMES etc.); the Paddle cloud env names are still honored so
launch scripts port over unchanged.
"""
from __future__ import annotations

import os

from .utils.launch_utils import get_cluster, logger

__all__ = []


def get_cloud_cluster(args_node_ips=None, args_node_ip=None, args_port=6170,
                      selected_devices=None):
    """ref cloud_utils.py:27 — prefers PADDLE_TRAINERS/POD_IP env (the cloud
    scheduler contract), falls back to the passed args."""
    node_ips = os.getenv("PADDLE_TRAINERS")
    node_ips = node_ips.split(",") if node_ips else (args_node_ips or ["127.0.0.1"])
    node_ip = os.getenv("POD_IP", args_node_ip or node_ips[0])
    port = int(os.getenv("PADDLE_PORT", args_port))
    devices = selected_devices if selected_devices is not None else [0]

    trainer_endpoints = []
    for ip in node_ips:
        trainer_endpoints.append([f"{ip}:{port + i}" for i in range(len(devices))])
    cluster, pod = get_cluster(node_ips, node_ip, trainer_endpoints, devices)
    logger.debug("cloud cluster: %s", cluster)
    return cluster, pod


def _get_trainers_num() -> int:
    return int(os.getenv("PADDLE_TRAINERS_NUM", 1))


def get_cluster_and_pod(args):
    """ref cloud_utils.py:124"""
    return get_cloud_cluster(
        getattr(args, "cluster_node_ips", None),
        getattr(args, "node_ip", None),
        getattr(args, "started_port", 6170) or 6170,
        list(range(getattr(args, "nproc_per_node", 1) or 1)))
