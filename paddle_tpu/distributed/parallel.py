"""DataParallel wrapper (ref: python/paddle/fluid/dygraph/parallel.py:399 +
C++ EagerReducer reducer.cc:462).

TPU-native: gradient bucketing + async NCCL allreduce is unnecessary — under
pjit with a sharded batch, XLA inserts the gradient psum and overlaps it with
backward compute automatically. Eager mode on a single host already sees all
chips, so DataParallel reduces to: (a) marking the module, (b) providing
no_sync()/gradient averaging semantics for API parity when processes > 1.
"""
from __future__ import annotations

import contextlib

from ..nn.layer_base import Layer
from .collective import ReduceOp, all_reduce
from .env import get_world_size


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25, last_comm_buffer_size=1,
                 find_unused_parameters=False, group=None):
        super().__init__()
        self._layers = layers
        self.add_sublayer("_layers", layers)
        self._group = group
        self.find_unused_parameters = find_unused_parameters
        self._grad_sync_enabled = True

    def forward(self, *inputs, **kwargs):
        out = self._layers(*inputs, **kwargs)
        return out

    @contextlib.contextmanager
    def no_sync(self):
        """Ref parallel.py no_sync — skip grad allreduce inside the context."""
        prev = self._grad_sync_enabled
        self._grad_sync_enabled = False
        try:
            yield
        finally:
            self._grad_sync_enabled = prev

    def sync_gradients(self):
        """Average grads across data-parallel workers (explicit, called by the
        optimizer wrapper or user after backward in multi-process eager)."""
        if not self._grad_sync_enabled or get_world_size() <= 1:
            return
        for p in self._layers.parameters():
            if p.grad is not None:
                all_reduce(p.grad, op=ReduceOp.AVG, group=self._group)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        self.sync_gradients()
