"""paddle.distributed.passes — program-level passes over the static facade
(ref python/paddle/distributed/passes/__init__.py).

Working passes (they rewrite the recorded-op Program that Executor jits):
  auto_parallel_amp / auto_parallel_fp16 / auto_parallel_bf16 — cast
    matmul-class op inputs to low precision (compute hits the MXU in
    bf16/fp16, results stay fp32), the list-based O1 policy of
    ref passes/auto_parallel_amp.py / auto_parallel_bf16.py.
  auto_parallel_recompute — wrap selected ops' fns in jax.checkpoint so
    their outputs are rematerialized in backward (ref
    auto_parallel_recompute.py, which re-inserts fwd sub-blocks).
  auto_parallel_gradient_merge — wrap the program optimizer with a pure
    k-step gradient accumulator (ref auto_parallel_gradient_merge.py).
  auto_parallel_sharding — record ZeRO stage + param shard axis on the
    program for the parallel engine (ref auto_parallel_sharding.py; the
    actual sharding is GSPMD NamedSharding at jit time).
Registered no-ops with rationale (XLA subsumes them): fuse_all_reduce,
fuse_optimizer, fused_attention, fuse_gemm_epilogue.
"""
from .pass_base import (  # noqa: F401
    PassBase,
    PassContext,
    PassManager,
    PassType,
    new_pass,
    register_pass,
)
from . import passes as _passes  # noqa: F401  (registers concrete passes)

__all__ = ["PassBase", "PassContext", "PassManager", "PassType", "new_pass",
           "register_pass"]
