"""Program-pass framework (ref python/paddle/distributed/passes/pass_base.py:
PassContext :20, PassBase :50, register_pass :123, new_pass :132,
PassManager :312).

TPU-native meaning of a "pass": the reference rewrites ProgramDesc protobuf
IR; here a pass rewrites our recorded-op Program (paddle_tpu/static/graph.py)
before Executor.run jits the replay.  Anything a pass leaves in place is
still optimized by XLA — so comm/fusion passes that exist in the reference
purely to do what XLA already does (fuse_all_reduce, fuse_optimizer) are
registered as explicit no-ops with a recorded rationale in the PassContext.
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Dict, List

__all__ = ["PassContext", "PassType", "PassBase", "register_pass", "new_pass",
           "PassManager"]


class PassContext:
    def __init__(self):
        self._applied_passes: List["PassBase"] = []
        self._attrs: Dict[str, Any] = {}
        self.notes: List[str] = []

    @property
    def passes(self):
        return tuple(self._applied_passes)

    def set_attr(self, key, value):
        self._attrs[key] = value

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)


class PassType:
    UNKNOWN = 0
    COMP_OPT = 1
    COMM_OPT = 2
    PARALLEL_OPT = 3
    FUSION_OPT = 4
    CALC_OPT = 5


_PASS_REGISTRY: Dict[str, type] = {}


class PassBase(ABC):
    """One program transform; subclasses set attrs then implement
    _check_self/_apply_single_impl (same contract as the reference)."""

    name: str = ""
    _type = PassType.UNKNOWN

    def __init__(self):
        self._attrs: Dict[str, Any] = {}

    def set_attr(self, key, value):
        self._attrs[key] = value
        return self

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)

    def _check_self(self) -> bool:
        return True

    def _check_conflict(self, other_pass: "PassBase") -> bool:
        return True

    def apply(self, main_programs, startup_programs, context: PassContext = None):
        context = context or PassContext()
        if not isinstance(main_programs, (list, tuple)):
            main_programs = [main_programs]
        if not isinstance(startup_programs, (list, tuple)):
            startup_programs = [startup_programs] * len(main_programs)
        if not self._check_self():
            raise ValueError(f"pass {self.name!r} attrs invalid: {self._attrs}")
        if not all(self._check_conflict(p) for p in context.passes):
            raise ValueError(f"pass {self.name!r} conflicts with already-applied "
                             f"passes {[p.name for p in context.passes]}")
        for main, startup in zip(main_programs, startup_programs):
            self._apply_single_impl(main, startup, context)
        context._applied_passes.append(self)
        return context

    @abstractmethod
    def _apply_single_impl(self, main_program, startup_program, context):
        ...


def register_pass(name):
    def impl(cls):
        if name in _PASS_REGISTRY:
            raise ValueError(f"pass {name!r} already registered")
        cls.name = name
        _PASS_REGISTRY[name] = cls
        return cls
    return impl


def new_pass(name, pass_attrs=None) -> PassBase:
    if name not in _PASS_REGISTRY:
        raise ValueError(f"unknown pass {name!r}; registered: "
                         f"{sorted(_PASS_REGISTRY)}")
    p = _PASS_REGISTRY[name]()
    for k, v in (pass_attrs or {}).items():
        p.set_attr(k, v)
    return p


class PassManager:
    """Ordered application of passes over (main, startup) program pairs
    (ref pass_base.py:312)."""

    def __init__(self, passes: List[PassBase]):
        self._passes = list(passes)
        self._context = PassContext()

    def apply(self, main_programs, startup_programs):
        for p in self._passes:
            p.apply(main_programs, startup_programs, self._context)
        return self._context

    @property
    def context(self):
        return self._context

    @property
    def names(self):
        return [p.name for p in self._passes]
