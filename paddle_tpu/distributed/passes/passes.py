"""Concrete program passes. See package docstring for the mapping to the
reference pass files (python/paddle/distributed/passes/auto_parallel_*.py)."""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .pass_base import PassBase, PassType, register_pass

# ops whose compute should run in low precision under O1 AMP — mirrors the
# white list in ref python/paddle/fluid/dygraph/amp/auto_cast.py (matmul/conv
# class ops; everything reduction/norm-like stays fp32)
_AMP_COMPUTE_OPS = {
    "matmul", "mm", "bmm", "conv2d", "conv3d", "conv1d", "conv2d_transpose",
    "linear", "einsum", "addmm", "matmul_v2", "mul", "fc",
}


def _cast_op_fn(fn, compute_dtype):
    """Wrap an op fn: float32 array inputs -> compute_dtype, float outputs
    back to float32 (bf16 MXU compute, fp32 residuals)."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        cast_args = [a.astype(compute_dtype)
                     if hasattr(a, "dtype") and a.dtype == jnp.float32 else a
                     for a in args]
        out = fn(*cast_args, **kwargs)

        def up(o):
            return (o.astype(jnp.float32)
                    if hasattr(o, "dtype") and o.dtype == compute_dtype else o)

        if isinstance(out, (tuple, list)):
            return type(out)(up(o) for o in out)
        return up(out)

    return wrapped


class _AmpPassBase(PassBase):
    _type = PassType.CALC_OPT
    _dtype = jnp.bfloat16

    def _apply_single_impl(self, main_program, startup_program, context):
        custom_white = set(self.get_attr("custom_white_list") or ())
        white = _AMP_COMPUTE_OPS | custom_white
        n = 0
        for op in main_program.ops:
            if op.op_name in white:
                op.fn = _cast_op_fn(op.fn, self._dtype)
                n += 1
        main_program._version += 1
        context.notes.append(
            f"{self.name}: cast {n} compute ops to {jnp.dtype(self._dtype).name}")


@register_pass("auto_parallel_bf16")
class AutoParallelBF16Pass(_AmpPassBase):
    _dtype = jnp.bfloat16


@register_pass("auto_parallel_fp16")
class AutoParallelFP16Pass(_AmpPassBase):
    _dtype = jnp.float16


@register_pass("auto_parallel_amp")
class AutoParallelAMPPass(_AmpPassBase):
    """O1 AMP; attr 'dtype' selects float16 (default, ref) or bfloat16."""

    def _apply_single_impl(self, main_program, startup_program, context):
        self._dtype = (jnp.bfloat16 if self.get_attr("dtype") == "bfloat16"
                       else jnp.float16)
        super()._apply_single_impl(main_program, startup_program, context)


@register_pass("auto_parallel_recompute")
class AutoParallelRecomputePass(PassBase):
    """Remat: wrap op fns in jax.checkpoint so their activations are
    recomputed in backward instead of saved (ref auto_parallel_recompute.py
    rebuilds forward sub-blocks in the backward region)."""

    _type = PassType.COMP_OPT

    def _apply_single_impl(self, main_program, startup_program, context):
        selected = self.get_attr("checkpoints")  # op names; None -> all
        n = 0
        for op in main_program.ops:
            if selected is None or op.op_name in selected:
                op.fn = jax.checkpoint(op.fn, static_argnums=())
                n += 1
        main_program._version += 1
        context.notes.append(f"{self.name}: remat-wrapped {n} ops")


class _GradientMergeOptimizer:
    """Pure k-step gradient accumulation around an optimizer (the state
    threads through Executor.run's opt_state untouched)."""

    def __init__(self, inner, k_steps: int, avg: bool = True):
        self.inner = inner
        self.k_steps = int(k_steps)
        self.avg = avg

    def init_state(self, params):
        return {
            "inner": self.inner.init_state(params),
            "acc": {k: jnp.zeros_like(v, dtype=jnp.float32)
                    for k, v in params.items()},
            "cnt": jnp.zeros((), dtype=jnp.int32),
        }

    def get_lr(self):
        return self.inner.get_lr()

    def pure_update(self, params, grads, state, lr, step, pnames=None,
                    regularizers=None):
        acc = {k: state["acc"][k] + grads[k].astype(jnp.float32)
               for k in grads}
        cnt = state["cnt"] + 1
        do_step = (cnt % self.k_steps) == 0

        def apply_fn(operand):
            params_, acc_, inner_state = operand
            eff = ({k: v / self.k_steps for k, v in acc_.items()}
                   if self.avg else acc_)
            new_params, new_inner = self.inner.pure_update(
                params_, eff, inner_state, lr, step,
                regularizers=regularizers)
            zeroed = {k: jnp.zeros_like(v) for k, v in acc_.items()}
            return new_params, new_inner, zeroed

        def skip_fn(operand):
            params_, acc_, inner_state = operand
            return params_, inner_state, acc_

        new_params, new_inner, new_acc = jax.lax.cond(
            do_step, apply_fn, skip_fn, (params, acc, state["inner"]))
        return new_params, {"inner": new_inner, "acc": new_acc, "cnt": cnt}

    def __getattr__(self, item):
        return getattr(self.inner, item)


@register_pass("auto_parallel_gradient_merge")
class AutoParallelGradientMergePass(PassBase):
    _type = PassType.COMP_OPT

    def _check_self(self):
        return int(self.get_attr("k_steps", 1)) >= 1

    def _apply_single_impl(self, main_program, startup_program, context):
        k = int(self.get_attr("k_steps", 1))
        if k <= 1 or main_program.optimizer is None:
            context.notes.append(f"{self.name}: skipped (k_steps={k})")
            return
        main_program.optimizer = _GradientMergeOptimizer(
            main_program.optimizer, k, avg=bool(self.get_attr("avg", True)))
        context.notes.append(f"{self.name}: k_steps={k}")


@register_pass("auto_parallel_sharding")
class AutoParallelShardingPass(PassBase):
    """Record the ZeRO stage / shard axis on the program; the parallel engine
    turns this into NamedSharding on params+opt state at jit time (GSPMD
    inserts the reduce-scatter/allgather the reference pass writes by hand)."""

    _type = PassType.PARALLEL_OPT

    def _apply_single_impl(self, main_program, startup_program, context):
        stage = int(self.get_attr("stage", 1))
        axis = self.get_attr("sharding_axis", "sharding")
        main_program.sharding_config = {"stage": stage, "axis": axis}
        context.notes.append(f"{self.name}: stage={stage} axis={axis!r}")


class _XLANoOpPass(PassBase):
    """Passes the reference needs but XLA already performs inside the compiled
    program; applying them records the rationale."""

    _type = PassType.FUSION_OPT
    rationale = "subsumed by XLA fusion/scheduling"

    def _apply_single_impl(self, main_program, startup_program, context):
        context.notes.append(f"{self.name}: no-op ({self.rationale})")


@register_pass("fuse_all_reduce")
class FuseAllReducePass(_XLANoOpPass):
    rationale = ("gradient all-reduces are emitted and bucketed by GSPMD "
                 "inside the jitted train step")


@register_pass("fuse_optimizer")
class FuseOptimizerPass(_XLANoOpPass):
    rationale = "optimizer update is one fused XLA program already"


@register_pass("fused_attention")
class FusedAttentionPass(_XLANoOpPass):
    rationale = "attention uses the Pallas flash kernel (paddle_tpu/ops)"


@register_pass("fuse_gemm_epilogue")
class FuseGemmEpiloguePass(_XLANoOpPass):
    rationale = "matmul+bias+activation epilogues are fused by XLA"
