"""paddle.distributed.metric (ref python/paddle/distributed/metric/)."""
from .metrics import (  # noqa: F401
    get_metric,
    init_metric,
    print_auc,
    print_metric,
    update_metric,
)

__all__ = []
