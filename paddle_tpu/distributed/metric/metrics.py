"""Distributed metrics (ref python/paddle/distributed/metric/metrics.py:26
init_metric — PS-side global AUC aggregated over workers with gloo, :151
print_metric, :182 print_auc).

TPU-native: the reference computes global AUC by gloo-allreducing per-worker
confusion histograms inside the C++ PS metric manager.  Here the same math
runs on-device: each process accumulates a fixed-bin prediction histogram per
label, `all_reduce` (XLA collective / multihost broadcast) merges them, and
AUC is the trapezoid integral over the merged histogram — identical to the
reference's bucketed AUC (ctr_accessor AUC buckets).
"""
from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, to_array

__all__ = []

_NUM_BUCKETS = 4096


class _AucAccumulator:
    def __init__(self, name: str, num_buckets: int = _NUM_BUCKETS):
        self.name = name
        self.num_buckets = num_buckets
        self.pos = np.zeros(num_buckets, dtype=np.float64)
        self.neg = np.zeros(num_buckets, dtype=np.float64)

    def update(self, preds, labels):
        preds = np.asarray(to_array(preds) if isinstance(preds, Tensor) else preds,
                           dtype=np.float64).reshape(-1)
        labels = np.asarray(to_array(labels) if isinstance(labels, Tensor) else labels,
                            dtype=np.float64).reshape(-1)
        idx = np.clip((preds * self.num_buckets).astype(np.int64), 0,
                      self.num_buckets - 1)
        np.add.at(self.pos, idx, labels)
        np.add.at(self.neg, idx, 1.0 - labels)

    def global_hist(self):
        """Merge histograms across processes (the gloo allreduce of ref
        metrics.py) via the collective backend; no-op single-process."""
        from .. import collective as C

        pos = Tensor(jnp.asarray(self.pos))
        neg = Tensor(jnp.asarray(self.neg))
        try:
            C.all_reduce(pos)
            C.all_reduce(neg)
        except Exception:
            pass
        return np.asarray(to_array(pos)), np.asarray(to_array(neg))

    def compute(self) -> float:
        pos, neg = self.global_hist()
        # descending threshold sweep: high buckets are predicted-positive first
        tp = np.cumsum(pos[::-1])
        fp = np.cumsum(neg[::-1])
        total_pos, total_neg = tp[-1], fp[-1]
        if total_pos == 0 or total_neg == 0:
            return 0.5
        tpr = np.concatenate([[0.0], tp / total_pos])
        fpr = np.concatenate([[0.0], fp / total_neg])
        trapezoid = getattr(np, "trapezoid", np.trapz)
        return float(trapezoid(tpr, fpr))


_METRICS: Dict[str, _AucAccumulator] = {}


def init_metric(metric_ptr=None, metric_config: Optional[str] = None,
                name: str = "auc", method: str = "bucket",
                num_buckets: int = _NUM_BUCKETS, **kwargs):
    """Register a named global metric accumulator (ref metrics.py:26 parses a
    yaml config into the PS metric manager; here config is kwargs)."""
    _METRICS[name] = _AucAccumulator(name, num_buckets)
    return _METRICS[name]


def update_metric(name: str, preds, labels):
    _METRICS[name].update(preds, labels)


def get_metric(name: str) -> float:
    return _METRICS[name].compute()


def print_metric(metric_ptr=None, name: str = "auc") -> str:
    """ref metrics.py:151"""
    value = _METRICS[name].compute()
    msg = f"global metric {name}: AUC={value:.6f}"
    print(msg)
    return msg


def print_auc(metric_ptr=None, is_day: bool = False, phase: str = "all",
              name: str = "auc") -> float:
    """ref metrics.py:182"""
    value = _METRICS[name].compute()
    print(f"[{'day' if is_day else 'pass'}:{phase}] AUC={value:.6f}")
    return value
