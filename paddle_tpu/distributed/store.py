"""TCPStore — native rendezvous KV store.

API parity with the reference's store (ref paddle/phi/core/distributed/store/
tcp_store.h TCPStore: set/get/add/wait + world-size barrier), used to
bootstrap multi-host jobs before jax.distributed is up.  The data path is the
C++ poll-loop server in ``csrc/tcp_store.cpp`` loaded via ctypes; when the
shared object is missing (fresh checkout, no toolchain) a pure-Python
``socketserver`` fallback with the same wire protocol semantics is used from
``launch/rendezvous.py``.
"""
from __future__ import annotations

import ctypes
import threading
import time
from typing import Optional, Tuple

_lib = None
_lib_lock = threading.Lock()


def _load():
    from ..utils.native_build import ensure_lib

    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        path = ensure_lib("tcp_store")
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.pts_server_start.restype = ctypes.c_void_p
        lib.pts_server_start.argtypes = [ctypes.c_int]
        lib.pts_server_stop.argtypes = [ctypes.c_void_p]
        lib.pts_client_connect.restype = ctypes.c_void_p
        lib.pts_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                           ctypes.c_int]
        lib.pts_client_close.argtypes = [ctypes.c_void_p]
        lib.pts_set.restype = ctypes.c_int
        lib.pts_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_char_p, ctypes.c_int]
        lib.pts_get.restype = ctypes.c_int
        lib.pts_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_char_p, ctypes.c_int]
        lib.pts_add.restype = ctypes.c_int64
        lib.pts_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_int64]
        lib.pts_wait.restype = ctypes.c_int
        lib.pts_wait.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_int64, ctypes.c_char_p, ctypes.c_int]
        lib.pts_num_keys.restype = ctypes.c_int64
        lib.pts_num_keys.argtypes = [ctypes.c_void_p]
        lib.pts_delete.restype = ctypes.c_int
        lib.pts_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.pts_setnx.restype = ctypes.c_int
        lib.pts_setnx.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_char_p, ctypes.c_int,
                                  ctypes.c_char_p, ctypes.c_int,
                                  ctypes.POINTER(ctypes.c_int)]
        _lib = lib
        return _lib


_MAX_VAL = 1 << 20


class PortInUseError(OSError):
    """Server socket could not bind — distinct from connect timeouts so the
    launch rendezvous can fall back to client mode ONLY for this case."""


class TCPStore:
    """ref TCPStore(host, port, is_master, world_size, timeout).

    The master rank also runs the server; every rank (master included) is a
    client. ``native`` is False when running on the Python fallback."""

    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: float = 120.0):
        self.host, self.port = host, port
        self.is_master = is_master
        self.world_size = world_size
        self.timeout = timeout
        self._server = None
        self._client = None
        self._py = None
        self._barrier_rounds: dict = {}
        lib = _load()
        if lib is not None:
            if is_master:
                self._server = lib.pts_server_start(port)
                if not self._server:
                    raise PortInUseError(f"TCPStore: cannot bind port {port}")
            self._client = lib.pts_client_connect(
                host.encode(), port, int(timeout * 1000))
            if not self._client:
                if self._server:
                    lib.pts_server_stop(self._server)
                raise TimeoutError(
                    f"TCPStore: cannot reach {host}:{port} within {timeout}s")
        else:  # pure-Python fallback (JSON wire protocol, str values)
            from .launch.rendezvous import KVServer, KVClient

            if is_master:
                try:
                    self._py_server = KVServer(port)
                except OSError as e:
                    raise PortInUseError(str(e)) from e
            self._py = KVClient(f"{host}:{port}")

    @property
    def native(self) -> bool:
        return self._client is not None

    def set(self, key: str, value) -> None:
        data = value if isinstance(value, bytes) else str(value).encode()
        if self._py is not None:
            self._py.set(key, data.decode("latin-1"))
            return
        if _lib.pts_set(self._client, key.encode(), data, len(data)) != 0:
            raise RuntimeError(f"TCPStore.set({key!r}) failed")

    def get(self, key: str) -> bytes:
        """Blocking get (reference get waits for the key)."""
        return self.wait(key, self.timeout)

    def try_get(self, key: str) -> Optional[bytes]:
        if self._py is not None:
            v = self._py.get(key)
            return None if v is None else v.encode("latin-1")
        buf = ctypes.create_string_buffer(_MAX_VAL)
        n = _lib.pts_get(self._client, key.encode(), buf, _MAX_VAL)
        if n == -2:
            raise ConnectionError(
                f"TCPStore: connection to {self.host}:{self.port} lost")
        if n == -3:
            raise ValueError(
                f"TCPStore value for {key!r} exceeds the {_MAX_VAL} byte limit")
        return None if n < 0 else buf.raw[:n]

    def add(self, key: str, delta: int = 1) -> int:
        if self._py is not None:
            return self._py.add(key, delta)
        v = _lib.pts_add(self._client, key.encode(), delta)
        if v == -(2 ** 63):
            raise RuntimeError(f"TCPStore.add({key!r}) failed")
        return int(v)

    def wait(self, key: str, timeout: Optional[float] = None) -> bytes:
        t = self.timeout if timeout is None else timeout
        if self._py is not None:
            deadline = time.time() + t
            while time.time() < deadline:
                v = self.try_get(key)
                if v is not None:
                    return v
                time.sleep(0.05)
            raise TimeoutError(f"TCPStore.wait({key!r}) timed out after {t}s")
        buf = ctypes.create_string_buffer(_MAX_VAL)
        n = _lib.pts_wait(self._client, key.encode(), int(t * 1000), buf,
                          _MAX_VAL)
        if n == -2:
            raise ConnectionError(
                f"TCPStore: connection to {self.host}:{self.port} lost")
        if n == -3:
            raise ValueError(
                f"TCPStore value for {key!r} exceeds the {_MAX_VAL} byte limit")
        if n < 0:
            raise TimeoutError(f"TCPStore.wait({key!r}) timed out after {t}s")
        return buf.raw[:n]

    def set_nx(self, key: str, value) -> Tuple[bool, bytes]:
        """Set-if-absent (atomic claim). Returns (claimed, current_value) —
        the winning writer's value, delivered atomically with the claim in
        one round trip. The crash-safe primitive the launch rendezvous
        builds rank slots on."""
        data = value if isinstance(value, bytes) else str(value).encode()
        if self._py is not None:
            r = self._py.setnx(key, data.decode("latin-1"))
            return r["claimed"], r["value"].encode("latin-1")
        buf = ctypes.create_string_buffer(_MAX_VAL)
        claimed = ctypes.c_int(0)
        n = _lib.pts_setnx(self._client, key.encode(), data, len(data), buf,
                           _MAX_VAL, ctypes.byref(claimed))
        if n == -2:
            raise ConnectionError(
                f"TCPStore: connection to {self.host}:{self.port} lost")
        if n == -3:
            raise ValueError(
                f"TCPStore value for {key!r} exceeds the {_MAX_VAL} byte limit")
        return bool(claimed.value), buf.raw[:n]

    def delete_key(self, key: str) -> bool:
        if self._py is not None:
            return self._py.delete(key)
        return _lib.pts_delete(self._client, key.encode()) == 0

    def num_keys(self) -> int:
        if self._py is not None:
            return len(self._py.list(""))
        return int(_lib.pts_num_keys(self._client))

    def barrier(self, name: str = "barrier", timeout: Optional[float] = None):
        """All world_size ranks arrive before any leaves (ref barrier via
        add + wait-for-count). Reusable: each call uses a fresh round-numbered
        key, assuming every rank calls barrier() the same number of times
        (the standard collective contract)."""
        rnd = self._barrier_rounds.get(name, 0)
        self._barrier_rounds[name] = rnd + 1
        key = f"/{name}/{rnd}"
        n = self.add(f"{key}/count", 1)
        if n == self.world_size:
            self.set(f"{key}/done", b"1")
        self.wait(f"{key}/done", timeout)

    def close(self):
        lib = _lib
        if self._client is not None and lib is not None:
            lib.pts_client_close(self._client)
            self._client = None
        if self._server is not None and lib is not None:
            lib.pts_server_stop(self._server)
            self._server = None
        if getattr(self, "_py_server", None) is not None:
            self._py_server.stop()
            self._py_server = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
