"""Partition/reshard/convert machinery of the semi-auto SPMD system.

Reference surface (python/paddle/distributed/auto_parallel/): completion.py
(dist-attr propagation), partitioner.py (per-rank program slicing), reshard.py
(comm insertion for mismatched shardings), converter.py (checkpoint reshard
across strategy changes), cluster.py (topology description).

TPU-native behavior: GSPMD does propagation/partition/comm-insertion inside
XLA, so these classes expose the *results* of that pipeline — the sharding
annotations XLA settled on, the per-rank local shapes, and device_put-based
resharding — the same artifacts the reference's partitioner tests assert on
(SURVEY §4: program-text checks without N devices).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P


class Completer:
    """Ref completion.py — propagate dist attrs over the whole graph.

    GSPMD runs propagation during compilation; ``complete`` compiles the
    function with the given input shardings and reports the shardings XLA
    chose for every output (and, via ``hlo_text``, every internal op)."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def complete(self, fn, *example_args, in_specs: Optional[Sequence] = None):
        shardings = None
        if in_specs is not None:
            shardings = [NamedSharding(self.mesh, s if isinstance(s, P) else
                                       P(*s) if s else P())
                         for s in in_specs]
        with self.mesh:
            lowered = jax.jit(fn, in_shardings=shardings).lower(*example_args)
            compiled = lowered.compile()
        return CompletedProgram(lowered, compiled)


class CompletedProgram:
    def __init__(self, lowered, compiled):
        self._lowered = lowered
        self._compiled = compiled

    @property
    def hlo_text(self) -> str:
        """Optimized HLO with sharding={...} annotations — the analogue of
        the reference's annotated ProgramDesc text."""
        return self._compiled.as_text()

    def output_shardings(self) -> list:
        out = self._compiled.output_shardings
        return list(out) if isinstance(out, (list, tuple)) else [out]

    def input_shardings(self) -> list:
        ins = self._compiled.input_shardings
        if isinstance(ins, tuple) and len(ins) == 2 and isinstance(ins[0],
                                                                   (list, tuple)):
            ins = ins[0]  # (args, kwargs) form
        return list(ins) if isinstance(ins, (list, tuple)) else [ins]


class Partitioner:
    """Ref partitioner.py — slice the global program per rank. On TPU the
    compiled executable is already per-device SPMD; this reports each
    tensor's local (per-rank) shard shape for a given PartitionSpec."""

    def __init__(self, mesh: Mesh, rank: int = 0):
        self.mesh = mesh
        self.rank = rank

    def local_shape(self, global_shape: Sequence[int], spec) -> tuple:
        s = spec if isinstance(spec, P) else P(*spec) if spec else P()
        return NamedSharding(self.mesh, s).shard_shape(tuple(global_shape))

    def partition_state(self, state: Dict[str, Any],
                        specs: Dict[str, Any]) -> Dict[str, tuple]:
        """Local shapes for every parameter (what each rank will hold)."""
        return {name: self.local_shape(np.shape(getattr(v, "value", v)),
                                       specs.get(name))
                for name, v in state.items()}


class Resharder:
    """Ref reshard.py — insert communication so a tensor laid out as
    ``src_spec`` becomes ``dst_spec``. device_put on a NamedSharding: XLA
    emits the all-gather/all-to-all/slice pattern."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def reshard(self, x, dst_spec):
        s = dst_spec if isinstance(dst_spec, P) else \
            P(*dst_spec) if dst_spec else P()
        val = getattr(x, "value", x)
        return jax.device_put(val, NamedSharding(self.mesh, s))


class Converter:
    """Ref converter.py — reshard a checkpoint across parallel-strategy
    changes: params saved under one (mesh, specs) layout are placed onto a
    new mesh/specs on load."""

    def __init__(self, state_dict: Dict[str, Any],
                 pre_strategy: Optional[Dict[str, Any]] = None,
                 cur_strategy: Optional[Dict[str, Any]] = None):
        self.state_dict = state_dict
        self.pre_strategy = pre_strategy or {}
        self.cur_strategy = cur_strategy or {}

    def convert(self, mesh: Mesh, specs: Optional[Dict[str, Any]] = None):
        specs = specs if specs is not None else self.cur_strategy
        r = Resharder(mesh)
        out = {}
        for name, v in self.state_dict.items():
            val = np.asarray(getattr(v, "value", v))
            out[name] = r.reshard(val, specs.get(name))
        return out


class Cluster:
    """Ref cluster.py — machine/device topology description, built from the
    live jax device set instead of a JSON cluster spec."""

    def __init__(self):
        devs = jax.devices()
        self.device_count = len(devs)
        self.process_count = jax.process_count()
        self.devices = [{
            "id": d.id,
            "process_index": d.process_index,
            "kind": getattr(d, "device_kind", "cpu"),
            "platform": d.platform,
            "coords": list(getattr(d, "coords", []) or []),
        } for d in devs]

    def machine_count(self):
        return self.process_count

    def device_kinds(self):
        return sorted({d["kind"] for d in self.devices})

    def __repr__(self):
        return (f"Cluster(processes={self.process_count}, "
                f"devices={self.device_count}, kinds={self.device_kinds()})")
