"""Rule-based parallel-strategy tuner.

Ref: python/paddle/distributed/auto_parallel/tuner/rule_based_tuner.py (+
cost_model.py): the reference searches dist-attr assignments over the op
graph with a cost model. On TPU the search space is the mesh shape itself —
(dp, sharding, tensor, pipe, context, expert) degrees — because GSPMD takes
care of per-op propagation once the mesh and the weight PartitionSpecs are
fixed. The rules encode the scaling-book recipe: shard params until they
fit (ZeRO axis), add TP when a single layer's working set or the per-chip
batch gets too small, add PP only past the TP sweet spot, keep DP for the
rest; context axis only for long sequences.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional


@dataclasses.dataclass
class ModelDesc:
    n_params: int                    # total parameter count
    hidden_size: int = 4096
    num_layers: int = 32
    num_attention_heads: int = 32
    seq_len: int = 4096
    vocab_size: int = 32000
    dtype_bytes: int = 2             # bf16 params


# optimizer-state memory model: bf16 param + bf16 grad + fp32 master +
# 2×fp32 Adam moments — shared by the rule-based tuner and the cost model
BYTES_PER_PARAM = 16.0


@dataclasses.dataclass
class ClusterDesc:
    n_devices: int
    hbm_bytes: int = 16 << 30        # v5e default
    devices_per_host: int = 4        # ICI island size for TP preference
    peak_flops: float = 197e12       # per chip (v5e)
    ici_bw: float = 1.6e11           # bytes/s per link direction


@dataclasses.dataclass
class TunedStrategy:
    dp: int = 1
    sharding: int = 1
    tensor: int = 1
    pipe: int = 1
    context: int = 1

    def degrees(self) -> Dict[str, int]:
        return {"dp": self.dp, "sharding": self.sharding, "tensor": self.tensor,
                "pipe": self.pipe, "context": self.context}

    def total(self) -> int:
        return self.dp * self.sharding * self.tensor * self.pipe * self.context


def tune(model: ModelDesc, cluster: ClusterDesc,
         max_seq_per_chip: int = 8192) -> TunedStrategy:
    """Pick mesh degrees for a transformer of ``model``'s size on ``cluster``.

    Memory model (per chip): params+grads+AdamW state ≈ 16 bytes/param when
    unsharded (bf16 param + bf16 grad + fp32 master + 2×fp32 moments),
    divided by (sharding × tensor × pipe).
    """
    n = cluster.n_devices
    s = TunedStrategy()
    bytes_per_param = BYTES_PER_PARAM
    budget = 0.6 * cluster.hbm_bytes  # leave room for activations

    # 1) TP: needed when one layer is too fat for a chip, preferred ≤ ICI island
    layer_bytes = bytes_per_param * model.n_params / max(model.num_layers, 1)
    tp = 1
    while (layer_bytes / tp > 0.25 * budget and tp < cluster.devices_per_host
           and tp * 2 <= n and model.num_attention_heads % (tp * 2) == 0):
        tp *= 2
    s.tensor = tp

    # 2) context axis for long sequences (ring attention)
    ctx = 1
    while model.seq_len // ctx > max_seq_per_chip and s.tensor * ctx * 2 <= n:
        ctx *= 2
    s.context = ctx

    # 3) ZeRO sharding until the full state fits
    remaining = n // (s.tensor * s.context)
    shard = 1
    while (bytes_per_param * model.n_params / (s.tensor * shard) > budget
           and shard * 2 <= remaining):
        shard *= 2
    s.sharding = shard

    # 4) PP only when sharding+TP still don't fit (very large models)
    remaining = n // (s.tensor * s.context * s.sharding)
    pp = 1
    while (bytes_per_param * model.n_params / (s.tensor * s.sharding * pp) > budget
           and pp * 2 <= remaining and model.num_layers % (pp * 2) == 0):
        pp *= 2
    s.pipe = pp

    # 5) everything left is DP
    s.dp = max(1, n // (s.tensor * s.context * s.sharding * s.pipe))
    return s


class RuleBasedTuner:
    """Object facade over :func:`tune` (ref rule_based_tuner.py class shape)."""

    def __init__(self, model: ModelDesc, cluster: Optional[ClusterDesc] = None):
        import jax

        self.model = model
        self.cluster = cluster or ClusterDesc(n_devices=len(jax.devices()))

    def tune(self) -> TunedStrategy:
        return tune(self.model, self.cluster)

    def build_mesh(self):
        """Materialize the tuned strategy as a jax Mesh."""
        import jax
        import numpy as np
        from jax.sharding import Mesh

        avail = len(jax.devices())
        if self.cluster.n_devices > avail:
            # tuned for a bigger pod than is attached — re-tune to what exists
            s = tune(self.model, dataclasses.replace(self.cluster, n_devices=avail))
        else:
            s = self.tune()
        degs = s.degrees()
        names = [k for k, v in degs.items() if v > 1] or ["dp"]
        shape = [degs[k] for k in names]
        devs = np.asarray(jax.devices()[: int(np.prod(shape))]).reshape(shape)
        axis_rename = {"dp": "data", "pipe": "pipe", "tensor": "tensor",
                       "sharding": "sharding", "context": "context"}
        return Mesh(devs, tuple(axis_rename[k] for k in names))
