"""Semi-auto SPMD API (ref: python/paddle/distributed/auto_parallel/ —
Engine engine.py:58, interface.py shard_tensor:28/shard_op:108,
process_mesh.py, Partitioner/Resharder).

TPU-native: ProcessMesh == jax Mesh; shard_tensor == device_put with a
NamedSharding; the Partitioner+Resharder pipeline == GSPMD (XLA propagates
dist attrs and inserts resharding collectives); Engine == ParallelEngine.
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ...parallel.api import shard_constraint, shard_tensor as _shard_tensor
from ...parallel.engine import ParallelEngine


class ProcessMesh:
    """Ref auto_parallel/process_mesh.py — ndarray of ranks with dim names."""

    def __init__(self, mesh: Sequence, dim_names: Optional[Sequence[str]] = None,
                 process_ids=None):
        self._mesh_arr = np.asarray(mesh)
        self._dim_names = list(dim_names) if dim_names else \
            [f"d{i}" for i in range(self._mesh_arr.ndim)]

    @property
    def shape(self):
        return list(self._mesh_arr.shape)

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return self._mesh_arr.reshape(-1).tolist()

    def to_jax_mesh(self) -> Mesh:
        devs = np.asarray(jax.devices())[self._mesh_arr]
        return Mesh(devs, tuple(self._dim_names))

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and \
            np.array_equal(self._mesh_arr, other._mesh_arr)

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names})"


def shard_tensor(x, process_mesh=None, shard_spec=None, mesh=None, placements=None):
    """Ref interface.py:28. shard_spec: list of dim names or None per axis."""
    jmesh = None
    if isinstance(process_mesh, ProcessMesh):
        jmesh = process_mesh.to_jax_mesh()
    elif isinstance(process_mesh, Mesh):
        jmesh = process_mesh
    elif mesh is not None:
        jmesh = mesh.to_jax_mesh() if isinstance(mesh, ProcessMesh) else mesh
    return _shard_tensor(x, mesh=jmesh, shard_spec=shard_spec)


def shard_op(op_fn, process_mesh=None, in_shard_specs=None, out_shard_specs=None):
    """Ref interface.py:108 — annotate an op's in/out shardings; on TPU a
    wrapper adding with_sharding_constraint on the outputs."""

    def wrapped(*args, **kwargs):
        out = op_fn(*args, **kwargs)
        if out_shard_specs:
            spec = out_shard_specs[0] if isinstance(out_shard_specs, (list, tuple)) \
                else out_shard_specs
            out = shard_constraint(out, P(*[s if s else None for s in spec]))
        return out

    return wrapped


class Strategy:
    """Ref auto_parallel/strategy.py."""

    def __init__(self):
        self.auto_mode = "semi"
        self.amp = _Cfg(enable=False, dtype="bfloat16")
        self.recompute = _Cfg(enable=False)
        self.sharding = _Cfg(enable=False, degree=1, stage=1)
        self.gradient_merge = _Cfg(enable=False, k_steps=1)


class _Cfg:
    def __init__(self, **kw):
        self.__dict__.update(kw)


class Engine:
    """Ref engine.py:58 — fit/evaluate/predict driving the sharded step.

    Wraps ParallelEngine: _build+_parallel (engine.py:515,:700) are replaced
    by jit-with-shardings."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.strategy = strategy or Strategy()
        self._engine: Optional[ParallelEngine] = None

    def _ensure(self):
        if self._engine is None:
            fsdp = bool(self.strategy.sharding.enable)
            remat = bool(self.strategy.recompute.enable)
            loss_fn = self.loss
            if hasattr(loss_fn, "forward"):  # Layer-style loss
                layer = loss_fn

                def loss_fn(*args):
                    return layer(*args)

            self._engine = ParallelEngine(self.model, optimizer=self.optimizer,
                                          loss_fn=loss_fn, fsdp=fsdp, remat=remat,
                                          donate=False)
        return self._engine

    @staticmethod
    def _loader(data, batch_size):
        from ...io import DataLoader

        return data if isinstance(data, DataLoader) else DataLoader(
            data, batch_size=batch_size)

    def fit(self, train_data, epochs=1, batch_size=1, steps_per_epoch=None,
            log_freq=10, verbose=1):
        eng = self._ensure()
        loader = self._loader(train_data, batch_size)
        history = []
        for epoch in range(epochs):
            for step, batch in enumerate(loader):
                if steps_per_epoch is not None and step >= steps_per_epoch:
                    break
                loss = eng.train_batch(*batch)
                if verbose and step % log_freq == 0:
                    print(f"epoch {epoch} step {step} loss "
                          f"{float(np.asarray(loss.value)):.4f}")
                history.append(float(np.asarray(loss.value)))
        return history

    def evaluate(self, eval_data, batch_size=1):
        eng = self._ensure()
        loader = self._loader(eval_data, batch_size)
        losses = [float(np.asarray(eng.eval_batch(*batch).value)) for batch in loader]
        return {"loss": float(np.mean(losses))}

    def predict(self, test_data, batch_size=1, has_labels=True):
        """Ref engine.py predict — forward-only over a dataset.

        ``has_labels``: whether each batch's LAST element is a label to drop
        (the train-step convention). Pass False for unlabeled test data so
        multi-input models receive every element."""
        # trained weights live in the engine's donated buffers; flow them
        # back into the Layer before predicting with it
        self._ensure().sync_to_model()
        loader = self._loader(test_data, batch_size)
        outs = []
        for batch in loader:
            if not isinstance(batch, (list, tuple)):
                xs = [batch]
            elif has_labels and len(batch) > 1:
                xs = batch[:-1]
            else:
                xs = batch
            outs.append(self.model(*xs))
        return outs

    def save(self, path, training=True):
        from ...framework.io_state import save

        eng = self._ensure()
        save(eng.state_dict(), path + ".pdparams")

    def load(self, path):
        from ...framework.io_state import load

        sd = load(path + ".pdparams")
        self.model.set_state_dict(sd)
        if self._engine is not None:
            self._engine._build_state()


def get_mesh():
    from ...distributed.collective import get_global_mesh

    return get_global_mesh()


from .partition import (Cluster, CompletedProgram, Completer, Converter,  # noqa: E402
                        Partitioner, Resharder)
from .tuner import ClusterDesc, ModelDesc, RuleBasedTuner, TunedStrategy, tune  # noqa: E402
from .cost_model import CostBreakdown, estimate_step_time, search  # noqa: E402
