"""Analytical cost model for parallel-strategy search.

Ref: python/paddle/distributed/auto_parallel/cost_model.py + cost/ (per-op
comp/comm cost classes fed from measured latency tables). TPU-native
redesign: there is no per-op latency table to keep — XLA fuses everything —
so the model is the roofline the scaling-book recipe reasons with:

- compute: dense transformer step FLOPs (6·N per token fwd+bwd) at an
  efficiency-derated peak,
- DP/ZeRO gradient reduction: ring-allreduce bytes over ICI (overlappable
  with backward: only the non-overlapped fraction is charged),
- TP: two allreduces of the activation block per layer (Megatron pattern),
- PP: the fill/drain bubble (pp-1)/micro stretching the step,
- memory: 16 bytes/param optimizer-state model (bf16 param+grad, fp32
  master+moments) divided over the sharding axes, plus activation bytes
  with remat assumed for what doesn't fit.

Numbers are *relative* — good enough to rank candidate meshes, which is all
the tuner needs (the reference's tables serve the same purpose).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from .tuner import BYTES_PER_PARAM, ClusterDesc, ModelDesc, TunedStrategy

MFU_CEILING = 0.55       # realistic dense-transformer efficiency ceiling
OVERLAP = 0.7            # fraction of grad reduction hidden under backward


@dataclasses.dataclass
class CostBreakdown:
    compute_s: float
    dp_comm_s: float
    tp_comm_s: float
    pp_bubble_frac: float    # dimensionless step stretch, NOT seconds
    feasible: bool
    mem_bytes: float

    @property
    def step_s(self) -> float:
        busy = self.compute_s + self.tp_comm_s + self.dp_comm_s
        return busy * (1.0 + self.pp_bubble_frac)


def _ring_allreduce_bytes(nbytes: float, n: int) -> float:
    return 2.0 * (n - 1) / max(n, 1) * nbytes


def estimate_step_time(model: ModelDesc, cluster: ClusterDesc,
                       s: TunedStrategy, global_batch: int = 32,
                       num_micro: Optional[int] = None) -> CostBreakdown:
    """Predict one training-step time for strategy ``s`` (relative units)."""
    n = s.total()
    assert n <= cluster.n_devices, \
        f"strategy needs {n} devices, cluster has {cluster.n_devices}"
    tokens = global_batch * model.seq_len
    tokens_per_chip = tokens / max(s.dp * s.context * s.sharding, 1)
    # model FLOPs: 6·N per token (fwd+bwd matmuls), split over tp×pp
    flops_per_chip = 6.0 * model.n_params * tokens_per_chip / (s.tensor * s.pipe)
    compute_s = flops_per_chip / (cluster.peak_flops * MFU_CEILING)

    # DP/ZeRO grad reduction: each chip owns n_params/(tp·pp) grads in bf16,
    # reduced over dp·sharding ranks; OVERLAP of it hides under backward
    red_ranks = s.dp * s.sharding
    grad_bytes = model.dtype_bytes * model.n_params / (s.tensor * s.pipe)
    dp_comm_s = 0.0
    if red_ranks > 1:
        dp_comm_s = (1 - OVERLAP) * _ring_allreduce_bytes(
            grad_bytes, red_ranks) / cluster.ici_bw

    # TP: Megatron pattern — 2 allreduces of the activation block per layer
    tp_comm_s = 0.0
    if s.tensor > 1:
        act_bytes = (tokens_per_chip * model.hidden_size * model.dtype_bytes)
        per_layer = 2.0 * _ring_allreduce_bytes(act_bytes, s.tensor) / cluster.ici_bw
        tp_comm_s = per_layer * model.num_layers / s.pipe

    # PP bubble stretches the step by (pp-1)/micro (GPipe/1F1B fill+drain)
    micro = num_micro or max(2 * s.pipe, 1)
    pp_bubble = (s.pipe - 1) / micro if s.pipe > 1 else 0.0

    # memory feasibility: state bytes over (tensor·sharding·pipe) + remat'd
    # activation floor (tokens_per_chip already carries the dp/context/
    # sharding batch split — do not divide again)
    state = BYTES_PER_PARAM * model.n_params / (s.tensor * s.sharding * s.pipe)
    act = (tokens_per_chip * model.hidden_size *
           model.dtype_bytes * model.num_layers / s.pipe / 4)  # remat floor
    mem = state + act
    feasible = mem <= 0.9 * cluster.hbm_bytes

    return CostBreakdown(compute_s, dp_comm_s, tp_comm_s, pp_bubble,
                         feasible, mem)


def _factorizations(n: int, axes: int):
    """All ordered (d0..d_{axes-1}) divisor tuples with prod == n."""
    if axes == 1:
        yield (n,)
        return
    for d in range(1, n + 1):
        if n % d == 0:
            for rest in _factorizations(n // d, axes - 1):
                yield (d,) + rest


def search(model: ModelDesc, cluster: ClusterDesc, global_batch: int = 32,
           max_candidates: int = 4096) -> Dict:
    """Cost-model-driven strategy search (the reference tuner's search loop
    over dist-attr candidates, collapsed to mesh-degree candidates): rank
    every feasible (dp, sharding, tensor, pipe) divisor factorization of
    the cluster by predicted step time."""
    best = None
    tried = 0
    for dp, shard, tp, pp in _factorizations(cluster.n_devices, 4):
        tried += 1
        if tried > max_candidates:
            break
        if tp > 1 and model.num_attention_heads % tp:
            continue
        if pp > 1 and model.num_layers % pp:
            continue
        if global_batch % max(dp * shard, 1):
            continue
        s = TunedStrategy(dp=dp, sharding=shard, tensor=tp, pipe=pp)
        cost = estimate_step_time(model, cluster, s, global_batch)
        if not cost.feasible:
            continue
        if best is None or cost.step_s < best["cost"].step_s:
            best = {"strategy": s, "cost": cost}
    if best is None:  # nothing fits — fall back to the rule-based answer
        from .tuner import tune

        s = tune(model, cluster)
        best = {"strategy": s,
                "cost": estimate_step_time(model, cluster, s, global_batch)}
    return best
