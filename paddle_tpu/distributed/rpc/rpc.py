"""paddle.distributed.rpc parity (ref python/paddle/distributed/rpc/rpc.py:73
init_rpc, :141 rpc_sync, :179 rpc_async, :270 shutdown, :299 get_worker_info).

TPU-native design: the reference runs RPC over brpc with a C++ agent
(paddle/fluid/distributed/rpc/).  On TPU pods the accelerator network (ICI)
is owned by XLA collectives, so RPC is a *host-side* control-plane facility —
a threaded TCP server per worker speaking length-prefixed pickle, with worker
discovery through the same KV store that the launch rendezvous uses
(launch/rendezvous.py, the TCPStore role).  Semantics match the reference:
named workers, sync/async calls of picklable Python functions, barriered
init/shutdown.
"""
from __future__ import annotations

import os
import pickle
import socket
import socketserver
import struct
import threading
import time
from collections import namedtuple
from concurrent.futures import ThreadPoolExecutor, Future
from typing import Dict, List, Optional

from ..launch.rendezvous import KVClient, KVServer

WorkerInfo = namedtuple("WorkerInfo", ["name", "rank", "ip", "port"])

_DEFAULT_RPC_TIMEOUT = 120.0


class _RpcState:
    def __init__(self):
        self.server: Optional["_RpcServer"] = None
        self.kv_server: Optional[KVServer] = None
        self.kv: Optional[KVClient] = None
        self.workers: Dict[str, WorkerInfo] = {}
        self.current: Optional[WorkerInfo] = None
        self.world_size: int = 0
        self.pool: Optional[ThreadPoolExecutor] = None


_STATE = _RpcState()


def _send_msg(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv_msg(sock: socket.socket):
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("rpc peer closed connection")
        hdr += chunk
    (n,) = struct.unpack("<Q", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("rpc peer closed connection")
        buf += chunk
    return pickle.loads(bytes(buf))


class _RpcHandler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            req = _recv_msg(self.request)
            fn, args, kwargs = req["fn"], req["args"], req["kwargs"]
            try:
                value = fn(*args, **kwargs)
                resp = {"ok": True, "value": value}
            except Exception as e:  # serialized back to the caller
                resp = {"ok": False, "exc": e}
            _send_msg(self.request, resp)
        except Exception:
            pass


class _RpcServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


def _gen_endpoint() -> str:
    ip = os.environ.get("POD_IP", "127.0.0.1")
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    return f"{ip}:{port}"


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None) -> None:
    """Start this worker's RPC agent and rendezvous with the others
    (ref rpc.py:73). rank 0 hosts the discovery KV store."""
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None else rank
    world_size = (int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
                  if world_size is None else world_size)
    master_endpoint = master_endpoint or os.environ.get(
        "PADDLE_MASTER_ENDPOINT", "127.0.0.1:8090")

    server_endpoint = os.environ.get("PADDLE_WORKER_ENDPOINT") or _gen_endpoint()
    ip, port = server_endpoint.rsplit(":", 1)

    srv = _RpcServer(("0.0.0.0", int(port)), _RpcHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    _STATE.server = srv

    if rank == 0:
        try:
            _STATE.kv_server = KVServer(int(master_endpoint.rsplit(":", 1)[1]))
        except OSError:
            _STATE.kv_server = None  # already hosted (single-process re-init)
    _STATE.kv = KVClient(master_endpoint)
    _STATE.kv.set(f"rpc/worker/{rank}", f"{name},{rank},{ip},{port}")

    while True:
        entries = _STATE.kv.list("rpc/worker/")
        if len(entries) >= world_size:
            break
        time.sleep(0.1)
    for v in entries.values():
        wname, wrank, wip, wport = v.split(",")
        _STATE.workers[wname] = WorkerInfo(wname, int(wrank), wip, int(wport))
    _STATE.current = _STATE.workers[name]
    _STATE.world_size = world_size
    _STATE.pool = ThreadPoolExecutor(max_workers=16)
    _barrier(rank, world_size)


def _barrier(rank: int, world_size: int, tag: str = "init") -> None:
    n = _STATE.kv.add(f"rpc/barrier/{tag}", 1)
    target = world_size * (n // world_size + (1 if n % world_size else 0))
    while int(_STATE.kv.get(f"rpc/barrier/{tag}") or 0) < target:
        time.sleep(0.05)


def _invoke(to: str, fn, args, kwargs, timeout: float):
    if to not in _STATE.workers:
        raise ValueError(f"unknown rpc worker {to!r}; known: "
                         f"{sorted(_STATE.workers)}")
    info = _STATE.workers[to]
    with socket.create_connection((info.ip, info.port), timeout=timeout) as s:
        if timeout and timeout > 0:
            s.settimeout(timeout)
        _send_msg(s, {"fn": fn, "args": tuple(args or ()),
                      "kwargs": dict(kwargs or {})})
        resp = _recv_msg(s)
    if not resp["ok"]:
        raise resp["exc"]
    return resp["value"]


class FutureWrapper:
    """Matches the reference's future: .wait() returns the result."""

    def __init__(self, fut: Future):
        self._fut = fut

    def wait(self):
        return self._fut.result()


def rpc_sync(to: str, fn, args=None, kwargs=None,
             timeout: float = _DEFAULT_RPC_TIMEOUT):
    """Run ``fn(*args, **kwargs)`` on worker ``to``; block for the result
    (ref rpc.py:141)."""
    return _invoke(to, fn, args, kwargs, timeout)


def rpc_async(to: str, fn, args=None, kwargs=None,
              timeout: float = _DEFAULT_RPC_TIMEOUT) -> FutureWrapper:
    """Async variant (ref rpc.py:179); returns a future with .wait()."""
    return FutureWrapper(_STATE.pool.submit(_invoke, to, fn, args, kwargs,
                                            timeout))


def shutdown() -> None:
    """Barrier then stop the agent (ref rpc.py:270)."""
    if _STATE.current is None:
        return
    _barrier(_STATE.current.rank, _STATE.world_size, tag="shutdown")
    if _STATE.pool:
        _STATE.pool.shutdown(wait=True)
    if _STATE.server:
        _STATE.server.shutdown()
        _STATE.server.server_close()
    if _STATE.kv_server:
        _STATE.kv_server.stop()
    _STATE.__init__()


def get_worker_info(name: str) -> WorkerInfo:
    return _STATE.workers[name]


def get_all_worker_infos() -> List[WorkerInfo]:
    return sorted(_STATE.workers.values(), key=lambda w: w.rank)


def get_current_worker_info() -> WorkerInfo:
    return _STATE.current
