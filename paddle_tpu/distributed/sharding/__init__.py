"""paddle.distributed.sharding parity — ``group_sharded_parallel`` /
``save_group_sharded_model``.

Ref: python/paddle/distributed/sharding/group_sharded.py (entry),
meta_parallel/sharding/group_sharded_stage2.py:46 (ZeRO-2: grads + opt state
sharded, comm overlap), group_sharded_stage3.py:60 (ZeRO-3: param sharding
with forward allgather + release), group_sharded_storage.py (flat buffers).

TPU-native ZeRO: one JAX process addresses every chip, so "shard across
ranks" becomes laying each array out over a ``sharding`` mesh axis with
``NamedSharding``. Computation follows data: eager ops and jitted steps over
these arrays run SPMD, with GSPMD inserting the stage-3 allgather-on-use and
reduce-scatter-on-grad that the reference hand-codes as NCCL bucket hooks
(stage3 ``_forward_pre_hook`` allgather / ``_release_param``). No flat-buffer
bookkeeping is needed — XLA owns layout and liveness.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ...framework.io_state import save as _save

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]

_AXIS = "sharding"


def _resolve_mesh(group=None) -> Mesh:
    """Mesh carrying the sharding axis: an explicit group's mesh, the ambient
    parallel mesh if it names one, else a fresh 1-D mesh over all devices."""
    mesh = getattr(group, "mesh", None)
    if mesh is not None:
        return mesh
    from ...parallel.api import current_mesh

    mesh = current_mesh()
    if mesh is not None and mesh.shape.get(_AXIS, 1) > 1:
        return mesh
    devs = np.array(jax.devices())
    return Mesh(devs, (_AXIS,))


def _axis_size(mesh: Mesh) -> int:
    return mesh.shape[_AXIS] if _AXIS in mesh.axis_names else 1


def _spec_for(shape, mesh: Mesh) -> P:
    """Canonical ZeRO layout (shared with ParallelEngine fsdp). min_size=1:
    the reference shards every param regardless of size
    (group_sharded_stage3.py segment split). Uneven splits are disallowed —
    this layout is applied with eager ``jax.device_put``."""
    from ...parallel.api import auto_shard_spec

    return auto_shard_spec(shape, _axis_size(mesh), axis=_AXIS, min_size=1,
                           allow_uneven=False)


def _put(arr, mesh: Mesh, spec: P):
    return jax.device_put(arr, NamedSharding(mesh, spec))


def _shard_params(model, mesh: Mesh):
    for p in model.parameters():
        spec = _spec_for(p.shape, mesh)
        p._value = _put(p._value, mesh, spec)


def _host_device():
    try:
        return jax.devices("cpu")[0]
    except RuntimeError:
        return None


def _wrap_optimizer_slots(optimizer, mesh: Mesh):
    """Created slots are laid out sharded (all ZeRO stages shard opt state —
    ref dygraph_sharding_optimizer.py:29 / stage2.py:46). Slots are always
    created device-side (creation happens lazily inside ``step()``, where
    they immediately meet device grads); host offload between steps is the
    step wrapper's job."""
    inner = optimizer._create_slots

    def _layout(v):
        return _put(v, mesh, _spec_for(v.shape, mesh))

    def sharded_create(p):
        slots = inner(p)
        return {k: _layout(v) for k, v in slots.items()}

    optimizer._create_slots = sharded_create
    # re-layout any slots that already exist
    for slots in optimizer._accumulators.values():
        for k, v in list(slots.items()):
            if k.startswith("__"):
                continue
            slots[k] = _layout(v)


class GroupShardedModel:
    """Thin wrapper returned by :func:`group_sharded_parallel`; forwards to the
    inner Layer (ref stage2/stage3 are nn.Layer wrappers with hooks; here the
    hooks are GSPMD layouts, so only the facade remains)."""

    def __init__(self, layer, level: str, mesh: Mesh):
        self._layers = layer
        self._level = level
        self._mesh = mesh

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def get_all_parameters(self):
        """Ref stage3 ``get_all_parameters`` — materialise full (replicated)
        values."""
        for p in self._layers.parameters():
            p._value = _put(p._value, self._mesh, P())
        return list(self._layers.parameters())

    def __getattr__(self, item):
        if "_layers" not in self.__dict__:  # mid-unpickle/deepcopy: no recursion
            raise AttributeError(item)
        return getattr(self._layers, item)


class _ShardedStepOptimizer:
    """Optimizer facade: before the inner step, grads are re-laid-out to the
    slot sharding so the update math runs scattered (the reduce-scatter of
    ref stage2 ``_grad_storage`` buckets, done by layout instead of NCCL)."""

    def __init__(self, optimizer, mesh: Mesh, params, offload: bool = False,
                 shard_grads: bool = True):
        self._inner_opt = optimizer
        self._mesh = mesh
        self._params = list(params)
        self._offload = offload
        self._shard_grads = shard_grads

    def _migrate_slots(self, to_host: bool):
        host = _host_device()
        for slots in self._inner_opt._accumulators.values():
            for k, v in list(slots.items()):
                if k.startswith("__"):
                    continue
                if to_host and host is not None:
                    slots[k] = jax.device_put(v, host)
                else:
                    slots[k] = _put(v, self._mesh, _spec_for(v.shape, self._mesh))

    def step(self):
        if self._shard_grads:
            for p in self._params:
                g = p._grad
                if g is not None:
                    spec = _spec_for(g.shape, self._mesh)
                    g._value = _put(g._value, self._mesh, spec)
        if self._offload:
            self._migrate_slots(to_host=False)  # h2d for the update
        self._inner_opt.step()
        if self._offload:
            self._migrate_slots(to_host=True)  # updated state back to host RAM

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()  # through the wrapper, so relayout/offload migration run
        return None, None

    def clear_grad(self, *a, **k):
        return self._inner_opt.clear_grad(*a, **k)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    def __getattr__(self, item):
        if "_inner_opt" not in self.__dict__:  # mid-unpickle/deepcopy: no recursion
            raise AttributeError(item)
        return getattr(self._inner_opt, item)


def group_sharded_parallel(model, optimizer, level: str = "os_g", scaler=None,
                           group=None, offload: bool = False, sync_buffers: bool = False,
                           buffer_max_size: int = 2 ** 23, segment_size: int = 2 ** 20,
                           sync_comm: bool = False, dp_group=None,
                           exclude_layer=None):
    """Shard model/optimizer state over the ``sharding`` mesh axis.

    ``level``: ``os`` (ZeRO-1, opt state), ``os_g`` (ZeRO-2, + grads),
    ``p_g_os`` (ZeRO-3, + params). Ref group_sharded.py signature; ``offload``
    maps to host memory via jax device_put to CPU when requested.
    """
    if level not in ("os", "os_g", "p_g_os"):
        raise ValueError(f"level must be os | os_g | p_g_os, got {level!r}")
    mesh = _resolve_mesh(group)
    if level == "p_g_os":
        _shard_params(model, mesh)
    _wrap_optimizer_slots(optimizer, mesh)
    params = list(model.parameters())
    shard_grads = level in ("os_g", "p_g_os")  # ZeRO-1 keeps grad layout as-is
    opt = (_ShardedStepOptimizer(optimizer, mesh, params, offload=offload,
                                 shard_grads=shard_grads)
           if (shard_grads or offload) else optimizer)
    wrapped = GroupShardedModel(model, level, mesh)
    return wrapped, opt, scaler


def save_group_sharded_model(model, output: str, optimizer=None) -> None:
    """Gather full state to host and save (ref group_sharded.py
    ``save_group_sharded_model`` — stage3 gathers before save)."""
    import os

    layer = getattr(model, "_layers", model)
    os.makedirs(output, exist_ok=True)
    # io_state._pack gathers (np.asarray) and keeps Parameter metadata
    _save(layer.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        _save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
