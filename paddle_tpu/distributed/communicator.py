"""paddle.distributed.communicator (ref communicator.py:40 Communicator —
the async/geo/sync PS gradient-communication daemon; :248 LargeScaleKV).

The brpc parameter server is a documented non-goal (SURVEY §7): TPU training
communicates through XLA collectives inside compiled steps, so there is no
background gradient-push daemon to manage. The class is kept as an explicit
API with lifecycle semantics (init/start/stop idempotency checks match the
reference) so PS-era scripts fail loudly at `init_with_ctx` rather than at
import.
"""
from __future__ import annotations

__all__ = ["Communicator", "LargeScaleKV"]

_NON_GOAL = (
    "the brpc parameter-server pipeline is not part of the TPU build "
    "(SURVEY §7 non-goals): gradient exchange happens as XLA collectives "
    "inside the jitted train step. Use collective mode "
    "(paddle.distributed.fleet with is_collective=True)."
)


class Communicator:
    """ref communicator.py:40."""

    def __init__(self, mode=None, kwargs=None, envs=None):
        self.mode = mode
        self._initialized = False
        self._running = False

    def init_with_ctx(self, *args, **kwargs):
        raise NotImplementedError(_NON_GOAL)

    def start(self):
        if not self._initialized:
            raise RuntimeError(
                "Communicator was not initialized (init_with_ctx); " + _NON_GOAL)

    def stop(self):
        self._running = False

    def is_running(self) -> bool:
        return self._running


class LargeScaleKV:
    """ref communicator.py:248 — host-RAM KV for huge sparse tables; a plain
    dict here (save/load parity for scripts that snapshot it)."""

    def __init__(self):
        self._store = {}

    def save(self, varname: str, path: str):
        import pickle

        with open(path, "wb") as f:
            pickle.dump(self._store.get(varname), f)

    def load(self, varname: str, path: str):
        import pickle

        with open(path, "rb") as f:
            self._store[varname] = pickle.load(f)

    def size(self, varname: str) -> int:
        v = self._store.get(varname)
        return 0 if v is None else len(v)
