"""Process topology → jax Mesh.

Ref: python/paddle/distributed/fleet/base/topology.py:53 CommunicateTopology
(dims [dp, pp, sharding, mp]) and :139 HybridCommunicateGroup (per-axis
process groups). TPU-native: the topology IS a jax.sharding.Mesh with named
axes; "communication groups" are mesh axis names — XLA lowers collectives
onto ICI rings per axis, so there is no per-group NCCL communicator to build.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis order: pipe outermost (cross-host OK: only p2p crosses it),
# then data/sharding (gradient reduction rides fast ICI within host when
# possible), then tensor innermost (latency-critical, needs fastest links),
# then context/expert as optional extra axes.
AXIS_ORDER = ("pipe", "data", "sharding", "sep", "expert", "tensor", "context")


def build_mesh(dp: int = 1, mp: int = 1, pp: int = 1, sharding: int = 1, sep: int = 1,
               ep: int = 1, cp: int = 1, devices: Optional[Sequence] = None) -> Mesh:
    """CommunicateTopology(dims=[dp,pp,sharding,mp]) → Mesh."""
    devices = list(devices) if devices is not None else jax.devices()
    sizes = {"pipe": pp, "data": dp, "sharding": sharding, "sep": sep, "expert": ep,
             "tensor": mp, "context": cp}
    total = int(np.prod(list(sizes.values())))
    if total != len(devices):
        raise ValueError(
            f"topology {sizes} needs {total} devices, have {len(devices)}")
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, AXIS_ORDER)


class CommunicateTopology:
    """Ref topology.py:53 — coordinate math over hybrid dims."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = list(itertools.product(*[range(d) for d in self._dims]))
        self._coord2rank = {c: i for i, c in enumerate(self.coordinate)}
        self._rank2coord = {i: c for i, c in enumerate(self.coordinate)}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs) -> int:
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank: int):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        axis = self._parallel_names.index(axis_name)
        return sorted(r for c, r in self._coord2rank.items() if c[axis] == index)

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        axis = self._parallel_names.index(axis_name)
        other_dims = [d for i, d in enumerate(self._dims) if i != axis]
        groups = []
        for other in itertools.product(*[range(d) for d in other_dims]):
            ranks = []
            for k in range(self._dims[axis]):
                coord = list(other)
                coord.insert(axis, k)
                ranks.append(self._coord2rank[tuple(coord)])
            groups.append(ranks)
        return groups

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = list(self.get_coord(global_rank))
        for k, v in kwargs.items():
            coord[self._parallel_names.index(k)] = v
        return self._coord2rank[tuple(coord)]


class HybridCommunicateGroup:
    """Ref topology.py:139 — exposes per-axis rank/degree queries; on TPU the
    "groups" are mesh axes, so this only carries coordinate bookkeeping."""

    def __init__(self, topology: CommunicateTopology, global_rank: int = 0):
        self._topo = topology
        self.global_rank = global_rank
        names = topology.get_hybrid_group_names()

        def dim(n):
            return topology.get_dim(n) if n in names else 1

        self._dp_degree = dim("data")
        self._mp_degree = dim("model")
        self._pp_degree = dim("pipe")
        self._sharding_degree = dim("sharding")
        self._sep_degree = dim("sep")
        coord = topology.get_coord(global_rank)
        self._coord = dict(zip(names, coord))

    # ranks within each parallel dimension
    def get_data_parallel_rank(self):
        return self._coord.get("data", 0)

    def get_model_parallel_rank(self):
        return self._coord.get("model", 0)

    def get_stage_id(self):
        return self._coord.get("pipe", 0)

    def get_sharding_parallel_rank(self):
        return self._coord.get("sharding", 0)

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def topology(self):
        return self._topo

    # group objects are axis-name handles on TPU
    def get_data_parallel_group(self):
        from .collective import Group

        return Group(axis="data", nranks=self._dp_degree,
                     rank=self.get_data_parallel_rank())

    def get_model_parallel_group(self):
        from .collective import Group

        return Group(axis="tensor", nranks=self._mp_degree,
                     rank=self.get_model_parallel_rank())

    def get_pipe_parallel_group(self):
        from .collective import Group

        return Group(axis="pipe", nranks=self._pp_degree, rank=self.get_stage_id())

    def get_sharding_parallel_group(self):
        from .collective import Group

        return Group(axis="sharding", nranks=self._sharding_degree,
                     rank=self.get_sharding_parallel_rank())

    def get_check_parallel_group(self, *a, **k):
        from .collective import Group

        return Group(axis=None, nranks=1, rank=0)

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank, pipe=stage_id, **kwargs)
