"""paddle.distributed.parallel_with_gloo (ref parallel_with_gloo.py:42
gloo_init_parallel_env / :139 gloo_barrier / :197 gloo_release — CPU-only
collective bootstrap used by parameter-server roles).

TPU-native: host-side CPU coordination goes through the same KV store the
launch rendezvous uses (there is no gloo ring; XLA owns the device
collectives). The barrier is the KV counter barrier — semantically the gloo
barrier the reference builds over its HTTP store.
"""
from __future__ import annotations

import time
from typing import Optional

from .launch.rendezvous import KVClient, KVServer

__all__ = ["gloo_init_parallel_env", "gloo_barrier", "gloo_release"]

_STATE = {"rank": 0, "size": 1, "kv": None, "server": None, "gen": 0}


def gloo_init_parallel_env(rank_id: int, rank_num: int,
                           server_endpoint: str) -> None:
    """ref :42 — rank 0 hosts the store; everyone registers and waits."""
    if rank_id == 0:
        try:
            _STATE["server"] = KVServer(int(server_endpoint.rsplit(":", 1)[1]))
        except OSError:
            _STATE["server"] = None
    kv = KVClient(server_endpoint)
    kv.set(f"gloo/worker/{rank_id}", "1")
    while len(kv.list("gloo/worker/")) < rank_num:
        time.sleep(0.05)
    _STATE.update(rank=rank_id, size=rank_num, kv=kv)


def gloo_barrier() -> None:
    """ref :139"""
    kv: Optional[KVClient] = _STATE["kv"]
    if kv is None:
        raise RuntimeError("call gloo_init_parallel_env first")
    _STATE["gen"] += 1
    key = f"gloo/barrier/{_STATE['gen']}"
    kv.add(key, 1)
    while int(kv.get(key) or 0) < _STATE["size"]:
        time.sleep(0.02)


def gloo_release() -> None:
    """ref :197"""
    if _STATE["server"] is not None:
        _STATE["server"].stop()
    _STATE.update(rank=0, size=1, kv=None, server=None, gen=0)
