"""Distributed environment (ref: python/paddle/distributed/parallel.py:108
init_parallel_env — TCPStore rendezvous at :279 + NCCL comm init).

TPU-native: jax.distributed.initialize() replaces TCPStore+NCCL bootstrap
(the TPU runtime does its own rendezvous over the coordinator address), and
process identity comes from jax.process_index(). Within a process all local
devices are visible, so "world" here = processes × local devices when
counting chips (the reference counts 1 GPU per process).
"""
from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


class ParallelEnv:
    """Ref python/paddle/fluid/dygraph/parallel.py ParallelEnv."""

    @property
    def rank(self) -> int:
        # env first, LAZILY: jax.process_index() initializes the XLA backend,
        # which must not happen before jax.distributed.initialize on a
        # launched multi-process job (the env var is set by the launch CLI)
        v = os.environ.get("PADDLE_TRAINER_ID")
        return int(v) if v is not None else jax.process_index()

    @property
    def world_size(self) -> int:
        v = os.environ.get("PADDLE_TRAINERS_NUM")
        return int(v) if v is not None else jax.process_count()

    @property
    def local_rank(self) -> int:
        return int(os.environ.get("PADDLE_LOCAL_RANK", 0))

    @property
    def dev_id(self) -> int:
        return self.local_rank

    @property
    def device_type(self) -> str:
        try:
            return jax.devices()[0].platform
        except RuntimeError:
            return "cpu"

    @property
    def current_endpoint(self) -> str:
        eps = self.trainer_endpoints
        r = self.rank
        return eps[r] if r < len(eps) else ""

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []

    @property
    def nrings(self) -> int:
        return 1


def init_parallel_env(coordinator_address: Optional[str] = None,
                      num_processes: Optional[int] = None,
                      process_id: Optional[int] = None):
    """paddle.distributed.init_parallel_env parity.

    Multi-host: wires jax.distributed.initialize from either explicit args or
    PADDLE_TRAINER_ENDPOINTS/PADDLE_TRAINER_ID env (as set by the launch CLI).
    Single-host: no-op (all local chips already visible).
    """
    global _initialized
    if _initialized:
        return ParallelEnv()
    env = ParallelEnv()
    eps = env.trainer_endpoints
    n = num_processes
    if n is None:
        # PADDLE_TRAINERS_NUM = nnodes * nproc_per_node; the endpoint list
        # is per-NODE, so len(eps) undercounts --nproc_per_node > 1 jobs
        # (every local process shares node 0's coordinator endpoint)
        wn = os.environ.get("PADDLE_TRAINERS_NUM")
        n = int(wn) if wn is not None else (len(eps) or None)
    if coordinator_address is None and eps:
        coordinator_address = eps[0]
    if os.environ.get("PADDLE_HEARTBEAT_FILE"):
        # launched with --elastic_timeout: start beating BEFORE the
        # coordinator rendezvous so a rank wedged in initialize is still
        # covered by the watcher (fleet/elastic.py)
        from .fleet.elastic import start_file_heartbeat

        start_file_heartbeat()
    if coordinator_address and (n or 1) > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=n,
            process_id=process_id if process_id is not None else env.rank,
        )
    _initialized = True
    return ParallelEnv()


def get_rank(group=None) -> int:
    if group is not None:
        return group.rank
    return ParallelEnv().rank


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    return ParallelEnv().world_size


def is_initialized() -> bool:
    return _initialized


def device_count() -> int:
    try:
        return jax.device_count()
    except RuntimeError:
        return 1


def local_device_count() -> int:
    try:
        return jax.local_device_count()
    except RuntimeError:
        return 1
