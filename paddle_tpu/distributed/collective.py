"""Eager collectives API (ref: python/paddle/distributed/communication/*.py,
ProcessGroup C++ runtime paddle/fluid/distributed/collective/process_group.h:53).

TPU-native design (SURVEY §5.8): there is ONE backend — XLA collectives.
Inside pjit/shard_map programs, collectives are psum/all_gather/ppermute and
never touch this module. This eager API exists for host-driven parity
(paddle.distributed.all_reduce(t) style code): it executes the collective
over a named axis of the ACTIVE GLOBAL MESH via shard_map when the tensor is
sharded there, and degrades to the mathematical identity (world=1) otherwise.
Cross-process eager collectives go through jax's global-array path the same
way.
"""
from __future__ import annotations

import dataclasses
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, to_array

# ---------------------------------------------------------------------------
# Group: on TPU a "process group" is a mesh-axis handle.
# ---------------------------------------------------------------------------

_global_mesh: Optional[jax.sharding.Mesh] = None
_groups: dict = {}
_next_group_id = 0


def set_global_mesh(mesh) -> None:
    global _global_mesh
    _global_mesh = mesh


def get_global_mesh():
    return _global_mesh


@dataclasses.dataclass
class Group:
    """Ref process_group.h:53 ProcessGroup — reduced to (axis, rank, nranks).

    axis=None means the trivial single-member group.
    """

    axis: Optional[str] = None
    nranks: int = 1
    rank: int = 0
    id: int = 0
    ranks: Optional[List[int]] = None

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        if self.ranks is None:
            return rank
        return self.ranks.index(rank) if rank in self.ranks else -1

    @property
    def process_group(self):
        return self


ProcessGroup = Group  # reference name (ref process_group.h:53)


class _Task:
    """Async completion handle (ref process_group.h Task :55-88). XLA calls
    are async by default; wait() blocks on the result buffer."""

    def __init__(self, result=None):
        self._result = result

    def wait(self):
        if self._result is not None:
            jax.block_until_ready(self._result)
        return True

    def is_completed(self):
        return True

    def synchronize(self):
        self.wait()


def new_group(ranks=None, backend=None, timeout=None, axis=None):
    """Ref collective.py:185 new_group. On TPU, groups over explicit rank
    lists are only used by the launch/bootstrap layer; compute-path groups
    are mesh axes."""
    global _next_group_id
    _next_group_id += 1
    from .env import get_rank

    nranks = len(ranks) if ranks else 1
    r = get_rank()
    grp_rank = ranks.index(r) if ranks and r in ranks else 0
    g = Group(axis=axis, nranks=nranks, rank=grp_rank, id=_next_group_id, ranks=ranks)
    _groups[_next_group_id] = g
    return g


def get_group(gid: int) -> Optional[Group]:
    return _groups.get(gid)


def _axis_size(axis: str) -> int:
    if _global_mesh is None or axis is None:
        return 1
    return int(_global_mesh.shape[axis]) if axis in _global_mesh.shape else 1


# ---------------------------------------------------------------------------
# ReduceOp
# ---------------------------------------------------------------------------


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


def _reduce_fn(op):
    return {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
            ReduceOp.MIN: jax.lax.pmin,
            ReduceOp.PROD: lambda x, n: jnp.exp(jax.lax.psum(jnp.log(x), n)),
            ReduceOp.AVG: jax.lax.pmean}[op]


def _run_on_axis(x, axis: str, per_shard_fn, out_specs_fn=None):
    """Execute per-shard collective body via shard_map over `axis` of the
    global mesh; x must be sharded over that axis (or replicated)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = _global_mesh
    in_spec = _infer_spec(x, axis)
    out_spec = out_specs_fn(in_spec) if out_specs_fn else in_spec
    fn = shard_map(per_shard_fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec,
                   check_rep=False)
    return fn(x)


def _infer_spec(x, axis):
    from jax.sharding import PartitionSpec as P

    try:
        sh = x.sharding
        if hasattr(sh, "spec"):
            return sh.spec
    except Exception:
        pass
    return P()  # replicated


# ---------------------------------------------------------------------------
# Public collectives (eager host API)
# ---------------------------------------------------------------------------


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = group.axis if group is not None else "data"
    n = _axis_size(axis)
    if n <= 1:
        return _Task(tensor.value if isinstance(tensor, Tensor) else tensor)
    val = to_array(tensor)
    red = _reduce_fn(op)
    out = _run_on_axis(val, axis, lambda v: red(v, axis))
    if isinstance(tensor, Tensor):
        tensor._value = out
    return _Task(out)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    axis = group.axis if group is not None else "data"
    n = _axis_size(axis)
    val = to_array(tensor)
    if n <= 1:
        tensor_list.append(Tensor(val))
        return _Task(val)
    out = _run_on_axis(
        val, axis, lambda v: jax.lax.all_gather(v, axis),
        out_specs_fn=lambda s: s)
    # out has leading axis n per shard; split into list
    for i in range(n):
        tensor_list.append(Tensor(out[i]))
    return _Task(out)


def all_gather_object(object_list, obj, group=None):
    object_list.append(obj)
    return _Task()


def reduce(tensor, dst, op=ReduceOp.SUM, group=None, sync_op=True):
    # On TPU all-reduce then discard is the same cost pattern under XLA.
    return all_reduce(tensor, op, group, sync_op)


def broadcast(tensor, src, group=None, sync_op=True):
    # Replicated arrays are already consistent; cross-process broadcast uses
    # process 0's value via jax multihost utils when world>1.
    from .env import get_world_size

    if get_world_size() > 1:
        try:
            from jax.experimental import multihost_utils

            val = multihost_utils.broadcast_one_to_all(to_array(tensor))
            if isinstance(tensor, Tensor):
                tensor._value = val
            return _Task(val)
        except Exception:
            pass
    return _Task(to_array(tensor))


def broadcast_object_list(object_list, src=0, group=None):
    return _Task()


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list:
        from .env import get_rank

        idx = group.rank if group is not None else 0
        val = to_array(tensor_list[idx])
        if isinstance(tensor, Tensor):
            tensor._value = val
        return _Task(val)
    return _Task(to_array(tensor))


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    axis = group.axis if group is not None else "data"
    n = _axis_size(axis)
    if n <= 1:
        red = to_array(tensor_list[0])
        for t in tensor_list[1:]:
            red = red + to_array(t)
        if isinstance(tensor, Tensor):
            tensor._value = red
        return _Task(red)
    stacked = jnp.stack([to_array(t) for t in tensor_list])
    out = _run_on_axis(
        stacked, axis,
        lambda v: jax.lax.psum_scatter(v, axis, scatter_dimension=0, tiled=False))
    if isinstance(tensor, Tensor):
        tensor._value = out
    return _Task(out)


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    axis = group.axis if group is not None else "data"
    n = _axis_size(axis)
    if n <= 1:
        out_tensor_list.extend(Tensor(to_array(t)) for t in in_tensor_list)
        return _Task()
    stacked = jnp.stack([to_array(t) for t in in_tensor_list])
    out = _run_on_axis(
        stacked, axis,
        lambda v: jax.lax.all_to_all(v, axis, split_axis=0, concat_axis=0, tiled=False))
    for i in range(out.shape[0]):
        out_tensor_list.append(Tensor(out[i]))
    return _Task(out)


alltoall = all_to_all


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "Host-driven p2p send/recv is not a TPU primitive; pipeline-parallel "
        "communication uses ppermute inside compiled programs "
        "(paddle_tpu.distributed.fleet.meta_parallel.pipeline).")


def recv(tensor, src=0, group=None, sync_op=True):
    raise NotImplementedError(
        "Host-driven p2p send/recv is not a TPU primitive; see pipeline parallel.")


isend = send
irecv = recv


def barrier(group=None):
    try:
        from jax.experimental import multihost_utils

        from .env import get_world_size

        if get_world_size() > 1:
            multihost_utils.sync_global_devices("paddle_tpu_barrier")
    except Exception:
        pass
    return _Task()


def wait(tensor, group=None, use_calc_stream=True):
    jax.block_until_ready(to_array(tensor))


def destroy_process_group(group=None):
    global _groups
    if group is None:
        _groups = {}
    else:
        _groups.pop(group.id, None)


def get_backend(group=None) -> str:
    return "xla"


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """Single-tensor all_to_all (ref communication/all_to_all.py
    alltoall_single): the first dim is split across the group instead of
    passing explicit tensor lists."""
    axis = group.axis if group is not None else "data"
    n = _axis_size(axis)
    v = to_array(in_tensor)
    for sizes in (in_split_sizes, out_split_sizes):
        if sizes is not None and len(set(sizes)) > 1:
            raise NotImplementedError(
                "alltoall_single: unequal split sizes are not supported by "
                "the XLA all_to_all lowering — pad to equal splits")
    # the collective is meaningful when the input is sharded over the group
    # axis (global chunk-ownership transpose); an eagerly replicated array
    # is this process's own tensor — exchanged with itself (identity), the
    # same world-per-process view the other eager collectives take
    spec = tuple(getattr(getattr(v, "sharding", None), "spec", ()) or ())
    if n > 1 and axis not in spec:
        n = 1
    if n <= 1:
        if out_tensor is not None and isinstance(out_tensor, Tensor):
            out_tensor._value = v
            return _Task(v)
        return Tensor(v)
    out = _run_on_axis(
        v, axis,
        lambda x: jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                     tiled=True))
    if out_tensor is not None and isinstance(out_tensor, Tensor):
        out_tensor._value = out
        return _Task(out)
    return Tensor(out)


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """Scatter picklable python objects (ref communication/scatter.py
    scatter_object_list): rank i receives in_object_list[i] from src."""
    idx = group.rank if group is not None else 0
    out_object_list.clear()
    if in_object_list:
        out_object_list.append(in_object_list[idx])
    return None


def is_available() -> bool:
    """Whether the distributed package is usable (ref parallel.py
    is_available) — always True here: the XLA-collectives backend is
    compiled in."""
    return True


class ParallelMode:
    """Parallelism kinds (ref fleet/base/topology.py:28)."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Model-parallel linear/embedding over the tensor axis (ref
    fleet/layers/mpu/mp_ops.py split:653): builds the corresponding
    parallel layer (weights GSPMD-sharded over "tensor") and returns its
    output on ``x``.  axis=1 on a linear splits the out-features
    (column-parallel); axis=0 splits in-features (row-parallel)."""
    from .fleet.meta_parallel.mp_layers import (ColumnParallelLinear,
                                               RowParallelLinear,
                                               VocabParallelEmbedding)

    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr)
        return layer(x)
    if operation == "linear":
        if axis == 1:
            layer = ColumnParallelLinear(size[0], size[1],
                                         weight_attr=weight_attr,
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out)
        else:
            layer = RowParallelLinear(size[0], size[1],
                                      weight_attr=weight_attr,
                                      has_bias=bias_attr is not False,
                                      input_is_parallel=False)
        return layer(x)
    raise ValueError(f"unknown operation {operation!r} (linear|embedding)")
