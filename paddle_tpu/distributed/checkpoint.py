"""Sharded, async checkpointing (ref: auto_parallel/dist_saver.py +
converter.py reshard-on-load; auto_checkpoint.py periodic snapshots).

TPU-native: orbax-backed. Arrays are saved with their shardings; on load,
orbax reshards to the target sharding (= converter.py capability natively).

Crash-safety primitives shared with :mod:`.train_checkpoint`:

- :func:`write_manifest` / :func:`verify_manifest` — a per-file CRC32 +
  size manifest (``MANIFEST.json``) over a checkpoint directory, written
  last so its presence certifies a complete write; verification rereads
  every file so on-disk bit rot (or an injected ``ckpt_read`` fault) is
  detected before any state is trusted.
- :func:`replace_dir` — atomic write-then-rename commit: snapshots are
  staged under a dot-prefixed temp dir in the same parent (same
  filesystem, so the final ``os.replace`` is atomic) and only renamed
  into place once the manifest is down. A kill at any point leaves
  either the previous generation or an ignorable ``.tmp-*`` husk —
  never a torn directory that looks like a checkpoint.

``AutoCheckpoint`` routes its periodic snapshots through this commit
path, and ``latest()`` only returns manifest-valid generations.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..framework.core import Tensor

MANIFEST_NAME = "MANIFEST.json"
_TMP_PREFIX = ".tmp-"


def _to_arrays(tree):
    return jax.tree_util.tree_map(
        lambda x: x.value if isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def tree_path_key(path) -> str:
    """Canonical string for a jax tree path: dict keys / sequence indices
    / attr names joined with ``/`` (``("model", "weight")`` →
    ``"model/weight"``). This is the key :func:`load_state_dict` expects
    in its ``shardings`` dict — stable across tree transforms, unlike the
    ``id()``-keyed scheme it replaces (leaf identity changes under any
    ``tree_map``, silently dropping every sharding)."""
    parts = []
    for p in path:
        if isinstance(p, jax.tree_util.DictKey):
            parts.append(str(p.key))
        elif isinstance(p, jax.tree_util.SequenceKey):
            parts.append(str(p.idx))
        elif isinstance(p, jax.tree_util.GetAttrKey):
            parts.append(str(p.name))
        else:  # pragma: no cover - exotic key types
            parts.append(str(p))
    return "/".join(parts)


def save_state_dict(state_dict: Dict[str, Any], path: str, async_save: bool = False):
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    arrays = _to_arrays(state_dict)
    # orbax wants a pytree of arrays; numpy-ify scalars
    arrays = jax.tree_util.tree_map(
        lambda x: np.asarray(x) if not isinstance(x, (jax.Array, np.ndarray)) else x, arrays)
    ckptr.save(path, arrays, force=True)
    if not async_save:
        ckptr.wait_until_finished()
    return ckptr


def load_state_dict(path: str, target: Optional[Dict[str, Any]] = None,
                    shardings: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Restore a state tree; with ``target``, orbax reshards every array
    onto the requested sharding on load (GSPMD reshard-on-load).

    ``shardings`` maps :func:`tree_path_key` strings of the *target*
    tree (e.g. ``"model/weight"``, or ``"weight"`` for a flat dict) to
    ``jax.sharding.Sharding`` objects; leaves without an entry load
    unsharded."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if target is not None:
        def abstract_leaf(tree_path, x):
            if not isinstance(x, (Tensor, jax.Array, np.ndarray)):
                return x
            sh = shardings.get(tree_path_key(tree_path)) if shardings else None
            return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype, sharding=sh)

        abstract = jax.tree_util.tree_map_with_path(
            abstract_leaf, _to_arrays(target))
        restored = ckptr.restore(path, abstract)
    else:
        restored = ckptr.restore(path)
    return jax.tree_util.tree_map(lambda x: Tensor(x) if isinstance(
        x, (jax.Array, np.ndarray)) else x, restored)


# --------------------------------------------------------------------------- #
# Integrity manifest + atomic directory commit
# --------------------------------------------------------------------------- #


def _iter_files(root: str):
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in sorted(filenames):
            full = os.path.join(dirpath, fn)
            rel = os.path.relpath(full, root)
            if rel == MANIFEST_NAME or not os.path.isfile(full):
                continue
            yield full, rel.replace(os.sep, "/")


def _file_crc32(path: str) -> Tuple[int, int]:
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            size += len(chunk)
    return crc & 0xFFFFFFFF, size


def write_manifest(path: str, step: Optional[int] = None,
                   fingerprint: Optional[str] = None,
                   extra: Optional[dict] = None) -> dict:
    """CRC32+size every regular file under ``path`` into
    ``MANIFEST.json`` (itself written tmp+rename). Call LAST: the
    manifest certifies the directory is complete and untampered."""
    files = {rel: {"crc32": crc, "size": size}
             for full, rel in _iter_files(path)
             for crc, size in [_file_crc32(full)]}
    manifest = {"format": 1, "step": step, "fingerprint": fingerprint,
                "files": files, **({"extra": extra} if extra else {})}
    tmp = os.path.join(path, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=0, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(path, MANIFEST_NAME))
    return manifest


def read_manifest(path: str) -> Optional[dict]:
    mf = os.path.join(path, MANIFEST_NAME)
    if not os.path.isfile(mf):
        return None
    try:
        with open(mf) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def verify_manifest(path: str) -> List[str]:
    """Re-read every manifest-listed file and CRC-check it. Returns a
    list of problems (empty == the generation is valid): a missing or
    unparseable manifest, missing shards, size or CRC mismatches."""
    manifest = read_manifest(path)
    if manifest is None:
        return ["missing or unreadable MANIFEST.json"]
    problems: List[str] = []
    for rel, meta in sorted(manifest.get("files", {}).items()):
        full = os.path.join(path, rel)
        if not os.path.isfile(full):
            problems.append(f"missing shard {rel}")
            continue
        crc, size = _file_crc32(full)
        if size != meta["size"]:
            problems.append(
                f"size mismatch {rel}: {size} != {meta['size']}")
        elif crc != meta["crc32"]:
            problems.append(
                f"crc mismatch {rel}: {crc:#010x} != {meta['crc32']:#010x}")
    return problems


def staging_path(final_path: str) -> str:
    """Dot-prefixed sibling staging dir (same parent → same filesystem →
    the commit rename is atomic); generation listers skip dot entries."""
    head, tail = os.path.split(os.path.abspath(final_path))
    return os.path.join(head, _TMP_PREFIX + tail)


def replace_dir(tmp_path: str, final_path: str) -> str:
    """Atomically promote a fully-written staging dir to its final name.
    An existing destination (re-save of the same step) is parked aside
    first so readers never observe a partially-replaced generation."""
    tmp_path, final_path = os.path.abspath(tmp_path), os.path.abspath(final_path)
    trash = None
    if os.path.exists(final_path):
        trash = staging_path(final_path) + ".old"
        shutil.rmtree(trash, ignore_errors=True)
        os.replace(final_path, trash)
    os.replace(tmp_path, final_path)
    if trash is not None:
        shutil.rmtree(trash, ignore_errors=True)
    return final_path


def sweep_stale_staging(save_dir: str) -> int:
    """Remove ``.tmp-*`` husks a killed writer left behind. Safe any
    time: live stagings exist only inside an in-flight save on this
    host, and a fresh process has none."""
    n = 0
    if not os.path.isdir(save_dir):
        return 0
    for d in os.listdir(save_dir):
        if d.startswith(_TMP_PREFIX):
            shutil.rmtree(os.path.join(save_dir, d), ignore_errors=True)
            n += 1
    return n


class AutoCheckpoint:
    """Periodic train-loop snapshots with exactly-once epoch bookkeeping
    (ref fluid/incubate/checkpoint/auto_checkpoint.py).

    Every snapshot goes through the stage → manifest → rename commit, so
    a kill mid-save can no longer leave a torn ``step_*`` directory that
    ``resume`` would trust; ``latest()`` additionally CRC-verifies
    candidates newest-first and falls back past corrupt generations."""

    def __init__(self, save_dir: str, every_n_steps: int = 1000, keep_last: int = 3,
                 async_save: bool = False):
        self.save_dir = save_dir
        self.every_n_steps = every_n_steps
        self.keep_last = keep_last
        self.async_save = async_save
        self._step = 0
        self._saved = []
        self._inflight: Optional[threading.Thread] = None
        sweep_stale_staging(save_dir)

    def _commit(self, state: dict, tag: str):
        tmp = staging_path(tag)
        shutil.rmtree(tmp, ignore_errors=True)
        save_state_dict(state, tmp)
        write_manifest(tmp, step=self._step)
        replace_dir(tmp, tag)

    def step(self, model=None, optimizer=None, extra: Optional[dict] = None):
        from .fleet.elastic import pulse_heartbeat

        pulse_heartbeat()
        self._step += 1
        if self._step % self.every_n_steps != 0:
            return None
        self.wait()
        tag = os.path.join(self.save_dir, f"step_{self._step}")
        state = {}
        if model is not None:
            state["model"] = dict(model.state_dict())
        if optimizer is not None:
            state["optimizer"] = optimizer.state_dict()
        state["meta"] = {"step": np.asarray(self._step), **(extra or {})}
        if self.async_save:
            # the full commit (orbax write + manifest + rename) rides a
            # worker thread; the step loop never blocks on the filesystem
            self._inflight = threading.Thread(
                target=self._commit, args=(state, tag), daemon=True)
            self._inflight.start()
        else:
            self._commit(state, tag)
        self._saved.append(tag)
        while len(self._saved) > self.keep_last:
            old = self._saved.pop(0)
            shutil.rmtree(old, ignore_errors=True)
        return tag

    def wait(self):
        """Block until any in-flight async snapshot has committed."""
        if self._inflight is not None:
            self._inflight.join()
            self._inflight = None

    def latest(self) -> Optional[str]:
        if not os.path.isdir(self.save_dir):
            return None
        steps = []
        for d in os.listdir(self.save_dir):
            if d.startswith("step_"):
                try:
                    steps.append((int(d.split("_")[1]), os.path.join(self.save_dir, d)))
                except ValueError:
                    pass
        for _step, path in sorted(steps, reverse=True):
            if not verify_manifest(path):
                return path
        return None

    def resume(self, model=None, optimizer=None) -> int:
        self.wait()
        path = self.latest()
        if path is None:
            return 0
        state = load_state_dict(path)
        if model is not None and "model" in state:
            model.set_state_dict(state["model"])
        if optimizer is not None and "optimizer" in state:
            optimizer.set_state_dict(state["optimizer"])
        self._step = int(np.asarray(
            state["meta"]["step"].value if isinstance(state["meta"]["step"], Tensor)
            else state["meta"]["step"]))
        return self._step
