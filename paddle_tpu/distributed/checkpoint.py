"""Sharded, async checkpointing (ref: auto_parallel/dist_saver.py +
converter.py reshard-on-load; auto_checkpoint.py periodic snapshots).

TPU-native: orbax-backed. Arrays are saved with their shardings; on load,
orbax reshards to the target sharding (= converter.py capability natively).
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..framework.core import Tensor


def _to_arrays(tree):
    return jax.tree_util.tree_map(
        lambda x: x.value if isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def save_state_dict(state_dict: Dict[str, Any], path: str, async_save: bool = False):
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    arrays = _to_arrays(state_dict)
    # orbax wants a pytree of arrays; numpy-ify scalars
    arrays = jax.tree_util.tree_map(
        lambda x: np.asarray(x) if not isinstance(x, (jax.Array, np.ndarray)) else x, arrays)
    ckptr.save(path, arrays, force=True)
    if not async_save:
        ckptr.wait_until_finished()
    return ckptr


def load_state_dict(path: str, target: Optional[Dict[str, Any]] = None,
                    shardings: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    ckptr = ocp.StandardCheckpointer()
    if target is not None:
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                tuple(x.shape), x.dtype,
                sharding=shardings.get(id(x)) if shardings else None)
            if isinstance(x, (Tensor, jax.Array, np.ndarray)) else x,
            _to_arrays(target))
        restored = ckptr.restore(path, abstract)
    else:
        restored = ckptr.restore(path)
    return jax.tree_util.tree_map(lambda x: Tensor(x) if isinstance(
        x, (jax.Array, np.ndarray)) else x, restored)


class AutoCheckpoint:
    """Periodic train-loop snapshots with exactly-once epoch bookkeeping
    (ref fluid/incubate/checkpoint/auto_checkpoint.py)."""

    def __init__(self, save_dir: str, every_n_steps: int = 1000, keep_last: int = 3,
                 async_save: bool = False):
        self.save_dir = save_dir
        self.every_n_steps = every_n_steps
        self.keep_last = keep_last
        self.async_save = async_save
        self._step = 0
        self._saved = []

    def step(self, model=None, optimizer=None, extra: Optional[dict] = None):
        from .fleet.elastic import pulse_heartbeat

        pulse_heartbeat()
        self._step += 1
        if self._step % self.every_n_steps != 0:
            return None
        tag = os.path.join(self.save_dir, f"step_{self._step}")
        state = {}
        if model is not None:
            state["model"] = dict(model.state_dict())
        if optimizer is not None:
            state["optimizer"] = optimizer.state_dict()
        state["meta"] = {"step": np.asarray(self._step), **(extra or {})}
        save_state_dict(state, tag, async_save=self.async_save)
        self._saved.append(tag)
        while len(self._saved) > self.keep_last:
            old = self._saved.pop(0)
            try:
                import shutil

                shutil.rmtree(old, ignore_errors=True)
            except OSError:
                pass
        return tag

    def latest(self) -> Optional[str]:
        if not os.path.isdir(self.save_dir):
            return None
        steps = []
        for d in os.listdir(self.save_dir):
            if d.startswith("step_"):
                try:
                    steps.append((int(d.split("_")[1]), os.path.join(self.save_dir, d)))
                except ValueError:
                    pass
        return max(steps)[1] if steps else None

    def resume(self, model=None, optimizer=None) -> int:
        path = self.latest()
        if path is None:
            return 0
        state = load_state_dict(path)
        if model is not None and "model" in state:
            model.set_state_dict(state["model"])
        if optimizer is not None and "optimizer" in state:
            optimizer.set_state_dict(state["optimizer"])
        self._step = int(np.asarray(
            state["meta"]["step"].value if isinstance(state["meta"]["step"], Tensor)
            else state["meta"]["step"]))
        return self._step
