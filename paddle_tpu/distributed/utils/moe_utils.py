"""MoE token exchange (ref python/paddle/distributed/utils/moe_utils.py:20
global_scatter, :146 global_gather — NCCL alltoall of variable token counts).

TPU-native: XLA requires static shapes inside compiled programs, so the
exchange is expressed on capacity-padded expert buckets (the GShard
formulation our MoELayer uses): tensors are laid out
``[world_size * num_local_experts, capacity, d_model]`` and one
`lax.all_to_all` over the expert mesh axis moves bucket i*k..(i+1)*k to rank
i.  `local_count`/`global_count` are accepted for API parity and validated
against capacity; dynamic-count NCCL semantics have no static-shape
equivalent — callers route via capacity + dispatch masks instead (see
incubate/distributed/models/moe/moe_layer.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, to_array

__all__ = ["global_scatter", "global_gather"]


def _exchange(x, group, take_from_axis: bool):
    axis = group.axis if group is not None else "expert"
    arr = to_array(x)
    try:
        n = jax.lax.axis_size(axis)
        in_mesh = True
    except NameError:
        in_mesh = False
    if not in_mesh:
        # outside shard_map / pjit: single participant — exchange is identity
        return arr
    if arr.shape[0] % n != 0:
        raise ValueError(
            f"leading dim {arr.shape[0]} must be divisible by the "
            f"{axis!r}-axis size {n} (world_size*num_local_experts buckets)")
    return jax.lax.all_to_all(
        arr.reshape((n, arr.shape[0] // n) + arr.shape[1:]),
        axis, split_axis=0, concat_axis=0, tiled=False,
    ).reshape(arr.shape)


def global_scatter(x, local_count=None, global_count=None, group=None,
                   use_calc_stream=True):
    """Send expert buckets to their owning ranks (ref moe_utils.py:20)."""
    out = _exchange(x, group, take_from_axis=False)
    return Tensor(out) if isinstance(x, Tensor) else out


def global_gather(x, local_count=None, global_count=None, group=None,
                  use_calc_stream=True):
    """Inverse of global_scatter: bring this rank's tokens home
    (ref moe_utils.py:146). With capacity-padded buckets the exchange is an
    involution, so the wire pattern is the same all_to_all."""
    out = _exchange(x, group, take_from_axis=True)
    return Tensor(out) if isinstance(x, Tensor) else out
