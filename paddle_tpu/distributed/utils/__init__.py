"""paddle.distributed.utils (ref python/paddle/distributed/utils/)."""
from . import launch_utils, log_utils, moe_utils  # noqa: F401
from .log_utils import get_logger  # noqa: F401
from .launch_utils import (  # noqa: F401
    Cluster,
    Pod,
    Trainer,
    find_free_ports,
    get_cluster,
    get_host_name_ip,
    terminate_local_procs,
)

__all__ = []
