"""ref python/paddle/distributed/utils/log_utils.py:18 get_logger."""
import logging


def get_logger(log_level, name="root"):
    logger = logging.getLogger(name)
    logger.setLevel(log_level)
    if not logger.handlers:
        log_handler = logging.StreamHandler()
        log_format = logging.Formatter(
            "%(levelname)s %(asctime)s %(filename)s:%(lineno)d] %(message)s")
        log_handler.setFormatter(log_format)
        logger.addHandler(log_handler)
    return logger
