"""Cluster/Pod/Trainer topology description + local-proc management (ref
python/paddle/distributed/utils/launch_utils.py:132 Cluster, :243 Pod,
:306 get_cluster, :387 find_free_ports, :468 start_local_trainers).

TPU note: "selected_gpus" becomes per-process TPU chip ordinals; on real TPU
pods one process drives all local chips, so multi-proc launch is for
multi-host jobs and CPU-mesh tests.
"""
from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
from contextlib import closing
from typing import List, Optional

from .log_utils import get_logger

logger = get_logger("INFO", "launch_utils")


class Trainer:
    def __init__(self):
        self.accelerators: List[int] = []
        self.endpoint: Optional[str] = None
        self.rank: Optional[int] = None

    def __str__(self):
        return f"accelerators:{self.accelerators} endpoint:{self.endpoint} rank:{self.rank}"

    def __eq__(self, t):
        return (self.accelerators == t.accelerators
                and self.endpoint == t.endpoint and self.rank == t.rank)

    def __ne__(self, t):
        return not self == t

    def rank_str(self):
        return str(self.rank)


class Pod:
    def __init__(self):
        self.rank: Optional[int] = None
        self.id: Optional[str] = None
        self.addr: Optional[str] = None
        self.port: Optional[int] = None
        self.trainers: List[Trainer] = []
        self.accelerators: List[int] = []

    def __str__(self):
        return (f"rank:{self.rank} id:{self.id} addr:{self.addr} port:{self.port} "
                f"trainers_num:{len(self.trainers)}")

    def __eq__(self, pod):
        return (self.rank == pod.rank and self.id == pod.id
                and self.addr == pod.addr and self.port == pod.port
                and self.trainers == pod.trainers)

    def __ne__(self, pod):
        return not self == pod

    def rank_str(self):
        return str(self.rank)

    def get_visible_accelerators(self):
        return ",".join(str(a) for a in self.accelerators)


class Cluster:
    def __init__(self, hdfs=None):
        self.job_server = None
        self.pods: List[Pod] = []
        self.hdfs = hdfs
        self.job_stage_flag = None

    def __str__(self):
        return f"job_server:{self.job_server} pods:{[str(p) for p in self.pods]}"

    def __eq__(self, cluster):
        return (len(self.pods) == len(cluster.pods)
                and all(a == b for a, b in zip(self.pods, cluster.pods)))

    def __ne__(self, cluster):
        return not self == cluster

    def update_pods(self, cluster):
        self.pods = list(cluster.pods)

    def trainers_nranks(self) -> int:
        return len(self.trainers_endpoints())

    def pods_nranks(self) -> int:
        return len(self.pods)

    def trainers_endpoints(self) -> List[str]:
        return [t.endpoint for pod in self.pods for t in pod.trainers]

    def pods_endpoints(self) -> List[str]:
        return [f"{pod.addr}:{pod.port}" for pod in self.pods]

    def get_pod_by_id(self, pod_id):
        for pod in self.pods:
            if str(pod_id) == str(pod.id):
                return pod
        return None


def get_cluster(node_ips, node_ip, trainer_endpoints, selected_accelerators) -> tuple:
    """Build (Cluster, current Pod) from node/endpoint lists (ref :306)."""
    assert isinstance(trainer_endpoints, list), "trainer_endpoints must be a list"
    cluster = Cluster()
    trainer_rank = 0
    for node_rank, ip in enumerate(node_ips):
        pod = Pod()
        pod.rank = node_rank
        pod.addr = ip
        pod.id = node_rank
        cur_node_endpoints = trainer_endpoints[node_rank]
        for i in range(len(cur_node_endpoints)):
            trainer = Trainer()
            trainer.accelerators.append(selected_accelerators[i])
            trainer.endpoint = cur_node_endpoints[i]
            trainer.rank = trainer_rank
            trainer_rank += 1
            pod.trainers.append(trainer)
        cluster.pods.append(pod)
    pod_rank = node_ips.index(node_ip)
    return cluster, cluster.pods[pod_rank]


def get_host_name_ip():
    try:
        host_name = socket.gethostname()
        host_ip = socket.gethostbyname(host_name)
        return host_name, host_ip
    except Exception:
        return None


def find_free_ports(num: int):
    """ref :387 — probe the OS for num free TCP ports."""
    port_set = set()
    step = 0
    while True:
        with closing(socket.socket(socket.AF_INET, socket.SOCK_STREAM)) as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("", 0))
            port_set.add(s.getsockname()[1])
        if len(port_set) >= num:
            return port_set
        step += 1
        if step > 400:
            logger.warning("can't find available port; exhausted %d probes", step)
            return None


def add_arguments(argname, type, default, help, argparser, **kwargs):
    argparser.add_argument(
        "--" + argname, default=default, type=type,
        help=help + " Default: %(default)s.", **kwargs)


class TrainerProc:
    def __init__(self):
        self.proc = None
        self.log_fn = None
        self.log_offset = None
        self.rank = None
        self.local_rank = None
        self.cmd = None


def _prepare_trainer_env(cluster: Cluster, trainer: Trainer) -> dict:
    return {
        "PADDLE_TRAINER_ID": str(trainer.rank),
        "PADDLE_CURRENT_ENDPOINT": trainer.endpoint,
        "PADDLE_TRAINERS_NUM": str(cluster.trainers_nranks()),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(cluster.trainers_endpoints()),
        "PADDLE_LOCAL_DEVICE_IDS": ",".join(str(a) for a in trainer.accelerators),
    }


def start_local_trainers(cluster: Cluster, pod: Pod, training_script: str,
                         training_script_args, log_dir=None):
    """Spawn one subprocess per trainer in this pod (ref :468)."""
    current_env = {k: v for k, v in os.environ.items()
                   if k not in ("http_proxy", "https_proxy")}
    procs = []
    for idx, t in enumerate(pod.trainers):
        proc_env = _prepare_trainer_env(cluster, t)
        current_env.update(proc_env)
        cmd = [sys.executable, "-u", training_script] + list(training_script_args)
        fn = None
        if log_dir is not None:
            os.makedirs(log_dir, exist_ok=True)
            fn = open(f"{log_dir}/workerlog.{idx}", "a")
            proc = subprocess.Popen(cmd, env=current_env, stdout=fn, stderr=fn)
        else:
            proc = subprocess.Popen(cmd, env=current_env)
        tp = TrainerProc()
        tp.proc = proc
        tp.rank = t.rank
        tp.local_rank = idx
        tp.log_fn = fn
        tp.log_offset = fn.tell() if fn else None
        tp.cmd = cmd
        procs.append(tp)
    return procs


def pull_worker_log(tp: TrainerProc):
    if tp.log_fn:
        with open(tp.log_fn.name, "r") as fin:
            fin.seek(tp.log_offset, 0)
            for line in fin:
                try:
                    sys.stdout.write(line)
                except UnicodeEncodeError:
                    pass
            tp.log_offset = fin.tell()


def watch_local_trainers(procs: List[TrainerProc], nranks: int):
    """Poll trainer procs; raise if any died abnormally (ref :527)."""
    alive = False
    error = False
    error_rank = []
    for p in procs:
        if p.log_fn and p.local_rank == 0:
            pull_worker_log(p)
        ret = p.proc.poll()
        if ret is None:
            alive = True
        elif ret != 0:
            error = True
            error_rank.append(p.rank)
    if error:
        terminate_local_procs(procs)
        raise RuntimeError(f"trainers {error_rank} exited abnormally")
    return alive


def terminate_local_procs(procs: List[TrainerProc]):
    """ref :333 — SIGTERM, grace period, then kill."""
    for p in procs:
        if p.proc and p.proc.poll() is None:
            p.proc.terminate()
            if p.log_fn:
                p.log_fn.close()
    for _ in range(20):
        if all(p.proc is None or p.proc.poll() is not None for p in procs):
            return
        time.sleep(0.1)
    for p in procs:
        if p.proc and p.proc.poll() is None:
            try:
                os.kill(p.proc.pid, signal.SIGKILL)
            except OSError:
                pass
