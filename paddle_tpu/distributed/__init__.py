"""paddle.distributed parity (ref: python/paddle/distributed/).

TPU-native mapping (SURVEY §5.8): one XLA-collectives backend over ICI/DCN;
mesh axes replace process groups; jax.distributed.initialize replaces
TCPStore+NCCL bootstrap; pjit/GSPMD sharding replaces per-rank program
slicing.
"""
from .collective import (Group, ParallelMode, ProcessGroup, ReduceOp, all_gather,
                         all_gather_object, all_reduce, all_to_all, alltoall,
                         alltoall_single, barrier, broadcast, broadcast_object_list,
                         destroy_process_group, get_backend, get_global_mesh, get_group,
                         irecv, is_available, isend, new_group, recv, reduce,
                         reduce_scatter, scatter, scatter_object_list, send,
                         set_global_mesh, split, wait)
from .env import (ParallelEnv, get_rank, get_world_size, init_parallel_env, is_initialized)
from .fleet.dataset import InMemoryDataset, QueueDataset  # noqa: F401
from .store import TCPStore
from .topology import CommunicateTopology, HybridCommunicateGroup, build_mesh
from .parallel import DataParallel
from . import auto_parallel  # noqa: F401
from . import checkpoint  # noqa: F401
from . import train_checkpoint  # noqa: F401
from . import communication  # noqa: F401
from . import fleet  # noqa: F401
from . import sharding  # noqa: F401
from .auto_parallel import ProcessMesh, shard_op, shard_tensor  # noqa: F401
from .launch_util import spawn  # noqa: F401
from . import io  # noqa: F401
from . import metric  # noqa: F401
from . import models  # noqa: F401
from . import passes  # noqa: F401
from . import rpc  # noqa: F401
from . import utils  # noqa: F401
from . import fleet_executor  # noqa: F401
from . import cloud_utils  # noqa: F401
from . import communicator  # noqa: F401
from . import entry_attr  # noqa: F401
from . import parallel_with_gloo  # noqa: F401
from .entry_attr import CountFilterEntry, ProbabilityEntry, ShowClickEntry  # noqa: F401
from .parallel_with_gloo import gloo_barrier, gloo_init_parallel_env, gloo_release  # noqa: F401

__all__ = [n for n in dir() if not n.startswith("_")]
