"""Cluster launcher CLI (ref: python/paddle/distributed/launch/main.py:18 +
controllers/collective.py:21 build_pod + job/ Pod/Container).

Usage parity:
    python -m paddle_tpu.distributed.launch [--nnodes N] [--master ip:port]
        [--nproc_per_node M] [--log_dir d] [--max_restart K] train.py args...

TPU semantics: one process drives all local chips, so nproc_per_node defaults
to 1 (the reference defaults to #GPUs). Multi-node: rendezvous over the KV
master, then each process gets PADDLE_TRAINER_ID/PADDLE_TRAINER_ENDPOINTS env
(same contract as collective.py:75-78) and jax.distributed.initialize is
driven from them by init_parallel_env.
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import time
from typing import List

from .rendezvous import ETCDMaster, HTTPMaster


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _local_ip() -> str:
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--master", default=None, help="master endpoint ip:port")
    p.add_argument("--nnodes", default="1", help="N or min:max (elastic)")
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--rank", type=int, default=-1)
    p.add_argument("--log_dir", default="log")
    p.add_argument("--run_mode", default="collective")
    p.add_argument("--job_id", default="default")
    p.add_argument("--max_restart", type=int, default=3)
    p.add_argument("--elastic_timeout", type=float, default=0.0,
                   help="seconds without a trainer heartbeat before the rank "
                        "is declared hung and the pod restarted (0=off); "
                        "trainers beat via PADDLE_HEARTBEAT_FILE (set "
                        "automatically) — init_parallel_env or "
                        "fleet.elastic.start_file_heartbeat() starts the beat")
    p.add_argument("--devices", "--gpus", default=None)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


class Container:
    """One managed process (ref launch/job/container.py)."""

    def __init__(self, cmd: List[str], env: dict, log_path: str,
                 heartbeat_file: str | None = None):
        self.cmd = cmd
        self.env = env
        self.log_path = log_path
        self.heartbeat_file = heartbeat_file
        self.proc: subprocess.Popen | None = None

    def start(self):
        os.makedirs(os.path.dirname(self.log_path) or ".", exist_ok=True)
        self._log = open(self.log_path, "ab")
        self.proc = subprocess.Popen(self.cmd, env={**os.environ, **self.env},
                                     stdout=self._log, stderr=subprocess.STDOUT)

    def poll(self):
        return self.proc.poll() if self.proc else None

    def terminate(self):
        if self.proc and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(10)
            except subprocess.TimeoutExpired:
                self.proc.kill()


class Pod:
    """All containers on this node (ref launch/job/pod.py)."""

    def __init__(self):
        self.containers: List[Container] = []

    def deploy(self):
        for c in self.containers:
            c.start()

    HANG_EXIT = 98  # pod killed by the heartbeat watcher

    @staticmethod
    def _norm(code: int) -> int:
        # signal deaths poll() as negative; normalize to 128+sig so a rank
        # killed by SIGKILL can never be masked by a sibling's exit 0
        return 128 - code if code < 0 else code

    def join(self, hang_timeout: float = 0.0) -> int:
        last_beat: dict = {}  # container -> (mtime, local time it changed)
        while True:
            codes = [c.poll() for c in self.containers]
            if all(code is not None for code in codes):
                return max(self._norm(code) for code in codes)
            if any(code not in (None, 0) for code in codes):
                for c in self.containers:
                    c.terminate()
                return max(self._norm(code) for code in codes
                           if code is not None)
            if hang_timeout > 0:
                # failure DETECTION beyond process exit (ref elastic
                # manager.py:260 lease heartbeats): a rank that stops
                # touching its heartbeat file while still running is hung —
                # kill the pod so the launcher's restart loop can recover.
                # Staleness = the mtime has not ADVANCED for hang_timeout by
                # the launcher's own clock (comparing successive mtimes, not
                # mtime-vs-wallclock, so a skewed NFS server clock cannot
                # fake staleness).
                now = time.time()
                for c in self.containers:
                    hb = c.heartbeat_file
                    if not (c.poll() is None and hb and os.path.exists(hb)):
                        continue
                    mtime = os.path.getmtime(hb)
                    prev = last_beat.get(c)
                    if prev is None or mtime != prev[0]:
                        last_beat[c] = (mtime, now)
                        continue
                    if now - prev[1] > hang_timeout:
                        print(f"[launch] rank heartbeat stale "
                              f"({hb}, >{hang_timeout}s): declaring hung",
                              file=sys.stderr)
                        for cc in self.containers:
                            cc.terminate()
                        return self.HANG_EXIT
            time.sleep(0.2 if hang_timeout > 0 else 1)

    def stop(self):
        for c in self.containers:
            c.terminate()


def build_pod(args, node_rank: int, endpoints: List[str]) -> Pod:
    """Ref controllers/collective.py:32: assign ranks + env per process."""
    pod = Pod()
    nnodes = len(endpoints)
    n = args.nproc_per_node
    for local_rank in range(n):
        global_rank = node_rank * n + local_rank
        env = {
            "PADDLE_TRAINER_ID": str(global_rank),
            "PADDLE_TRAINERS_NUM": str(nnodes * n),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_CURRENT_ENDPOINT": endpoints[node_rank],
            "PADDLE_MASTER": endpoints[0],
            "FLAGS_selected_devices": str(local_rank),
        }
        log = os.path.join(args.log_dir, f"workerlog.{global_rank}")
        hb = None
        if args.elastic_timeout > 0:
            hb = os.path.join(args.log_dir, f"heartbeat.{global_rank}")
            env["PADDLE_HEARTBEAT_FILE"] = hb
            env["PADDLE_HEARTBEAT_INTERVAL"] = str(
                max(0.2, args.elastic_timeout / 4))
            try:
                os.remove(hb)  # stale beat from a previous attempt
            except OSError:
                pass
        if hb is None:
            # clear any inherited value: a nested launch must not alias an
            # outer launcher's heartbeat file
            env["PADDLE_HEARTBEAT_FILE"] = ""
        cmd = [sys.executable, "-u", args.training_script] + args.training_script_args
        pod.containers.append(Container(cmd, env, log, heartbeat_file=hb))
    return pod


def launch(argv=None) -> int:
    args = parse_args(argv)
    nnodes = int(str(args.nnodes).split(":")[0])

    if nnodes <= 1 and args.master is None:
        endpoints = [f"127.0.0.1:{_free_port()}"]
        node_rank = 0
        master = None
    else:
        if args.master is not None and args.master.startswith("etcd://"):
            # external etcd rendezvous (ref controllers/master.py:177):
            # the cluster scheduler owns the store; nobody hosts anything
            master = ETCDMaster(args.master, nnodes)
        else:
            master_ep = args.master or f"{_local_ip()}:{_free_port()}"
            master_host = master_ep.rsplit(":", 1)[0]
            # the master host may be named by loopback, hostname, or LAN
            # ip — resolve spellings of "this machine" before deciding to
            # host. (0.0.0.0 is deliberately NOT local: with the wildcard
            # every node would claim mastership and split-brain its own
            # private store)
            local_names = {_local_ip(), "127.0.0.1", "localhost",
                           socket.gethostname()}
            try:
                local_names.add(socket.gethostbyname(socket.gethostname()))
            except OSError:
                pass
            is_master = args.rank in (0, -1) and (args.master is None or
                                                  master_host in local_names)
            master = HTTPMaster(master_ep, is_master, nnodes)
        my_ep = f"{_local_ip()}:{_free_port()}"
        # identity for slot claims: explicit env id (stable across elastic
        # restarts) > explicit rank (pins slot rank directly) > the unique
        # endpoint (same-host launchers can't collide; no restart rejoin)
        node_id = os.environ.get("PADDLE_NODE_ID") or (
            f"rank{args.rank}" if args.rank >= 0 else my_ep)
        endpoints = master.sync_peers(
            my_ep, args.job_id, node_id=node_id,
            preferred_slot=args.rank if args.rank >= 0 else None)
        node_rank = endpoints.index(my_ep) if args.rank < 0 else args.rank

    restarts = 0
    try:
        while True:
            pod = build_pod(args, node_rank, endpoints)
            pod.deploy()
            code = pod.join(hang_timeout=args.elastic_timeout)
            if code == 0:
                return 0
            restarts += 1
            if restarts > args.max_restart:
                print(f"[launch] giving up after {restarts - 1} restarts, exit {code}",
                      file=sys.stderr)
                return code
            print(f"[launch] restart {restarts}/{args.max_restart} (exit {code})",
                  file=sys.stderr)
    finally:
        if master is not None:
            master.stop()


if __name__ == "__main__":
    sys.exit(launch())
