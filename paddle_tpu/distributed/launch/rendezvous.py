"""Rendezvous masters (ref: launch/controllers/master.py — HTTPMaster:65
KV-barrier sync_peers, ETCDMaster:177).

TPU-native: a small threaded TCP KV store on node 0 (the TCPStore role, ref
paddle/phi/core/distributed/store/tcp_store.cc) used only for peer discovery;
the actual collective bootstrap is jax.distributed.initialize, which has its
own coordinator.
"""
from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from typing import Dict, List, Optional


class _KVHandler(socketserver.StreamRequestHandler):
    def handle(self):
        try:
            line = self.rfile.readline().decode().strip()
            req = json.loads(line)
            store: Dict[str, str] = self.server.kv  # type: ignore
            with self.server.lock:  # type: ignore
                if req["op"] == "set":
                    store[req["key"]] = req["value"]
                    resp = {"ok": True}
                elif req["op"] == "get":
                    resp = {"ok": req["key"] in store,
                            "value": store.get(req["key"])}
                elif req["op"] == "add":
                    store[req["key"]] = str(int(store.get(req["key"], "0"))
                                            + int(req["value"]))
                    resp = {"ok": True, "value": store[req["key"]]}
                elif req["op"] == "list":
                    prefix = req["key"]
                    resp = {"ok": True, "value": {k: v for k, v in store.items()
                                                  if k.startswith(prefix)}}
                elif req["op"] == "del":
                    resp = {"ok": store.pop(req["key"], None) is not None}
                else:
                    resp = {"ok": False}
            self.wfile.write((json.dumps(resp) + "\n").encode())
        except Exception:
            pass


class KVServer:
    def __init__(self, port: int):
        self.server = socketserver.ThreadingTCPServer(("0.0.0.0", port), _KVHandler,
                                                      bind_and_activate=False)
        self.server.allow_reuse_address = True
        self.server.server_bind()
        self.server.server_activate()
        self.server.kv = {}
        self.server.lock = threading.Lock()
        self._thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self.server.shutdown()


class KVClient:
    def __init__(self, endpoint: str):
        host, port = endpoint.rsplit(":", 1)
        self.addr = (host, int(port))

    def _req(self, **kw):
        for _ in range(300):
            try:
                with socket.create_connection(self.addr, timeout=5) as s:
                    s.sendall((json.dumps(kw) + "\n").encode())
                    data = s.makefile().readline()
                    return json.loads(data)
            except (ConnectionError, socket.timeout, OSError):
                time.sleep(0.2)
        raise TimeoutError(f"KV store at {self.addr} unreachable")

    def set(self, key, value):
        return self._req(op="set", key=key, value=value)

    def get(self, key):
        r = self._req(op="get", key=key)
        return r.get("value") if r.get("ok") else None

    def add(self, key, value=1):
        return int(self._req(op="add", key=key, value=value)["value"])

    def list(self, prefix):
        return self._req(op="list", key=prefix)["value"]

    def delete(self, key) -> bool:
        return bool(self._req(op="del", key=key).get("ok"))


class HTTPMaster:
    """sync_peers barrier (ref master.py:54,65): every node publishes its
    endpoint, waits until all N are present, gets a deterministic rank."""

    def __init__(self, master_endpoint: str, is_master: bool, nnodes: int):
        self.endpoint = master_endpoint
        self.nnodes = nnodes
        self.server: Optional[KVServer] = None
        if is_master:
            self.server = KVServer(int(master_endpoint.rsplit(":", 1)[1]))
        self.client = KVClient(master_endpoint)

    def sync_peers(self, my_endpoint: str, job_id: str = "default") -> List[str]:
        key = f"peers/{job_id}/{my_endpoint}"
        self.client.set(key, my_endpoint)
        while True:
            peers = self.client.list(f"peers/{job_id}/")
            if len(peers) >= self.nnodes:
                return sorted(peers.values())
            time.sleep(0.3)

    def stop(self):
        if self.server:
            self.server.stop()
