"""Rendezvous masters (ref: launch/controllers/master.py — HTTPMaster:65
KV-barrier sync_peers, ETCDMaster:177).

TPU-native: a small threaded TCP KV store on node 0 (the TCPStore role, ref
paddle/phi/core/distributed/store/tcp_store.cc) used only for peer discovery;
the actual collective bootstrap is jax.distributed.initialize, which has its
own coordinator.
"""
from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from typing import Dict, List, Optional


class _KVHandler(socketserver.StreamRequestHandler):
    def handle(self):
        try:
            line = self.rfile.readline().decode().strip()
            req = json.loads(line)
            store: Dict[str, str] = self.server.kv  # type: ignore
            with self.server.lock:  # type: ignore
                if req["op"] == "set":
                    store[req["key"]] = req["value"]
                    resp = {"ok": True}
                elif req["op"] == "get":
                    resp = {"ok": req["key"] in store,
                            "value": store.get(req["key"])}
                elif req["op"] == "add":
                    store[req["key"]] = str(int(store.get(req["key"], "0"))
                                            + int(req["value"]))
                    resp = {"ok": True, "value": store[req["key"]]}
                elif req["op"] == "list":
                    prefix = req["key"]
                    resp = {"ok": True, "value": {k: v for k, v in store.items()
                                                  if k.startswith(prefix)}}
                elif req["op"] == "del":
                    resp = {"ok": store.pop(req["key"], None) is not None}
                elif req["op"] == "setnx":
                    if req["key"] in store:
                        resp = {"ok": True, "claimed": False,
                                "value": store[req["key"]]}
                    else:
                        store[req["key"]] = req["value"]
                        resp = {"ok": True, "claimed": True,
                                "value": req["value"]}
                else:
                    resp = {"ok": False}
            self.wfile.write((json.dumps(resp) + "\n").encode())
        except Exception:
            pass


class KVServer:
    def __init__(self, port: int):
        self.server = socketserver.ThreadingTCPServer(("0.0.0.0", port), _KVHandler,
                                                      bind_and_activate=False)
        self.server.allow_reuse_address = True
        self.server.server_bind()
        self.server.server_activate()
        self.server.kv = {}
        self.server.lock = threading.Lock()
        self._thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self.server.shutdown()


class KVClient:
    def __init__(self, endpoint: str):
        host, port = endpoint.rsplit(":", 1)
        self.addr = (host, int(port))

    def _req(self, **kw):
        for _ in range(300):
            try:
                with socket.create_connection(self.addr, timeout=5) as s:
                    s.sendall((json.dumps(kw) + "\n").encode())
                    data = s.makefile().readline()
                    return json.loads(data)
            except (ConnectionError, socket.timeout, OSError):
                time.sleep(0.2)
        raise TimeoutError(f"KV store at {self.addr} unreachable")

    def set(self, key, value):
        return self._req(op="set", key=key, value=value)

    def get(self, key):
        r = self._req(op="get", key=key)
        return r.get("value") if r.get("ok") else None

    def add(self, key, value=1):
        return int(self._req(op="add", key=key, value=value)["value"])

    def list(self, prefix):
        return self._req(op="list", key=prefix)["value"]

    def delete(self, key) -> bool:
        return bool(self._req(op="del", key=key).get("ok"))

    def setnx(self, key, value):
        return self._req(op="setnx", key=key, value=value)


class HTTPMaster:
    """sync_peers barrier (ref master.py:54,65): every node publishes its
    endpoint, waits until all N are present, gets a deterministic rank.

    Backed by the native C++ TCPStore (csrc/tcp_store.cpp) when available —
    join-order rank assignment via the store's atomic add() counter — with
    the same algorithm over the pure-Python KV fallback otherwise."""

    def __init__(self, master_endpoint: str, is_master: bool, nnodes: int,
                 timeout: float = 300.0):
        from ..store import PortInUseError, TCPStore

        self.endpoint = master_endpoint
        self.nnodes = nnodes
        self.timeout = timeout
        host, port = master_endpoint.rsplit(":", 1)
        if is_master:
            try:
                self.store = TCPStore(host, int(port), is_master=True,
                                      world_size=nnodes, timeout=timeout)
                return
            except PortInUseError:
                # another same-host launcher already hosts the store (both
                # legitimately matched "this machine" with rank -1): join it.
                # Only the bind failure falls through — connect timeouts etc.
                # must propagate, not silently demote the master to a client
                pass
        self.store = TCPStore(host, int(port), is_master=False,
                              world_size=nnodes, timeout=timeout)

    def sync_peers(self, my_endpoint: str, job_id: str = "default",
                   node_id: str = None, preferred_slot: int = None) -> List[str]:
        """Claim rank slots 0..n-1 via atomic set-if-absent.

        Slots are keyed by a node identity (``node_id``; defaults to the
        unique endpoint), and the slot's endpoint is stored separately and
        overwritable — so a node relaunched with a fresh port re-finds its
        slot when it has a STABLE identity (set ``PADDLE_NODE_ID`` for
        elastic restarts; the default endpoint identity is unique per
        process, which keeps same-host multi-launcher setups collision-free
        but cannot survive a port change). ``preferred_slot`` pins the claim
        to one slot (used with explicit --rank so slot order == rank order).
        Crash-safe: a node that dies mid-claim leaves either nothing or a
        slot its replacement (same identity) reuses."""
        me = (node_id or my_endpoint).encode()
        claimed = None
        slots = [preferred_slot] if preferred_slot is not None else \
            range(self.nnodes)
        for i in slots:
            ok, cur = self.store.set_nx(f"peers/{job_id}/owner/{i}", me)
            if ok or cur == me:
                claimed = i
                break
        if claimed is None:
            raise RuntimeError(
                f"rendezvous: peer slot(s) {list(slots)} taken and node id "
                f"{me.decode()!r} owns none of them (stale job_id "
                f"{job_id!r}?)")
        # endpoint may change across restarts: plain set, not set_nx
        self.store.set(f"peers/{job_id}/ep/{claimed}", my_endpoint)
        # every node reads the same numbered slots, so the list (and the
        # endpoints.index-derived rank) is identical everywhere
        return [self.store.wait(f"peers/{job_id}/ep/{i}",
                                self.timeout).decode()
                for i in range(self.nnodes)]

    def stop(self):
        self.store.close()
