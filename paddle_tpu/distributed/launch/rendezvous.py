"""Rendezvous masters (ref: launch/controllers/master.py — HTTPMaster:65
KV-barrier sync_peers, ETCDMaster:177).

TPU-native: a small threaded TCP KV store on node 0 (the TCPStore role, ref
paddle/phi/core/distributed/store/tcp_store.cc) used only for peer discovery;
the actual collective bootstrap is jax.distributed.initialize, which has its
own coordinator.
"""
from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from typing import Dict, List, Optional


class _KVHandler(socketserver.StreamRequestHandler):
    def handle(self):
        try:
            line = self.rfile.readline().decode().strip()
            req = json.loads(line)
            store: Dict[str, str] = self.server.kv  # type: ignore
            with self.server.lock:  # type: ignore
                if req["op"] == "set":
                    store[req["key"]] = req["value"]
                    resp = {"ok": True}
                elif req["op"] == "get":
                    resp = {"ok": req["key"] in store,
                            "value": store.get(req["key"])}
                elif req["op"] == "add":
                    store[req["key"]] = str(int(store.get(req["key"], "0"))
                                            + int(req["value"]))
                    resp = {"ok": True, "value": store[req["key"]]}
                elif req["op"] == "list":
                    prefix = req["key"]
                    resp = {"ok": True, "value": {k: v for k, v in store.items()
                                                  if k.startswith(prefix)}}
                elif req["op"] == "del":
                    resp = {"ok": store.pop(req["key"], None) is not None}
                elif req["op"] == "setnx":
                    if req["key"] in store:
                        resp = {"ok": True, "claimed": False,
                                "value": store[req["key"]]}
                    else:
                        store[req["key"]] = req["value"]
                        resp = {"ok": True, "claimed": True,
                                "value": req["value"]}
                else:
                    resp = {"ok": False}
            self.wfile.write((json.dumps(resp) + "\n").encode())
        except Exception:
            pass


class KVServer:
    def __init__(self, port: int):
        self.server = socketserver.ThreadingTCPServer(("0.0.0.0", port), _KVHandler,
                                                      bind_and_activate=False)
        self.server.allow_reuse_address = True
        self.server.server_bind()
        self.server.server_activate()
        self.server.kv = {}
        self.server.lock = threading.Lock()
        self._thread = threading.Thread(target=self.server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self.server.shutdown()


class KVClient:
    def __init__(self, endpoint: str):
        host, port = endpoint.rsplit(":", 1)
        self.addr = (host, int(port))

    def _req(self, **kw):
        for _ in range(300):
            try:
                with socket.create_connection(self.addr, timeout=5) as s:
                    s.sendall((json.dumps(kw) + "\n").encode())
                    data = s.makefile().readline()
                    return json.loads(data)
            except (ConnectionError, socket.timeout, OSError):
                time.sleep(0.2)
        raise TimeoutError(f"KV store at {self.addr} unreachable")

    def set(self, key, value):
        return self._req(op="set", key=key, value=value)

    def get(self, key):
        r = self._req(op="get", key=key)
        return r.get("value") if r.get("ok") else None

    def add(self, key, value=1):
        return int(self._req(op="add", key=key, value=value)["value"])

    def list(self, prefix):
        return self._req(op="list", key=prefix)["value"]

    def delete(self, key) -> bool:
        return bool(self._req(op="del", key=key).get("ok"))

    def setnx(self, key, value):
        return self._req(op="setnx", key=key, value=value)


class HTTPMaster:
    """sync_peers barrier (ref master.py:54,65): every node publishes its
    endpoint, waits until all N are present, gets a deterministic rank.

    Backed by the native C++ TCPStore (csrc/tcp_store.cpp) when available —
    join-order rank assignment via the store's atomic add() counter — with
    the same algorithm over the pure-Python KV fallback otherwise."""

    def __init__(self, master_endpoint: str, is_master: bool, nnodes: int,
                 timeout: float = 300.0):
        from ..store import PortInUseError, TCPStore

        self.endpoint = master_endpoint
        self.nnodes = nnodes
        self.timeout = timeout
        host, port = master_endpoint.rsplit(":", 1)
        if is_master:
            try:
                self.store = TCPStore(host, int(port), is_master=True,
                                      world_size=nnodes, timeout=timeout)
                return
            except PortInUseError:
                # another same-host launcher already hosts the store (both
                # legitimately matched "this machine" with rank -1): join it.
                # Only the bind failure falls through — connect timeouts etc.
                # must propagate, not silently demote the master to a client
                pass
        self.store = TCPStore(host, int(port), is_master=False,
                              world_size=nnodes, timeout=timeout)

    def sync_peers(self, my_endpoint: str, job_id: str = "default",
                   node_id: str = None, preferred_slot: int = None) -> List[str]:
        """Claim rank slots 0..n-1 via atomic set-if-absent.

        Slots are keyed by a node identity (``node_id``; defaults to the
        unique endpoint), and the slot's endpoint is stored separately and
        overwritable — so a node relaunched with a fresh port re-finds its
        slot when it has a STABLE identity (set ``PADDLE_NODE_ID`` for
        elastic restarts; the default endpoint identity is unique per
        process, which keeps same-host multi-launcher setups collision-free
        but cannot survive a port change). ``preferred_slot`` pins the claim
        to one slot (used with explicit --rank so slot order == rank order).
        Crash-safe: a node that dies mid-claim leaves either nothing or a
        slot its replacement (same identity) reuses."""
        me = (node_id or my_endpoint).encode()
        claimed = None
        slots = [preferred_slot] if preferred_slot is not None else \
            range(self.nnodes)
        for i in slots:
            ok, cur = self.store.set_nx(f"peers/{job_id}/owner/{i}", me)
            if ok or cur == me:
                claimed = i
                break
        if claimed is None:
            raise RuntimeError(
                f"rendezvous: peer slot(s) {list(slots)} taken and node id "
                f"{me.decode()!r} owns none of them (stale job_id "
                f"{job_id!r}?)")
        # endpoint may change across restarts: plain set, not set_nx
        self.store.set(f"peers/{job_id}/ep/{claimed}", my_endpoint)
        # every node reads the same numbered slots, so the list (and the
        # endpoints.index-derived rank) is identical everywhere
        return [self.store.wait(f"peers/{job_id}/ep/{i}",
                                self.timeout).decode()
                for i in range(self.nnodes)]

    def stop(self):
        self.store.close()


class ETCDMaster:
    """Rendezvous through an EXTERNAL etcd cluster (ref
    launch/controllers/master.py:177 ETCDMaster — the deployment story when
    a cluster scheduler owns etcd). Same ``sync_peers`` contract as
    HTTPMaster, speaking the etcd v3 gRPC-gateway JSON API directly
    (``/v3/kv/put``, ``/v3/kv/range``, ``/v3/kv/txn``) so no client
    library is needed: a txn comparing ``create_revision == 0`` is the
    atomic set-if-absent that claims a rank slot.

    Select from the CLI with ``--master etcd://host:port``.
    """

    def __init__(self, endpoint: str, nnodes: int, timeout: float = 300.0):
        if endpoint.startswith("etcd://"):
            endpoint = endpoint[len("etcd://"):]
        if not endpoint.startswith("http"):
            endpoint = "http://" + endpoint
        self.base = endpoint.rstrip("/")
        self.nnodes = nnodes
        self.timeout = timeout

    # ------------------------------------------------------------- etcd ops
    @staticmethod
    def _b64(s) -> str:
        import base64

        if isinstance(s, str):
            s = s.encode()
        return base64.b64encode(s).decode()

    @staticmethod
    def _unb64(s) -> bytes:
        import base64

        return base64.b64decode(s)

    def _call(self, path: str, body: dict) -> dict:
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            self.base + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        deadline = time.monotonic() + self.timeout
        while True:
            try:
                with urllib.request.urlopen(req, timeout=10) as r:
                    return json.loads(r.read().decode() or "{}")
            except urllib.error.HTTPError as e:
                if e.code >= 500:  # transient server side — retry
                    err: OSError = e
                else:
                    # 4xx is a real misconfiguration (auth, wrong gateway
                    # path, bad txn) — surface it, don't spin to "timeout"
                    raise RuntimeError(
                        f"etcd {self.base}{path} rejected the request: "
                        f"HTTP {e.code} {e.reason}") from e
            except OSError as e:
                err = e
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"etcd at {self.base} unreachable ({err})")
            time.sleep(0.5)

    @staticmethod
    def _prefix_end(prefix: bytes) -> bytes:
        """etcd range_end for a prefix scan: prefix with last byte + 1."""
        return prefix[:-1] + bytes([prefix[-1] + 1])

    def _put(self, key: str, value: str):
        self._call("/v3/kv/put", {"key": self._b64(key),
                                  "value": self._b64(value)})

    def _range_prefix(self, prefix: str) -> Dict[bytes, bytes]:
        p = prefix.encode()
        r = self._call("/v3/kv/range", {
            "key": self._b64(p), "range_end": self._b64(self._prefix_end(p))})
        return {self._unb64(kv["key"]): self._unb64(kv["value"])
                for kv in (r.get("kvs") or [])}

    def _delete_prefix(self, prefix: str):
        p = prefix.encode()
        self._call("/v3/kv/deleterange", {
            "key": self._b64(p), "range_end": self._b64(self._prefix_end(p))})

    def _txn_claim(self, key: str, value: str):
        """Atomic set-if-absent: a txn comparing ``create_revision == 0``
        puts the key iff it does not exist, else reads the current owner.
        Returns (claimed, current_value)."""
        k = self._b64(key)
        r = self._call("/v3/kv/txn", {
            "compare": [{"key": k, "target": "CREATE",
                         "result": "EQUAL", "create_revision": "0"}],
            "success": [{"request_put": {
                "key": k, "value": self._b64(value)}}],
            "failure": [{"request_range": {"key": k}}],
        })
        if r.get("succeeded"):
            return True, value.encode()
        rng = (r.get("responses") or [{}])[0].get("response_range", {})
        kvs = rng.get("kvs") or []
        return False, (self._unb64(kvs[0]["value"]) if kvs else b"")

    # -------------------------------------------------------------- contract
    def sync_peers(self, my_endpoint: str, job_id: str = "default",
                   node_id: str = None, preferred_slot: int = None
                   ) -> List[str]:
        """Reference ETCDMaster.sync_peers algorithm (master.py:190): every
        arriving node WIPES the job prefix first (clearing stale keys left
        by dead incarnations on the persistent external store), then
        repeatedly republishes its own key and polls until exactly
        ``nnodes`` keys exist — a self-healing barrier (a late joiner's
        wipe is repaired by every live node's republish loop). Keys are
        rank-numbered when ``preferred_slot`` pins the rank, else
        node-identity-named and ordered alphabetically (the reference's
        sorted-pod-name rule)."""
        me = node_id or my_endpoint
        prefix = f"peers/{job_id}/"
        pinned = preferred_slot is not None
        key = prefix + (f"r/{preferred_slot:08d}" if pinned
                        else f"n/{me}")
        owner_key = prefix + f"o/{preferred_slot:08d}" if pinned else None
        self._delete_prefix(prefix)
        deadline = time.monotonic() + self.timeout
        while time.monotonic() < deadline:
            if pinned:
                # txn-based slot claim: two nodes pinning the same rank is
                # a launch misconfiguration — the loser FAILS FAST instead
                # of hanging the barrier to timeout. Re-asserted each loop
                # because a late joiner's wipe clears claims too.
                ok, cur = self._txn_claim(owner_key, me)
                if not ok and cur != me.encode():
                    raise RuntimeError(
                        f"rendezvous: rank slot {preferred_slot} already "
                        f"claimed by {cur.decode()!r} (this node is "
                        f"{me!r}) — two launchers pinned the same --rank")
            self._put(key, my_endpoint)
            kvs = self._range_prefix(prefix)
            eps = {k: v for k, v in kvs.items()
                   if not k.startswith(prefix.encode() + b"o/")}
            kinds = {k[len(prefix):len(prefix) + 2] for k in eps}
            if len(kinds) > 1:
                raise RuntimeError(
                    "rendezvous: some launchers pinned --rank and some "
                    "did not — pinned (r/) and unpinned (n/) entries do "
                    "not order against each other; use --rank on all "
                    "nodes or none")
            if len(eps) == self.nnodes:
                return [v.decode() for _, v in sorted(eps.items())]
            time.sleep(0.5)
        raise TimeoutError(
            f"rendezvous: {self.nnodes} peers never assembled under "
            f"{prefix!r} within {self.timeout:.0f}s")

    def stop(self):
        pass  # the cluster's etcd outlives the job
