"""Crash-safe training checkpoints: complete state capture, CRC32
manifests, atomic commit, and a never-crash-the-step-loop degradation
ladder.

The serving stack earned its crash-safety story in PR 8 (seeded fault
injection + snapshot/restore, token-exact resume). This module is the
training half of the same contract, built on
:mod:`paddle_tpu.distributed.checkpoint` (orbax arrays + manifest/commit
primitives) and :mod:`paddle_tpu.faults` (scripted ``ckpt_write`` /
``ckpt_read`` / ``data_feed`` sites):

- **Complete state.** One :meth:`TrainCheckpointer.save` captures
  params + optimizer moments (via ``ParallelEngine.engine_state_dict``
  or eager ``model``/``optimizer`` state_dicts), the AMP loss-scaler
  (scale, growth/backoff counters), the LR-schedule state, the
  data-iterator cursor, the per-host RNG key, the step counter, and a
  config fingerprint. Anything less and "resume" silently forks the
  run; with all of it, a run killed at step k replays k+1..n with
  losses and final params **bit-exact** vs an unkilled twin.
- **Atomic commit.** Arrays and host state are staged under a dot
  directory, CRC32-manifested, then ``os.replace``d into place — a kill
  leaves the previous generation intact, never a torn dir.
- **Degradation ladder.** A failed write (torn file, full disk, or an
  injected ``ckpt_write`` fault) retries with backoff; past
  ``save_retries`` the save is dropped, counted, and the step loop
  continues against the last manifest-valid generation. A corrupt read
  (CRC mismatch, e.g. an injected on-disk bit flip at ``ckpt_read``) is
  detected before any state is trusted and restore falls back to the
  previous generation.
- **Async save.** The commit (orbax write + manifest + rename) rides a
  worker thread off the step path; capture (device→host gather) stays
  synchronous so the snapshot is a consistent step boundary.
- **Reshard-on-load.** SPMD engines restore through orbax with
  path-keyed target shardings (GSPMD reshards on load), so a checkpoint
  written on one mesh layout restores onto another.

Observability lands in a :class:`~paddle_tpu.telemetry.MetricsRegistry`
(``train_checkpoint_*`` counters, save-lag / last-step gauges) — the
same registry substrate serving uses. Passing a
:class:`~paddle_tpu.telemetry.TrainTelemetry` as ``telemetry=`` (to the
checkpointer AND the data feed) additionally lands ``ckpt_save`` /
``ckpt_restore`` / ``data_feed`` spans on the training timeline row and
feeds retry backoffs to the train watchdog's ``ckpt_backoff_storm``
detector.
"""
from __future__ import annotations

import os
import pickle
import shutil
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..faults import NULL_INJECTOR, DataFeedFault, FaultInjector
from .checkpoint import (load_state_dict, read_manifest, replace_dir,
                         save_state_dict, staging_path, sweep_stale_staging,
                         tree_path_key, verify_manifest, write_manifest)

__all__ = [
    "CheckpointCorruptError", "CheckpointableDataFeed", "TrainCheckpointer",
    "config_fingerprint",
]

_HOST_STATE = "host_state.pkl"
_ARRAYS_DIR = "arrays"


def _set_engine_step(engine, step: int) -> None:
    # mirrors ParallelEngine.set_engine_state's step placement: a host
    # int32 under multi-process (broadcast by the next dispatch), a
    # device scalar single-process
    import jax
    import jax.numpy as jnp

    engine._step_count = (np.asarray(step, np.int32)
                          if jax.process_count() > 1
                          else jnp.asarray(step, jnp.int32))


class CheckpointCorruptError(RuntimeError):
    """Every on-disk generation failed manifest verification — there is
    no valid state to resume from (distinct from "no checkpoint yet",
    which restores to a fresh start)."""


def config_fingerprint(config: Any) -> str:
    """Stable fingerprint of a run configuration (any json-able tree).
    Stored in every manifest; ``TrainCheckpointer(fingerprint=...)``
    refuses to restore state written under a different one — resuming a
    run with silently-changed hyperparameters is a fork, not a resume."""
    import json
    import zlib

    blob = json.dumps(config, sort_keys=True, default=str).encode()
    return f"{zlib.crc32(blob) & 0xFFFFFFFF:08x}"


class CheckpointableDataFeed:
    """Deterministic host data feed with an explicit cursor.

    ``make_batch(cursor)`` must be a pure function of its cursor (seeded
    synthesis, an index into a shuffled epoch permutation, a file
    offset...), which makes the cursor THE iterator state: checkpoint it
    and the resumed run re-reads the identical sample stream. The
    ``data_feed`` fault site fires before the cursor advances, so an
    injected feed hiccup is retried with no stream divergence.
    """

    def __init__(self, make_batch: Callable[[int], Any], *, cursor: int = 0,
                 injector: FaultInjector = NULL_INJECTOR,
                 telemetry=None):
        self.make_batch = make_batch
        self.cursor = int(cursor)
        self.injector = injector
        self.telemetry = telemetry

    def next_batch(self) -> Any:
        spec = self.injector.fire("data_feed")
        if spec is not None:
            raise DataFeedFault(
                f"injected data-feed fault at cursor {self.cursor}")
        tel = self.telemetry
        if tel is None:
            batch = self.make_batch(self.cursor)
        else:
            t0 = tel.clock()
            batch = self.make_batch(self.cursor)
            tel.record_data_feed(t0, tel.clock(), cursor=self.cursor)
        self.cursor += 1
        return batch

    def state(self) -> Dict[str, int]:
        return {"cursor": self.cursor}

    def load_state(self, state: Dict[str, int]) -> None:
        self.cursor = int(state["cursor"])


class TrainCheckpointer:
    """Complete-state training checkpoints with atomic commit, CRC32
    verification, bounded-retry save, generation fallback on corrupt
    read, and optional async commit. See the module docstring for the
    contract; ``tests/test_train_checkpoint.py`` pins bit-exact resume.
    """

    def __init__(self, save_dir: str, *, keep_last: int = 3,
                 async_save: bool = False,
                 injector: FaultInjector = NULL_INJECTOR,
                 metrics=None, clock: Callable[[], float] = time.monotonic,
                 save_retries: int = 2, backoff_s: float = 0.02,
                 fingerprint: Optional[str] = None,
                 telemetry=None):
        self.save_dir = save_dir
        self.keep_last = int(keep_last)
        self.async_save = async_save
        self.injector = injector
        self.save_retries = int(save_retries)
        self.backoff_s = float(backoff_s)
        self.fingerprint = fingerprint
        self._clock = clock
        self.telemetry = telemetry
        if metrics is None and telemetry is not None:
            metrics = telemetry.registry
        self._registry = metrics
        self._inflight: Optional[threading.Thread] = None
        self.last_error: Optional[str] = None
        os.makedirs(save_dir, exist_ok=True)
        sweep_stale_staging(save_dir)

    # ------------------------------------------------------------- metrics
    @property
    def metrics(self):
        if self._registry is None:
            # lazy: telemetry is a leaf module (numpy/json only), shared
            # with serving so dashboards read one substrate
            from ..telemetry import MetricsRegistry

            self._registry = MetricsRegistry(clock=self._clock)
        return self._registry

    def _count(self, name: str, help: str, n: float = 1.0) -> None:
        self.metrics.counter("train_checkpoint_" + name, help).inc(n)

    def _gauge(self, name: str, help: str, v: float) -> None:
        self.metrics.gauge("train_checkpoint_" + name, help).set(v)

    # ------------------------------------------------------------- capture
    def _capture(self, step, engine, model, optimizer, scaler, data_feed,
                 extra) -> Tuple[dict, dict]:
        """Host snapshot of the full training state at a step boundary.
        Synchronous on purpose: capture must see a consistent state even
        when the commit itself rides the async thread."""
        from ..framework.random import get_rng_state
        from ..optimizer.lr import LRScheduler

        arrays: Dict[str, Any] = {}
        host: Dict[str, Any] = {
            "step": int(step),
            "fingerprint": self.fingerprint,
            "extra": extra or {},
            "rng": np.asarray(get_rng_state()),
        }
        opt = optimizer
        if engine is not None:
            eng_state = engine.engine_state_dict()
            arrays["params"] = eng_state["params"]
            arrays["opt_state"] = eng_state["opt_state"]
            host["engine_step"] = int(eng_state["step"])
            opt = opt or engine.optimizer
        elif model is not None:
            arrays["model"] = {k: v for k, v in model.state_dict().items()}
        if opt is not None and engine is None:
            osd = opt.state_dict()
            host["opt_host"] = {
                "global_step": int(osd.pop("global_step", 0))}
            host["lr_sched"] = osd.pop("LR_Scheduler", None)
            arrays["opt_state"] = osd
        elif opt is not None:
            lr = getattr(opt, "_learning_rate", None)
            if isinstance(lr, LRScheduler):
                host["lr_sched"] = lr.state_dict()
        if scaler is not None:
            host["scaler"] = scaler.state_dict()
        if data_feed is not None:
            host["data_feed"] = data_feed.state()
        return arrays, host

    # -------------------------------------------------------------- commit
    def _write_generation(self, arrays: dict, host: dict, final: str,
                          step: int) -> None:
        """One staged write attempt: arrays (orbax) + host pickle +
        manifest, then the atomic rename. The ``ckpt_write`` fault fires
        after the payload is staged but before the manifest — exactly
        where a real kill tears a write."""
        tmp = staging_path(final)
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        if arrays:
            save_state_dict(arrays, os.path.join(tmp, _ARRAYS_DIR))
        blob = pickle.dumps(host, protocol=4)
        with open(os.path.join(tmp, _HOST_STATE), "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        spec = self.injector.fire("ckpt_write")
        if spec is not None:
            # torn write: truncate one staged file mid-payload, then die
            # before the manifest — the ladder must retry or fall back,
            # and no reader may ever trust this staging dir
            victim = os.path.join(tmp, _HOST_STATE)
            size = os.path.getsize(victim)
            with open(victim, "r+b") as f:
                f.truncate(max(0, size // 2))
            raise OSError(f"injected torn checkpoint write ({spec.kind or 'torn'})")
        write_manifest(tmp, step=step, fingerprint=self.fingerprint)
        replace_dir(tmp, final)

    def _commit(self, arrays: dict, host: dict, final: str, step: int,
                t_request: float) -> bool:
        """Degradation ladder, rung 1: bounded retry with backoff. A save
        that still fails is DROPPED (counted, never raised) — the step
        loop must not crash because the filesystem hiccuped; the last
        manifest-valid generation stays the resume point."""
        for attempt in range(self.save_retries + 1):
            try:
                self._write_generation(arrays, host, final, step)
                break
            except (OSError, ValueError) as e:
                self.last_error = f"{type(e).__name__}: {e}"
                if attempt == self.save_retries:
                    shutil.rmtree(staging_path(final), ignore_errors=True)
                    self._count("save_failures",
                                "saves dropped after exhausting retries")
                    return False
                self._count("save_retries", "torn-write retry attempts")
                if self.telemetry is not None:
                    self.telemetry.note_ckpt_backoff(step=step)
                time.sleep(self.backoff_s * (2 ** attempt))
        self._count("saves", "generations committed")
        self._gauge("last_step", "step of the newest committed generation",
                    step)
        self._gauge("save_lag_s",
                    "request-to-durable latency of the last commit",
                    self._clock() - t_request)
        self._prune()
        return True

    def _prune(self) -> None:
        gens = self.generations()
        for _step, path in gens[:-self.keep_last] if self.keep_last else []:
            shutil.rmtree(path, ignore_errors=True)
        self._gauge("generations", "committed generations on disk",
                    len(self.generations()))

    # ---------------------------------------------------------------- save
    def save(self, step: int, *, engine=None, model=None, optimizer=None,
             scaler=None, data_feed=None, extra: Optional[dict] = None
             ) -> Optional[str]:
        """Capture + commit one generation for ``step``. Returns the
        final path (the commit may still be in flight with
        ``async_save=True`` — ``wait()`` joins it), or ``None`` if a
        synchronous commit was dropped by the ladder."""
        tel = self.telemetry
        t_span = tel.clock() if tel is not None else 0.0
        t_request = self._clock()
        self.wait()
        arrays, host = self._capture(step, engine, model, optimizer, scaler,
                                     data_feed, extra)
        final = os.path.join(self.save_dir, f"step_{int(step):08d}")
        if self.async_save:
            self._inflight = threading.Thread(
                target=self._commit,
                args=(arrays, host, final, int(step), t_request),
                daemon=True)
            self._inflight.start()
            if tel is not None:
                # the span covers the step-path cost only: capture +
                # thread handoff; the commit rides the worker thread
                tel.record_ckpt("ckpt_save", t_span, tel.clock(),
                                step=int(step), mode="async")
            return final
        ok = self._commit(arrays, host, final, int(step), t_request)
        if tel is not None:
            tel.record_ckpt("ckpt_save", t_span, tel.clock(),
                            step=int(step), dropped=not ok)
        return final if ok else None

    def wait(self) -> None:
        """Join any in-flight async commit."""
        if self._inflight is not None:
            self._inflight.join()
            self._inflight = None

    # ------------------------------------------------------------- listing
    def generations(self) -> List[Tuple[int, str]]:
        """Committed generations, oldest first (no validity check)."""
        out = []
        if not os.path.isdir(self.save_dir):
            return out
        for d in os.listdir(self.save_dir):
            if d.startswith("step_") and not d.startswith("."):
                try:
                    out.append((int(d.split("_")[1]),
                                os.path.join(self.save_dir, d)))
                except ValueError:
                    pass
        return sorted(out)

    def latest_valid(self) -> Optional[Tuple[int, str]]:
        """Newest generation that passes CRC verification; corrupt ones
        are counted and skipped (degradation ladder, rung 2)."""
        for step, path in reversed(self.generations()):
            spec = self.injector.fire("ckpt_read")
            if spec is not None:
                self._corrupt_on_disk(path)
            problems = verify_manifest(path)
            if not problems:
                return step, path
            self.last_error = f"{path}: {problems[0]}"
            self._count("corrupt_reads",
                        "generations failing CRC verification")
            self._count("generation_fallbacks",
                        "restores skipping past a corrupt generation")
        return None

    def _corrupt_on_disk(self, path: str) -> None:
        """Apply an injected ``ckpt_read`` fault: flip one seeded bit of
        the first manifest-listed shard, in place — the manifest must
        catch it."""
        manifest = read_manifest(path)
        if not manifest:
            return
        for rel in sorted(manifest.get("files", {})):
            full = os.path.join(path, rel)
            if os.path.isfile(full) and os.path.getsize(full) > 0:
                self.injector.corrupt_file(full)
                return

    # ------------------------------------------------------------- restore
    def restore(self, *, engine=None, model=None, optimizer=None,
                scaler=None, data_feed=None) -> Optional[Dict[str, Any]]:
        """Restore the newest valid generation into the given consumers.

        Walks generations newest→oldest past corrupt ones; returns the
        restored host-state dict (``["step"]`` is the resume step), or
        ``None`` when no generation exists (fresh start). Raises
        :class:`CheckpointCorruptError` if generations exist but none
        verifies, and ``ValueError`` on a config-fingerprint mismatch.
        """
        from ..framework.random import set_rng_state
        from ..optimizer.lr import LRScheduler

        tel = self.telemetry
        t_span = tel.clock() if tel is not None else 0.0
        self.wait()
        had_any = bool(self.generations())
        found = self.latest_valid()
        if found is None:
            if had_any:
                raise CheckpointCorruptError(
                    f"no manifest-valid generation under {self.save_dir} "
                    f"(last error: {self.last_error})")
            if tel is not None:
                tel.record_ckpt("ckpt_restore", t_span, tel.clock(),
                                outcome="fresh_start")
            return None
        step, path = found
        manifest = read_manifest(path) or {}
        if self.fingerprint is not None and \
                manifest.get("fingerprint") not in (None, self.fingerprint):
            raise ValueError(
                f"config fingerprint mismatch: checkpoint {path} was "
                f"written under {manifest.get('fingerprint')!r}, this run "
                f"is {self.fingerprint!r} — refusing to resume a forked "
                f"config")
        with open(os.path.join(path, _HOST_STATE), "rb") as f:
            host = pickle.load(f)
        arrays_path = os.path.join(path, _ARRAYS_DIR)
        has_arrays = os.path.isdir(arrays_path)
        opt = optimizer
        if engine is not None:
            opt = opt or engine.optimizer
        if engine is not None and has_arrays:
            if engine._spmd:
                # GSPMD reshard-on-load: path-keyed shardings from THIS
                # engine's layout — the checkpoint may have been written
                # on a different mesh; orbax reshards each array on load
                target = {"params": dict(engine.params),
                          "opt_state": engine.opt_state}
                shardings = {}
                for n, v in engine.params.items():
                    shardings[f"params/{n}"] = v.sharding
                for n, slots in engine.opt_state.items():
                    for k, v in slots.items():
                        shardings[f"opt_state/{n}/{k}"] = v.sharding
                restored = load_state_dict(arrays_path, target=target,
                                           shardings=shardings)
                unwrap = lambda t: t.value if hasattr(t, "value") else t
                engine.params = {n: unwrap(v)
                                 for n, v in restored["params"].items()}
                engine.opt_state = {
                    n: {k: unwrap(v) for k, v in slots.items()}
                    for n, slots in restored["opt_state"].items()}
                _set_engine_step(engine,
                                 host.get("engine_step", host["step"]))
            else:
                restored = load_state_dict(arrays_path)
                # restore IS the deliberate host boundary: set_engine_state
                # re-places host values against this engine's layout
                unwrap = lambda t: np.asarray(  # graftlint: noqa[host-sync]
                    t.value if hasattr(t, "value") else t)
                engine.set_engine_state({
                    "params": {n: unwrap(v)
                               for n, v in restored["params"].items()},
                    "opt_state": {
                        n: {k: unwrap(v) for k, v in slots.items()}
                        for n, slots in restored["opt_state"].items()},
                    "step": host.get("engine_step", host["step"])})
        elif model is not None and has_arrays:
            restored = load_state_dict(arrays_path)
            if "model" in restored:
                model.set_state_dict(restored["model"])
            if opt is not None and "opt_state" in restored:
                sd = dict(restored["opt_state"])
                sd["global_step"] = host.get("opt_host", {}).get(
                    "global_step", 0)
                if host.get("lr_sched") is not None:
                    sd["LR_Scheduler"] = host["lr_sched"]
                opt.set_state_dict(sd)
        if opt is not None and host.get("lr_sched") is not None:
            lr = getattr(opt, "_learning_rate", None)
            if isinstance(lr, LRScheduler):
                lr.set_state_dict(host["lr_sched"])
        if scaler is not None and host.get("scaler") is not None:
            scaler.load_state_dict(host["scaler"])
        if data_feed is not None and host.get("data_feed") is not None:
            data_feed.load_state(host["data_feed"])
        if host.get("rng") is not None:
            set_rng_state(host["rng"])
        self._count("restores", "successful restores")
        if tel is not None:
            tel.record_ckpt("ckpt_restore", t_span, tel.clock(),
                            step=int(host["step"]))
        return host
