"""paddle.distributed.spawn parity (ref: python/paddle/distributed/spawn.py).

On TPU, one process drives all local chips, so spawn(nprocs=N) for local
multi-chip is an anti-pattern; it exists for multi-host simulation in tests
(CPU backend) and API parity.
"""
from __future__ import annotations

import multiprocessing as mp
import os
from typing import Callable


def _worker(fn, rank, nprocs, args, env):
    os.environ.update(env)
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    fn(*args)


def spawn(func: Callable, args=(), nprocs=1, join=True, daemon=False, **options):
    if nprocs == 1:
        func(*args)
        return None
    ctx = mp.get_context("spawn")
    procs = []
    env = dict(os.environ)
    for rank in range(nprocs):
        p = ctx.Process(target=_worker, args=(func, rank, nprocs, args, env), daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError(f"spawned process exited with code {p.exitcode}")
    return procs
