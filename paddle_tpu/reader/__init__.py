"""paddle.reader parity (ref: python/paddle/reader/__init__.py)."""
from .decorator import (  # noqa: F401
    ComposeNotAligned, buffered, cache, chain, compose, firstn, map_readers,
    multiprocess_reader, shuffle, xmap_readers,
)

__all__ = []
