"""Reader decorators (ref: python/paddle/reader/decorator.py).

A "reader creator" is a no-arg callable returning a generator of samples.
These combinators wrap reader creators; they are host-side data plumbing and
deliberately stay off-device (feeding happens at the DataLoader boundary).
"""
from __future__ import annotations

import itertools
import multiprocessing
import queue as _queue
import random as _random
import threading

__all__ = [
    "cache", "map_readers", "buffered", "compose", "chain", "shuffle",
    "firstn", "xmap_readers", "multiprocess_reader",
]


def cache(reader):
    """Cache the reader's full output in memory on first pass (ref decorator.py:45)."""
    all_data = tuple(reader())

    def __impl__():
        for item in all_data:
            yield item

    return __impl__


def map_readers(func, *readers):
    """Yield func applied across the zipped outputs of ``readers`` (ref :85)."""

    def reader():
        rs = [r() for r in readers]
        for e in map(func, *rs):
            yield e

    return reader


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer of ``buf_size`` samples (ref :127)."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            _random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """Concatenate readers sequentially (ref :176)."""

    def reader():
        rs = [r() for r in readers]
        for e in itertools.chain(*rs):
            yield e

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """Zip readers into flattened tuples per sample (ref :241).

    check_alignment=True (default) raises ComposeNotAligned when the readers
    run out at different lengths.
    """
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                for o in outputs:
                    if o is None:
                        raise ComposeNotAligned(
                            "outputs of readers are not aligned.")
                yield sum(list(map(make_tuple, outputs)), ())

    return reader


def buffered(reader, size):
    """Prefetch up to ``size`` samples on a background thread (ref :299)."""

    class EndSignal:
        pass

    end = EndSignal()

    def read_worker(r, q):
        for d in r:
            q.put(d)
        q.put(end)

    def data_reader():
        r = reader()
        q = _queue.Queue(maxsize=size)
        t = threading.Thread(target=read_worker, args=(r, q))
        t.daemon = True
        t.start()
        e = q.get()
        while e is not end:
            yield e
            e = q.get()

    return data_reader


def firstn(reader, n):
    """Limit the reader to its first ``n`` samples (ref :361)."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


class XmapEndSignal:
    pass


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Apply ``mapper`` over the reader with ``process_num`` worker threads
    (ref :406). With order=True output order matches input order."""
    end = XmapEndSignal()

    def read_worker(r, q):
        for i in r():
            q.put(i)
        q.put(end)

    def order_read_worker(r, q):
        for i, d in enumerate(r()):
            q.put((i, d))
        q.put(end)

    def handle_worker(in_q, out_q, m):
        sample = in_q.get()
        while not isinstance(sample, XmapEndSignal):
            out_q.put(m(sample))
            sample = in_q.get()
        in_q.put(end)
        out_q.put(end)

    def order_handle_worker(in_q, out_q, m, order_holder):
        ins = in_q.get()
        while not isinstance(ins, XmapEndSignal):
            order_id, sample = ins
            r = m(sample)
            while order_id != order_holder[0]:
                pass
            out_q.put(r)
            order_holder[0] += 1
            ins = in_q.get()
        in_q.put(end)
        out_q.put(end)

    def xreader():
        # fresh queues/order counter per call — the reader must be re-iterable
        # across epochs (ref decorator.py xreader creates them per invocation)
        in_queue = _queue.Queue(buffer_size)
        out_queue = _queue.Queue(buffer_size)
        out_order = [0]
        target = order_read_worker if order else read_worker
        t = threading.Thread(target=target, args=(reader, in_queue))
        t.daemon = True
        t.start()
        target = order_handle_worker if order else handle_worker
        args = (in_queue, out_queue, mapper, out_order) if order else \
            (in_queue, out_queue, mapper)
        workers = []
        for _ in range(process_num):
            w = threading.Thread(target=target, args=args)
            w.daemon = True
            w.start()
            workers.append(w)

        finish = 0
        while finish < process_num:
            sample = out_queue.get()
            if isinstance(sample, XmapEndSignal):
                finish += 1
            else:
                yield sample

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Fan-in multiple readers through worker processes (ref :502).

    Samples from all readers are interleaved; each reader runs in its own
    process, results travel back over a multiprocessing queue.
    """
    if len(readers) < 1:
        raise ValueError("multiprocess_reader needs at least one reader")

    def _read_into_queue(r, q):
        try:
            for sample in r():
                if sample is None:
                    raise ValueError("sample has None")
                q.put(sample)
            q.put(None)
        except Exception:
            q.put("")
            raise

    def queue_reader():
        q = multiprocessing.Queue(queue_size)
        workers = []
        for r in readers:
            p = multiprocessing.Process(target=_read_into_queue, args=(r, q))
            p.daemon = True
            p.start()
            workers.append(p)

        finish_num = 0
        while finish_num < len(readers):
            sample = q.get()
            if sample is None:
                finish_num += 1
            elif sample == "":
                raise ValueError("multiprocess reader raises an exception")
            else:
                yield sample

    return queue_reader
