"""Profiler (ref: python/paddle/profiler/profiler.py:344 Profiler,
ProfilerState:79, timer.py benchmark).

TPU-native: device-side tracing delegates to jax.profiler (XLA/TPU trace →
TensorBoard); host-side RecordEvent spans are kept in-process and dumped as
chrome-trace JSON (ref chrometracing_logger.cc) so the runtime layers we own
are observable without TensorBoard.
"""
from __future__ import annotations

import contextlib
import enum
import json
import os
import threading
import time
from typing import Callable, List, Optional


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 2
    TPU = 3


class _HostEventRecorder:
    """Thread-local host event store (ref host_event_recorder.h)."""

    def __init__(self):
        self._events: List[dict] = []
        self._lock = threading.Lock()
        self.enabled = False

    def add(self, name: str, ts: float, dur: float, cat: str = "op",
            tid: Optional[int] = None, args: Optional[dict] = None):
        if not self.enabled:
            return
        ev = {
            "name": name, "ph": "X", "ts": ts * 1e6, "dur": dur * 1e6,
            "pid": os.getpid(),
            "tid": threading.get_ident() if tid is None else tid, "cat": cat,
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def drain(self) -> List[dict]:
        with self._lock:
            ev, self._events = self._events, []
            return ev


_recorder = _HostEventRecorder()


class RecordEvent:
    """RAII span (ref platform/profiler RecordEvent; usable as ctx or decorator)."""

    def __init__(self, name: str, event_type=None):
        self.name = name
        self._start = None

    def begin(self):
        self._start = time.perf_counter()

    def end(self):
        if self._start is not None:
            _recorder.add(self.name, self._start, time.perf_counter() - self._start)
            self._start = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Ref profiler.py make_scheduler."""

    def scheduler(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        cycle = closed + ready + record
        if repeat > 0 and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name: str, worker_name: Optional[str] = None):
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        fname = os.path.join(
            dir_name, f"{worker_name or 'worker'}_{int(time.time())}.pt.trace.json")
        with open(fname, "w") as f:
            json.dump({"traceEvents": prof._events}, f)
        return fname

    return handler


class Profiler:
    """Ref profiler.py:344."""

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None, with_flops=False):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(closed=0, ready=0, record=scheduler[1] - scheduler[0],
                           skip_first=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else (lambda _: ProfilerState.RECORD))
        self._on_trace_ready = on_trace_ready
        self._step = 0
        self._events: List[dict] = []
        self._state = ProfilerState.CLOSED
        self._timer_only = timer_only
        self._jax_tracing = False
        self._trace_dir = None

    def start(self):
        self._state = self._scheduler(self._step)
        _recorder.enabled = self._state in (ProfilerState.RECORD,
                                            ProfilerState.RECORD_AND_RETURN)
        if _recorder.enabled and not self._timer_only:
            self._maybe_start_jax_trace()

    def _maybe_start_jax_trace(self):
        from ..framework.flags import GLOBAL_FLAGS

        trace_dir = GLOBAL_FLAGS.get("profiler_trace_dir")
        if trace_dir:
            try:
                import jax

                jax.profiler.start_trace(trace_dir)
                self._jax_tracing = True
                self._trace_dir = trace_dir
            except Exception:
                self._jax_tracing = False

    def step(self, num_samples=None):
        self._step += 1
        new_state = self._scheduler(self._step)
        if new_state != self._state:
            if self._state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
                self._events.extend(_recorder.drain())
                if self._state == ProfilerState.RECORD_AND_RETURN and self._on_trace_ready:
                    self._on_trace_ready(self)
            self._state = new_state
            _recorder.enabled = new_state in (ProfilerState.RECORD,
                                              ProfilerState.RECORD_AND_RETURN)

    def stop(self):
        self._events.extend(_recorder.drain())
        _recorder.enabled = False
        if self._jax_tracing:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:
                pass
            self._jax_tracing = False
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def export(self, path: str, format: str = "json"):
        with open(path, "w") as f:
            json.dump({"traceEvents": self._events}, f)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        """Op-summary table (ref profiler_statistic.py)."""
        agg = {}
        for e in self._events:
            a = agg.setdefault(e["name"], {"calls": 0, "total": 0.0})
            a["calls"] += 1
            a["total"] += e["dur"] / 1e3  # ms
        lines = [f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>12}"]
        for name, a in sorted(agg.items(), key=lambda kv: -kv[1]["total"]):
            lines.append(f"{name:<40}{a['calls']:>8}{a['total']:>12.3f}"
                         f"{a['total'] / a['calls']:>12.3f}")
        out = "\n".join(lines)
        print(out)
        return out


class Timer:
    """Throughput meter (ref profiler/timer.py benchmark())."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._start = None
        self._samples = 0
        self._elapsed = 0.0
        self._reader_elapsed = 0.0

    def begin(self):
        self._start = time.perf_counter()

    def end(self, num_samples=1):
        if self._start is not None:
            self._elapsed += time.perf_counter() - self._start
            self._samples += num_samples
            self._start = None

    def ips(self):
        return self._samples / self._elapsed if self._elapsed > 0 else 0.0


def benchmark():
    return Timer()


@contextlib.contextmanager
def trace(name: str):
    """jax.profiler.TraceAnnotation + host RecordEvent in one."""
    import jax

    with jax.profiler.TraceAnnotation(name), RecordEvent(name):
        yield


def load_profiler_result(filename: str):
    with open(filename) as f:
        return json.load(f)
