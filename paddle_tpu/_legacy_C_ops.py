"""paddle._legacy_C_ops — alias of _C_ops (ref python/paddle/_legacy_C_ops.py
re-exports core.ops legacy generated functions; our dispatch has a single
generation, so the two namespaces are identical)."""
from ._C_ops import *  # noqa: F401,F403
from . import _C_ops as _c


def __getattr__(name):
    return getattr(_c, name)
