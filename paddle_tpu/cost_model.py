"""Op cost model (ref: python/paddle/cost_model/ backed by
static_op_benchmark.json).

TPU-native: costs come from XLA's own analysis (jitted computation
cost_analysis), not a benchmark table — exact for the target chip.
"""
from __future__ import annotations

from typing import Any, Callable, Dict

import jax


class CostModel:
    def profile_measure(self, fn: Callable, *example_args, device="tpu",
                        fetch_cost_list=("time",)) -> Dict[str, Any]:
        lowered = jax.jit(fn).lower(*example_args)
        compiled = lowered.compile()
        try:
            analysis = compiled.cost_analysis()
            if isinstance(analysis, list):
                analysis = analysis[0]
        except Exception:
            analysis = {}
        return {
            "flops": analysis.get("flops", 0.0),
            "bytes accessed": analysis.get("bytes accessed", 0.0),
            "time": analysis.get("optimal_seconds", 0.0),
            "analysis": dict(analysis),
        }

    def static_cost_data(self):
        return {}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """paddle.flops parity (ref hapi/dynamic_flops.py) via XLA cost analysis."""
    import numpy as np

    from .framework.core import Tensor
    from .jit import functional_call, state_values

    params = state_values(net)
    x = Tensor(np.zeros(input_size, np.float32))

    def fn(p, v):
        out = functional_call(net, p, Tensor(v))
        return out.value if isinstance(out, Tensor) else out

    cm = CostModel()
    res = cm.profile_measure(fn, params, x.value)
    total = res["flops"]
    if print_detail:
        print(f"Total FLOPs: {total:,.0f}")
    return int(total)
