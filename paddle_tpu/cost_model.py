"""Op cost model (ref: python/paddle/cost_model/ backed by
static_op_benchmark.json).

TPU-native: costs come from XLA's own analysis (jitted computation
cost_analysis), not a benchmark table — exact for the target chip.

Two layers live here:

- :class:`CostModel` — per-op costs straight from XLA ``cost_analysis``
  on a lowered computation (exact, but only for one jitted program).
- :class:`PagedTickCostModel` — an analytic *serving* predictor: what a
  paged decode tick costs as a function of batch width, context blocks,
  and model size, with four scalar coefficients (host round-trip, fixed
  tick overhead, per-FLOP, per-byte) that start at documented priors and
  are calibrated online from measured autotune trials
  (``paddle_tpu/autotune/cost.py`` drives the calibration loop).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Mapping, Optional, Sequence

import jax


class CostModel:
    def profile_measure(self, fn: Callable, *example_args, device="tpu",
                        fetch_cost_list=("time",)) -> Dict[str, Any]:
        lowered = jax.jit(fn).lower(*example_args)
        compiled = lowered.compile()
        try:
            analysis = compiled.cost_analysis()
            if isinstance(analysis, list):
                analysis = analysis[0]
        except Exception:
            analysis = {}
        return {
            "flops": analysis.get("flops", 0.0),
            "bytes accessed": analysis.get("bytes accessed", 0.0),
            "time": analysis.get("optimal_seconds", 0.0),
            "analysis": dict(analysis),
        }

    def static_cost_data(self):
        return {}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """paddle.flops parity (ref hapi/dynamic_flops.py) via XLA cost analysis."""
    import numpy as np

    from .framework.core import Tensor
    from .jit import functional_call, state_values

    params = state_values(net)
    x = Tensor(np.zeros(input_size, np.float32))

    def fn(p, v):
        out = functional_call(net, p, Tensor(v))
        return out.value if isinstance(out, Tensor) else out

    cm = CostModel()
    res = cm.profile_measure(fn, params, x.value)
    total = res["flops"]
    if print_detail:
        print(f"Total FLOPs: {total:,.0f}")
    return int(total)


# --------------------------------------------------------------------------
# Analytic paged-tick serving cost model
# --------------------------------------------------------------------------

#: Reference shape the priors are anchored to — the suite's smallest
#: serving stand-in (~360k params, 8 decoding sequences, ~4 resident KV
#: blocks each at block_size=16/f32). PR 3 measured the speculative
#: break-even at these shapes as ≈ k/2 accepted drafts per verify window
#: (gate_low = 2.0 at k = 4); the flop prior below is derived so the
#: uncalibrated model reproduces that measurement exactly. Calibration
#: from real trials then overrides all four coefficients.
REF_N_PARAMS = 360_000
REF_DECODING = 8
REF_CTX_BLOCKS = 4.0
REF_BLOCK_BYTES = 16_384

C_TRIP_PRIOR = 2e-3    # seconds per host<->device round trip
C_TICK_PRIOR = 4e-4    # fixed seconds per fused decode tick
C_BYTE_PRIOR = 1e-10   # seconds per HBM byte moved (~10 GB/s effective)

_REF_FLOPS = 2.0 * REF_N_PARAMS * REF_DECODING            # width = 1
_REF_BYTES = 4 * REF_N_PARAMS + REF_DECODING * REF_CTX_BLOCKS * REF_BLOCK_BYTES
# chosen so compute and (overhead + bytes) balance at the reference
# shape: tick(width=k+1)/tick(width=1) - 1 == k/2, i.e. break-even 2.0
# at k=4 — the PR 3 gate threshold.
C_FLOP_PRIOR = (C_TICK_PRIOR + C_BYTE_PRIOR * _REF_BYTES) / _REF_FLOPS


@dataclasses.dataclass(frozen=True)
class TickShape:
    """What one fused decode tick looks like, in cost-relevant terms.

    ``width`` is tokens advanced per sequence per tick — 1 for plain
    decode, ``k + 1`` for a speculative verify window. KV-read bytes do
    not scale with width (the verify reads the same resident context the
    plain tick does); compute does.
    """

    decoding: int                       # sequences in decode this tick
    width: int = 1
    n_params: int = REF_N_PARAMS
    ctx_blocks: float = REF_CTX_BLOCKS  # mean resident KV blocks per seq
    block_bytes: int = REF_BLOCK_BYTES  # kv_block_bytes(cfg, bs, kv_quant)
    param_bytes: Optional[int] = None   # None = 4 bytes/param

    def flops(self) -> float:
        return 2.0 * self.n_params * self.decoding * self.width

    def hbm_bytes(self) -> float:
        pb = 4 * self.n_params if self.param_bytes is None \
            else self.param_bytes
        return float(pb) + self.decoding * self.ctx_blocks * self.block_bytes


class PagedTickCostModel:
    """``trip_seconds = c_trip + ticks * (c_tick + c_flop*flops +
    c_byte*bytes)`` — four coefficients, analytic features from
    :class:`TickShape`, priors anchored at the reference shape above and
    refined by :meth:`calibrate` from measured trials."""

    def __init__(self, c_trip: float = C_TRIP_PRIOR,
                 c_tick: float = C_TICK_PRIOR,
                 c_flop: float = C_FLOP_PRIOR,
                 c_byte: float = C_BYTE_PRIOR):
        self.c_trip = float(c_trip)
        self.c_tick = float(c_tick)
        self.c_flop = float(c_flop)
        self.c_byte = float(c_byte)

    # ------------------------------------------------------------ predict
    def tick_seconds(self, shape: TickShape) -> float:
        return (self.c_tick + self.c_flop * shape.flops()
                + self.c_byte * shape.hbm_bytes())

    def trip_seconds(self, shape: TickShape, ticks: int) -> float:
        """One host round trip running ``ticks`` fused ticks of this
        shape (``ticks`` = tick_window in steady-state decode)."""
        return self.c_trip + ticks * self.tick_seconds(shape)

    def predict(self, trips: float, ticks: float, flops: float,
                bytes_: float) -> float:
        """Seconds for aggregate trial totals (the calibration view)."""
        return (self.c_trip * trips + self.c_tick * ticks
                + self.c_flop * flops + self.c_byte * bytes_)

    def spec_break_even(self, k: int, shape: TickShape) -> float:
        """Accepted drafts per verify window where speculation pays:
        ``verify_window_cost / plain_tick_cost - 1``. At the reference
        shape this is k/2 — 2.0 for k = 4, the PR 3 ``gate_low``."""
        plain = self.tick_seconds(dataclasses.replace(shape, width=1))
        verify = self.tick_seconds(dataclasses.replace(shape, width=k + 1))
        return verify / plain - 1.0

    # ---------------------------------------------------------- calibrate
    def calibrate(self, trials: Sequence[Mapping[str, float]],
                  ridge: float = 1e-3) -> "PagedTickCostModel":
        """Fit the four coefficients to measured trials, regularized
        toward the current coefficients so a couple of trials refine the
        prior along measured directions without destroying it elsewhere.

        Each trial is a mapping with aggregate totals ``trips``,
        ``ticks``, ``flops``, ``bytes`` and the measured wall
        ``seconds``. Solved in prior-normalized coordinates (coefficient
        magnitudes span seven decades) as a ridge least-squares; returns
        a new model, never mutates."""
        import numpy as np

        if not trials:
            return PagedTickCostModel(self.c_trip, self.c_tick,
                                      self.c_flop, self.c_byte)
        prior = np.array([self.c_trip, self.c_tick,
                          self.c_flop, self.c_byte], dtype=np.float64)
        X = np.array([[t["trips"], t["ticks"], t["flops"], t["bytes"]]
                      for t in trials], dtype=np.float64)
        y = np.array([t["seconds"] for t in trials], dtype=np.float64)
        # u = c / prior, so the penalty ||u - 1|| is scale-free
        Xn = X * prior[None, :]
        G = Xn.T @ Xn
        lam = ridge * (np.trace(G) / 4.0 + 1e-30)
        u = np.linalg.solve(G + lam * np.eye(4),
                            Xn.T @ y + lam * np.ones(4))
        c = np.maximum(u, 0.0) * prior
        return PagedTickCostModel(*c.tolist())  # graftlint: noqa[host-sync]

    # -------------------------------------------------------------- (de)ser
    def to_dict(self) -> Dict[str, float]:
        return {"c_trip": self.c_trip, "c_tick": self.c_tick,
                "c_flop": self.c_flop, "c_byte": self.c_byte}

    @classmethod
    def from_dict(cls, d: Mapping[str, float]) -> "PagedTickCostModel":
        return cls(**{k: float(d[k])
                      for k in ("c_trip", "c_tick", "c_flop", "c_byte")})
