"""Discrete-event fleet simulation + real-fleet slice replay.

:class:`FleetSimulation` runs a drawn :class:`~.traffic.SessionTrace`
— typically a million-session day — against an *analytic* replica
service model in virtual time: arrivals route with the same
preferences the real router has (prefix-population affinity, then
load), sessions occupy slots for a prefill+decode service time derived
from the PR 14 cost model, queueing and TTFT/TPOT fall out of the
event order, and the PR 20 :class:`~..inference.autoscale.ElasticAutoscaler`
runs a control tick on a fixed cadence exactly as a live control loop
would (observed windowed demand + the diurnal forecast + windowed SLO
burn). One million arrivals complete in well under CI budget because
each event is a few dict operations — no engine, no tensors.

Why analytic? A day of real engine traffic is ~10^9 model steps; no CI
runs that. The split mirrors the autotuner's: the *model* explores the
big space (here: a whole day of elasticity), and a *measured slice*
anchors it — :func:`replay_slice` materializes the first N sessions of
the SAME trace into real prompts and pushes them through a real
:class:`~..inference.fleet.FleetRouter` (in-process or subprocess
replicas) in fast-time, where token-exactness, drains and kills are
checked against an undisturbed twin (suite stage 7l).

Everything is a pure function of (trace, model, policy): no wall
clock, no sleeps (GL015), no unseeded randomness — two runs at one
seed emit byte-identical reports (floats rounded once, at the edge).
"""
from __future__ import annotations

import heapq
import math
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ..inference.autoscale import ElasticAutoscaler
from ..inference.fleet import DEFAULT_SLO
from .traffic import SessionTrace, expected_session_rate

__all__ = ["FleetSimulation", "ReplicaServiceModel", "replay_slice"]


@dataclass(frozen=True)
class ReplicaServiceModel:
    """Analytic single-replica service rates — the sim's stand-in for
    one engine, sized from the cost model so the sim and the live
    autoscaler plan with the SAME capacity number."""

    decode_tok_s: float          # aggregate new-token throughput
    prefill_tok_s: float         # prompt-token prefill throughput
    slots: int                   # concurrent sessions per replica
    spawn_delay_s: float = 20.0  # scale-up lead time (boot + compile)

    def __post_init__(self) -> None:
        if self.decode_tok_s <= 0 or self.prefill_tok_s <= 0:
            raise ValueError("service rates must be > 0")
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {self.slots}")

    @classmethod
    def from_cost_model(cls, cost_model, config, workload, *,
                        prefill_ratio: float = 8.0,
                        spawn_delay_s: float = 20.0
                        ) -> "ReplicaServiceModel":
        """Derive rates from a :class:`ServingCostModel`: decode
        capacity is the model's ``capacity_tok_s`` prediction; prefill
        runs ``prefill_ratio`` times faster per token (chunked prefill
        is compute-dense where decode is trip-bound)."""
        tok_s = float(cost_model.capacity_tok_s(config, workload))
        return cls(decode_tok_s=tok_s,
                   prefill_tok_s=prefill_ratio * tok_s,
                   slots=int(cost_model.max_batch),
                   spawn_delay_s=float(spawn_delay_s))


class _SimReplica:
    __slots__ = ("idx", "spawned_t", "ready_t", "retired_t", "state",
                 "busy", "queue", "populations", "served")

    def __init__(self, idx: int, now: float, ready_t: float):
        self.idx = idx
        self.spawned_t = now
        self.ready_t = ready_t
        self.retired_t: Optional[float] = None
        self.state = "live"            # live | draining | retired
        self.busy = 0
        self.queue: deque = deque()    # session indices waiting
        self.populations: set = set()  # prefix populations seen
        self.served = 0


class FleetSimulation:
    """One seeded day of traffic against the analytic fleet (see
    module docstring). ``run()`` returns the JSON-able report."""

    def __init__(self, trace: SessionTrace, model: ReplicaServiceModel,
                 *, autoscaler: Optional[ElasticAutoscaler] = None,
                 initial_replicas: int = 1,
                 control_interval_s: float = 60.0,
                 forecast_horizon_s: float = 900.0,
                 slo: Optional[Dict[str, float]] = None):
        if initial_replicas < 1:
            raise ValueError("initial_replicas must be >= 1")
        self.trace = trace
        self.model = model
        self.autoscaler = autoscaler
        self.initial_replicas = int(initial_replicas)
        self.control_interval_s = float(control_interval_s)
        self.forecast_horizon_s = float(forecast_horizon_s)
        self.slo = dict(DEFAULT_SLO, **(slo or {}))

    # ------------------------------------------------------------ mechanics
    def _admit(self, rep: _SimReplica, i: int, now: float) -> None:
        """Start service for session ``i`` on ``rep`` (a slot is free).
        Service time = prefill of the non-cached prompt + max_new
        decode steps at the replica's per-slot rate under its load at
        admission."""
        spec = self.trace.spec
        pop = self._population[i]
        plen = self._prompt_len[i]
        hit = pop in rep.populations
        rep.populations.add(pop)
        eff_prompt = plen - min(spec.shared_prefix_tokens, plen - 1) \
            if hit else plen
        rep.busy += 1
        prefill_s = eff_prompt / self.model.prefill_tok_s
        per_tok_s = rep.busy / self.model.decode_tok_s
        ttft = (now - self._t[i]) + prefill_s
        tpot_ms = per_tok_s * 1000.0
        done_t = now + prefill_s + self._max_new[i] * per_tok_s
        ten = self._tenant[i]
        row = self._tenant_stats[ten]
        row[0] += 1
        wrow = self._window_stats[ten]
        wrow[0] += 1
        if ttft > self.slo["ttft_s"]:
            row[1] += 1
            wrow[1] += 1
        if tpot_ms > self.slo["tpot_ms"]:
            row[2] += 1
            wrow[2] += 1
        if hit:
            self._prefix_hits += 1
        self._ttft_sum += ttft
        self._tokens_served += plen + self._max_new[i]
        self._order += 1
        heapq.heappush(self._events,
                       (done_t, self._order, "complete", rep.idx))

    def _route(self, i: int, now: float) -> None:
        """Mirror the real router's preference order: a ready replica
        with a free slot that has seen this session's prefix
        population, else the freest ready replica, else queue on the
        shortest backlog."""
        pop = self._population[i]
        ready = [r for r in self._replicas
                 if r.state == "live" and r.ready_t <= now]
        if not ready:
            # every replica still booting/draining: queue on the one
            # that will be ready first (fleet can never be empty)
            candidates = [r for r in self._replicas
                          if r.state != "retired"]
            rep = min(candidates, key=lambda r: (r.ready_t, r.idx))
            rep.queue.append(i)
            self._queued_peak = max(
                self._queued_peak, sum(len(r.queue)
                                       for r in self._replicas))
            return
        free = [r for r in ready if r.busy < self.model.slots]
        if free:
            affine = [r for r in free if pop in r.populations]
            rep = min(affine or free,
                      key=lambda r: (r.busy + len(r.queue), r.idx))
            self._admit(rep, i, now)
            return
        rep = min(ready, key=lambda r: (r.busy + len(r.queue), r.idx))
        rep.queue.append(i)
        self._queued_peak = max(
            self._queued_peak,
            sum(len(r.queue) for r in self._replicas))

    def _complete(self, rep: _SimReplica, now: float) -> None:
        rep.busy -= 1
        rep.served += 1
        self._completed += 1
        if rep.queue and rep.state == "live":
            self._admit(rep, rep.queue.popleft(), now)
        elif rep.state == "draining" and rep.busy == 0 \
                and not rep.queue:
            self._retire(rep, now)

    def _retire(self, rep: _SimReplica, now: float) -> None:
        rep.state = "retired"
        rep.retired_t = now
        self._replica_hours += (now - rep.spawned_t) / 3600.0

    def _spawn(self, now: float) -> None:
        rep = _SimReplica(len(self._replicas), now,
                          now + self.model.spawn_delay_s)
        self._replicas.append(rep)
        self._peak_replicas = max(
            self._peak_replicas,
            sum(1 for r in self._replicas if r.state != "retired"))

    def _drain(self, now: float) -> None:
        """Token-exact scale-down, sim-side: victim stops routing, its
        queue migrates to peers immediately (the evacuate/admit path),
        its in-service sessions finish, then it retires."""
        live = [r for r in self._replicas
                if r.state == "live" and r.ready_t <= now]
        if len(live) <= 1:
            return
        victim = min(live, key=lambda r: (r.busy + len(r.queue),
                                          -r.idx))
        victim.state = "draining"
        moved = list(victim.queue)
        victim.queue.clear()
        self._migrated += len(moved)
        for i in moved:
            self._route(i, now)
        if victim.busy == 0:
            self._retire(victim, now)

    def _worst_window_burn(self) -> float:
        budget = max(1e-9, 1.0 - float(self.slo["target"]))
        worst = 0.0
        for count, tviol, pviol in self._window_stats.values():
            if count:
                worst = max(worst, max(tviol, pviol) / count / budget)
        return worst

    def _control(self, now: float) -> None:
        """One autoscaler control tick: observed windowed token demand,
        the diurnal forecast at ``now + horizon``, windowed burn."""
        if self.autoscaler is None:
            return
        dt = self.control_interval_s
        demand = self._window_tokens / dt
        forecast = (expected_session_rate(self.trace.spec,
                                          now + self.forecast_horizon_s)
                    * self.trace.mean_tokens)
        live = sum(1 for r in self._replicas
                   if r.state == "live")
        d = self.autoscaler.decide(now, live=live,
                                   demand_tok_s=demand,
                                   forecast_tok_s=forecast,
                                   burn_rate=self._worst_window_burn())
        if d.action == "up":
            for _ in range(d.count):
                self._spawn(now)
        elif d.action == "down":
            self._drain(now)
        self._window_tokens = 0.0
        for row in self._window_stats.values():
            row[0] = row[1] = row[2] = 0

    # ------------------------------------------------------------------ run
    def run(self) -> Dict[str, Any]:
        trace = self.trace
        spec = trace.spec
        n = len(trace)
        # python lists: ~5x faster scalar reads than numpy in the loop
        # (host numpy traffic arrays, never device tensors)
        self._t = trace.t.tolist()  # graftlint: noqa[host-sync]
        self._tenant = trace.tenant.tolist()  # graftlint: noqa[host-sync]
        self._population = trace.population.tolist()  # graftlint: noqa[host-sync]
        self._prompt_len = trace.prompt_len.tolist()  # graftlint: noqa[host-sync]
        self._max_new = trace.max_new.tolist()  # graftlint: noqa[host-sync]

        self._replicas: List[_SimReplica] = []
        self._events: List = []
        self._order = 0
        self._completed = 0
        self._migrated = 0
        self._queued_peak = 0
        self._prefix_hits = 0
        self._ttft_sum = 0.0
        self._tokens_served = 0
        self._replica_hours = 0.0
        self._peak_replicas = 0
        self._window_tokens = 0.0
        self._tenant_stats = {t: [0, 0, 0]        # [count, ttft_v, tpot_v]
                              for t in range(spec.tenants)}
        self._window_stats = {t: [0, 0, 0]
                              for t in range(spec.tenants)}
        for _ in range(self.initial_replicas):
            self._spawn(0.0)
            self._replicas[-1].ready_t = 0.0      # day starts warm

        if self.autoscaler is not None:
            self._order += 1
            heapq.heappush(self._events,
                           (self.control_interval_s, self._order,
                            "control", -1))

        ai = 0
        now = 0.0
        while ai < n or self._events:
            if self._events and (ai >= n
                                 or self._events[0][0] <= self._t[ai]):
                now, _, kind, idx = heapq.heappop(self._events)
                if kind == "complete":
                    self._complete(self._replicas[idx], now)
                else:
                    self._control(now)
                    if ai < n or any(r.busy or r.queue
                                     for r in self._replicas):
                        self._order += 1
                        heapq.heappush(
                            self._events,
                            (now + self.control_interval_s,
                             self._order, "control", -1))
            else:
                now = self._t[ai]
                self._window_tokens += (self._prompt_len[ai]
                                        + self._max_new[ai])
                self._route(ai, now)
                ai += 1

        end = max(now, spec.day_s)
        for rep in self._replicas:
            if rep.state != "retired":
                self._replica_hours += (end - rep.spawned_t) / 3600.0

        return self._report(end)

    # --------------------------------------------------------------- report
    def _report(self, end: float) -> Dict[str, Any]:
        spec = self.trace.spec
        budget = max(1e-9, 1.0 - float(self.slo["target"]))
        slo_rows: Dict[str, Any] = {}
        attained = True
        for t in sorted(self._tenant_stats):
            count, tviol, pviol = self._tenant_stats[t]
            if not count:
                continue
            row = {"sessions": count}
            for key, viol in (("ttft", tviol), ("tpot", pviol)):
                att = 1.0 - viol / count
                row[key] = {"attainment": round(att, 6),
                            "burn_rate": round(viol / count / budget, 6),
                            "violations": viol}
                attained = attained and att >= float(self.slo["target"])
            slo_rows[f"t{t}"] = row

        # static twin, analytically: a fleet sized for the diurnal PEAK
        # runs that many replicas all day
        peak_demand = (spec.sessions / spec.day_s
                       * (1.0 + spec.diurnal_amplitude)
                       * self.trace.mean_tokens)
        if self.autoscaler is not None:
            util = self.autoscaler.policy.target_utilization
            cap = self.autoscaler.capacity_tok_s
            events = [d.as_dict() for d in self.autoscaler.events]
            for ev in events:
                for k in ("t", "demand_tok_s", "forecast_tok_s",
                          "burn_rate"):
                    ev[k] = round(ev[k], 6)
            ups = sum(1 for d in self.autoscaler.events
                      if d.action == "up")
            downs = sum(1 for d in self.autoscaler.events
                        if d.action == "down")
        else:
            util = 0.75
            cap = self.model.decode_tok_s
            events, ups, downs = [], 0, 0
        static_replicas = max(1, int(math.ceil(
            peak_demand / (cap * util))))
        static_hours = static_replicas * end / 3600.0

        return {
            "sim_sessions": len(self.trace),
            "sim_virtual_hours": round(end / 3600.0, 6),
            "replica_hours": round(self._replica_hours, 6),
            "static_replicas": static_replicas,
            "static_replica_hours": round(static_hours, 6),
            "elastic_beats_static": bool(
                self._replica_hours < static_hours),
            "peak_replicas": self._peak_replicas,
            "replicas_spawned": len(self._replicas),
            "completed": self._completed,
            "migrated": self._migrated,
            "queued_peak": self._queued_peak,
            "prefix_hit_sessions": self._prefix_hits,
            "tokens_served": int(self._tokens_served),
            "mean_ttft_s": round(
                self._ttft_sum / max(1, self._completed), 6),
            "autoscale_events": events,
            "autoscale_event_count": len(events),
            "scale_ups": ups,
            "scale_downs": downs,
            "slo": slo_rows,
            "slo_attained": bool(attained),
            "slo_target": float(self.slo["target"]),
            "traffic_signature": self.trace.signature(),
        }


def replay_slice(trace: SessionTrace, fleet: Any, *, sessions: int,
                 clock: Any, compress: float = 1000.0,
                 tick_s: float = 0.25, max_len: Optional[int] = None,
                 max_new_cap: Optional[int] = None,
                 on_tick: Optional[Callable[[int, float, int], None]]
                 = None) -> Dict[str, Any]:
    """Replay the first ``sessions`` of ``trace`` through a REAL
    :class:`~..inference.fleet.FleetRouter` in fast-time: arrival times
    compress by ``compress``×, the shared ``clock`` (a
    :class:`~.clock.VirtualClock` the router was built on) advances
    ``tick_s`` per router step, and sessions submit the moment virtual
    now passes their compressed arrival. ``on_tick(tick_no, now,
    submitted)`` runs after every router step — the seam the stage-7l
    harness uses for mid-run kills and autoscaler control.

    Returns ``{"rids": [...in submit order], "results": {rid:
    tokens}, "ticks": int}`` — token streams ready to fingerprint
    against an undisturbed twin."""
    from .traffic import materialize_session

    n = min(int(sessions), len(trace))
    t0 = float(trace.t[0]) if n else 0.0
    arrivals = [(float(trace.t[i]) - t0) / compress for i in range(n)]
    rids: List[int] = []
    si = 0
    ticks = 0
    while True:
        now = clock()
        while si < n and arrivals[si] <= now:
            r = materialize_session(trace, si, max_len=max_len)
            new = (min(r.max_new, max_new_cap) if max_new_cap
                   else r.max_new)
            rids.append(fleet.submit(list(r.prompt), max_new_tokens=new,
                                     tenant=r.tenant))
            si += 1
        remaining = fleet.step()
        ticks += 1
        clock.advance(tick_s)
        if on_tick is not None:
            on_tick(ticks, now, si)
        if si >= n and remaining == 0:
            break
    results = fleet.run()
    return {"rids": rids, "results": results, "ticks": ticks}
