"""fleetsim: seeded discrete-event traffic simulation for the fleet.

A "day" of traffic from millions of synthetic users, compressed into
CI wall-time: :mod:`traffic` draws the whole day's session arrivals in
one vectorized, seeded pass (diurnal arrival curve, tenant Zipf,
shared-prefix populations, long-tail context lengths — the
``autotune/workload.py`` distributions at fleet scale);
:mod:`sim` replays them against an analytic replica service model
derived from the PR 14 cost model under the virtual clock in
:mod:`clock`, driving the PR 20 elastic autoscaler exactly as a live
control loop would; and :func:`~paddle_tpu.fleetsim.sim.replay_slice`
materializes a slice of the same trace into real prompts and pushes
them through a real :class:`~paddle_tpu.inference.fleet.FleetRouter`
(in-process or subprocess replicas) so the simulator's claims stay
anchored to token-exact execution.

Everything in this package is deterministic at a seed and runs in
*virtual* seconds — no ``time.sleep``, no wall-clock reads (graftlint
GL015 enforces this): two runs at one seed produce byte-identical JSON.
"""
from .clock import VirtualClock
from .sim import FleetSimulation, ReplicaServiceModel, replay_slice
from .traffic import (DayTrafficSpec, SessionTrace, draw_day,
                      expected_session_rate, materialize_session)

__all__ = [
    "DayTrafficSpec", "FleetSimulation", "ReplicaServiceModel",
    "SessionTrace", "VirtualClock", "draw_day", "expected_session_rate",
    "materialize_session", "replay_slice",
]
