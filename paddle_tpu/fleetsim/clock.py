"""Virtual time for fast-time simulation.

The whole serving stack already accepts ``clock=`` callables (that is
what makes chaos replays deterministic — see graftlint GL012); a
:class:`VirtualClock` is the simulation's implementation of that seam:
a number that only moves when the event loop moves it. A simulated day
is 86_400 *virtual* seconds and however few wall milliseconds the loop
needs. Monotonicity is enforced — an event popped out of order would
otherwise silently corrupt every latency metric downstream.
"""
from __future__ import annotations

__all__ = ["VirtualClock"]


class VirtualClock:
    """Injectable fast-time source: ``clock()`` reads, ``advance_to``
    moves. Reading never advances — unlike the autotuner's counting
    clock, simulation time belongs to the EVENT LOOP, not to whoever
    happens to look at the clock most often."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def __call__(self) -> float:
        return self._now

    @property
    def now(self) -> float:
        return self._now

    def advance_to(self, t: float) -> float:
        """Jump to absolute virtual time ``t`` (monotone)."""
        t = float(t)
        if t < self._now:
            raise ValueError(
                f"virtual clock cannot run backwards: at {self._now}, "
                f"asked to advance_to {t}")
        self._now = t
        return self._now

    def advance(self, dt: float) -> float:
        """Advance by ``dt`` virtual seconds (non-negative)."""
        if dt < 0:
            raise ValueError(f"dt must be >= 0, got {dt}")
        self._now += float(dt)
        return self._now
