"""Day-scale synthetic traffic: millions of seeded session arrivals.

This is ``autotune/workload.py`` lifted to fleet scale. A
:class:`DayTrafficSpec` names only *traffic* knobs — session count,
diurnal curve shape, tenant Zipf skew, shared-prefix populations,
long-tail context mix, seed — and :func:`draw_day` derives the whole
day in a handful of vectorized numpy passes: one million arrivals draw
in well under a second, and the result is a :class:`SessionTrace` of
parallel arrays (times sorted ascending) that the event loop walks
with an index, no per-session Python objects.

Distributions:

- **arrival times** — an inhomogeneous Poisson-like process with a
  diurnal intensity ``λ(t) ∝ 1 + a·cos(2π(t - peak)/day)`` drawn by
  inverse-CDF over a fine grid (vectorized, deterministic). The
  analytic form is exported as :func:`expected_session_rate` — the
  autoscaler's forecast looks *ahead* on this curve, which is exactly
  the "cost model predicting capacity ahead of the diurnal curve"
  contract;
- **tenants** — Zipf over ``tenants`` ranks (heavy head, long tail),
  like real multi-tenant serving;
- **prefix populations** — Zipf over ``populations`` shared-prompt
  groups; sessions in one population share a prompt prefix (system
  prompt / few-shot header), the fleet's prefix-cache workload;
- **context lengths** — the workload ladders, with a ``longtail_frac``
  mixture of the long ladder for the heavy tail.

:func:`materialize_session` turns trace row *i* into concrete token
ids on demand — seeded per (population, session), so any slice of the
trace materializes identically regardless of which sessions execute.
"""
from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..autotune.workload import (LONG_PROMPT_LADDER, SHORT_PROMPT_LADDER,
                                 TrafficRequest)

__all__ = [
    "DayTrafficSpec", "SessionTrace", "draw_day", "expected_session_rate",
    "materialize_session", "zipf_weights",
]


@dataclasses.dataclass(frozen=True)
class DayTrafficSpec:
    """Declarative day of fleet traffic. Only traffic knobs live here —
    the serving config cannot reach the draw (same contract as
    :class:`~paddle_tpu.autotune.workload.WorkloadSpec`)."""

    sessions: int = 1_000_000
    day_s: float = 86_400.0
    #: diurnal amplitude a in [0, 1): intensity swings (1-a)..(1+a)
    #: around the mean — 0 is flat, 0.8 is a pronounced peak
    diurnal_amplitude: float = 0.6
    #: peak time as a fraction of the day (0.58 ≈ early afternoon)
    peak_frac: float = 0.58
    tenants: int = 8
    tenant_zipf_s: float = 1.1
    populations: int = 64
    population_zipf_s: float = 1.05
    #: shared tokens at the head of every prompt in a population,
    #: truncated to prompt_len - 1 so every session keeps unique tail
    shared_prefix_tokens: int = 32
    prompt_ladder: Tuple[int, ...] = SHORT_PROMPT_LADDER
    longtail_ladder: Tuple[int, ...] = LONG_PROMPT_LADDER
    #: fraction of sessions drawing from the long-tail context ladder
    longtail_frac: float = 0.05
    max_new_ladder: Tuple[int, ...] = (8, 16, 32, 64)
    vocab_size: int = 128
    seed: int = 0

    def __post_init__(self) -> None:
        if self.sessions < 1:
            raise ValueError(f"sessions must be >= 1, got {self.sessions}")
        if not 0.0 <= self.diurnal_amplitude < 1.0:
            raise ValueError(
                f"diurnal_amplitude must be in [0, 1), got "
                f"{self.diurnal_amplitude}")
        if not 0.0 <= self.longtail_frac <= 1.0:
            raise ValueError(
                f"longtail_frac must be in [0, 1], got "
                f"{self.longtail_frac}")
        if self.tenants < 1 or self.populations < 1:
            raise ValueError("tenants and populations must be >= 1")
        if self.day_s <= 0:
            raise ValueError(f"day_s must be > 0, got {self.day_s}")

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        for k in ("prompt_ladder", "longtail_ladder", "max_new_ladder"):
            d[k] = list(d[k])
        return d


@dataclasses.dataclass(frozen=True)
class SessionTrace:
    """One drawn day as parallel arrays (index = session, times sorted).
    ``mean_tokens`` is the per-session expected token work (prompt +
    new) — the bridge from session rate to token demand."""

    spec: DayTrafficSpec
    t: np.ndarray            # float64, ascending arrival seconds
    tenant: np.ndarray       # int32 tenant rank
    population: np.ndarray   # int32 prefix-population rank
    prompt_len: np.ndarray   # int32
    max_new: np.ndarray      # int32

    def __len__(self) -> int:
        return int(self.t.shape[0])

    @property
    def mean_tokens(self) -> float:
        return float(np.mean(self.prompt_len + self.max_new))

    def tokens(self, i: int) -> int:
        return int(self.prompt_len[i] + self.max_new[i])

    def signature(self) -> str:
        """Stable hash over every drawn array — two sims replaying the
        same signature saw byte-identical traffic."""
        h = hashlib.sha256()
        for a in (self.t, self.tenant, self.population,
                  self.prompt_len, self.max_new):
            h.update(np.ascontiguousarray(a).tobytes())
        return h.hexdigest()[:16]


def zipf_weights(n: int, s: float) -> np.ndarray:
    """Normalized Zipf pmf over ranks 1..n: p(r) ∝ r^-s."""
    w = np.arange(1, n + 1, dtype=np.float64) ** -float(s)
    return w / w.sum()


def expected_session_rate(spec: DayTrafficSpec, t: float) -> float:
    """Analytic arrival intensity (sessions/second) at virtual time
    ``t`` — the diurnal curve the draw inverts. The autoscaler's
    forecast evaluates this at ``t + horizon``: capacity decisions lead
    the curve instead of chasing it."""
    a = spec.diurnal_amplitude
    phase = 2.0 * np.pi * ((t / spec.day_s) - spec.peak_frac)
    return float(spec.sessions / spec.day_s * (1.0 + a * np.cos(phase)))


def draw_day(spec: DayTrafficSpec) -> SessionTrace:
    """Draw the complete day, vectorized and seeded by the spec alone.

    Arrival times come from inverse-CDF sampling of the diurnal
    intensity on a 1-minute grid; attribute draws are independent
    vectorized passes on the same rng, so the whole trace is a pure
    function of the spec."""
    rng = np.random.RandomState(spec.seed & 0x7FFFFFFF)  # graftlint: noqa[np-random]
    n = spec.sessions

    # inverse-CDF arrival times on a fine grid: cumulative intensity
    # Λ(t) is strictly increasing (amplitude < 1), so interp is exact
    # to grid resolution and vectorizes over all n draws at once
    grid = np.linspace(0.0, spec.day_s, 1441)
    lam = 1.0 + spec.diurnal_amplitude * np.cos(
        2.0 * np.pi * (grid / spec.day_s - spec.peak_frac))
    cum = np.concatenate([[0.0], np.cumsum((lam[1:] + lam[:-1]) * 0.5)])
    cum /= cum[-1]
    u = rng.uniform(0.0, 1.0, n)
    t = np.sort(np.interp(u, cum, grid))

    tenant = rng.choice(spec.tenants, size=n,
                        p=zipf_weights(spec.tenants,
                                       spec.tenant_zipf_s)).astype(np.int32)
    population = rng.choice(
        spec.populations, size=n,
        p=zipf_weights(spec.populations,
                       spec.population_zipf_s)).astype(np.int32)

    short = np.asarray(spec.prompt_ladder, dtype=np.int32)
    long_ = np.asarray(spec.longtail_ladder, dtype=np.int32)
    prompt_len = short[rng.randint(0, len(short), n)]
    tail = rng.uniform(0.0, 1.0, n) < spec.longtail_frac
    if tail.any():
        prompt_len = np.where(
            tail, long_[rng.randint(0, len(long_), n)], prompt_len)
    max_new = np.asarray(spec.max_new_ladder, dtype=np.int32)[
        rng.randint(0, len(spec.max_new_ladder), n)]

    return SessionTrace(spec=spec, t=t, tenant=tenant,
                        population=population,
                        prompt_len=prompt_len.astype(np.int32),
                        max_new=max_new.astype(np.int32))


def materialize_session(trace: SessionTrace, i: int,
                        max_len: Optional[int] = None) -> TrafficRequest:
    """Concrete token ids for trace row ``i`` — a shared per-population
    prefix (seeded by the population, identical across every session in
    it: the prefix-cache workload) followed by a per-session unique
    tail. Deterministic per (spec.seed, population, i) so ANY slice of
    the trace materializes the same prompts. ``max_len`` clips
    prompt+new to a CPU-scale engine's window."""
    spec = trace.spec
    ln = int(trace.prompt_len[i])
    new = int(trace.max_new[i])
    if max_len is not None:
        ln = max(1, min(ln, max_len - new))
    pop = int(trace.population[i])
    k = min(spec.shared_prefix_tokens, ln - 1)
    prng = np.random.RandomState((spec.seed ^ 0x50C1A1 ^ pop) & 0x7FFFFFFF)  # graftlint: noqa[np-random]
    prefix = prng.randint(1, spec.vocab_size, max(k, 1))[:k]
    srng = np.random.RandomState((spec.seed ^ 0x7AF1 ^ (i * 2654435761)) & 0x7FFFFFFF)  # graftlint: noqa[np-random]
    tail = srng.randint(1, spec.vocab_size, ln - k)
    prompt = tuple(int(x) for x in prefix) + tuple(int(x) for x in tail)
    return TrafficRequest(prompt=prompt, max_new=new, priority=1,
                          tenant=f"t{int(trace.tenant[i])}", adapter=None)
