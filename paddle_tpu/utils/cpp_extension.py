"""Custom C++ op SDK (ref: python/paddle/utils/cpp_extension/ — PD_BUILD_OP
user ops JIT-compiled and loaded at runtime via
paddle/fluid/framework/custom_operator.cc).

TPU-native design: device-side custom kernels are Pallas (Python-authored);
this SDK covers HOST custom ops — C++ compiled to a shared lib and invoked
from traced programs through jax.pure_callback (CPU callback ring), or
eagerly via ctypes. The C ABI convention replaces PD_BUILD_OP:

    extern "C" void my_op(const float* in, float* out, long n);

`load(name, sources)` compiles with g++ and returns a module-like object
whose attributes are the exported functions wrapped as paddle ops.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import re
import subprocess
from typing import Callable, List, Optional, Sequence

import numpy as np

_BUILD_DIR = os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu_extensions")


class CppExtension:
    def __init__(self, sources, extra_compile_args=None, **kwargs):
        self.sources = sources
        self.extra_compile_args = extra_compile_args or []


CUDAExtension = CppExtension  # no CUDA in a TPU build; kept for import parity


def _compile(name: str, sources: Sequence[str], extra_args: Sequence[str],
             build_directory: Optional[str], verbose: bool) -> str:
    build_dir = build_directory or _BUILD_DIR
    os.makedirs(build_dir, exist_ok=True)
    tag = hashlib.sha1("".join(
        open(s).read() for s in sources).encode()).hexdigest()[:12]
    out = os.path.join(build_dir, f"{name}_{tag}.so")
    if not os.path.exists(out):
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-o", out,
               *sources, *extra_args]
        if verbose:
            print(" ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return out


_SIG_RE = re.compile(
    r'extern\s+"C"\s+void\s+(\w+)\s*\(([^)]*)\)')


class _LoadedOp:
    """Wraps one exported C function as an eager+traceable op.

    Convention: pointer args alternate (const T* input..., T* output...) and a
    trailing `long n` element count. The wrapper passes all inputs, allocates
    one like-shaped output, and calls back on host (jax.pure_callback under
    trace — the TPU analogue of a CPU custom kernel).
    """

    def __init__(self, cfunc, name):
        self._c = cfunc
        self.__name__ = name

    def _run_np(self, *arrays):
        arrays = [np.ascontiguousarray(a, np.float32) for a in arrays]
        out = np.empty_like(arrays[0])
        ptrs = [a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) for a in arrays]
        self._c(*ptrs, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                ctypes.c_long(arrays[0].size))
        return out

    def __call__(self, *tensors):
        import jax

        from ..framework.core import Tensor, to_array
        from ..framework.dispatch import apply_op

        def f(*vals):
            shape_dtype = jax.ShapeDtypeStruct(vals[0].shape, np.float32)
            return jax.pure_callback(
                lambda *np_vals: self._run_np(*[np.asarray(v) for v in np_vals]),
                shape_dtype, *vals)

        return apply_op(f, *tensors, op_name=self.__name__)


class _ExtensionModule:
    def __init__(self, lib, names):
        self._lib = lib
        for n in names:
            cf = getattr(lib, n)
            cf.restype = None
            setattr(self, n, _LoadedOp(cf, n))


def load(name: str, sources: Sequence[str], extra_cxx_cflags: Sequence[str] = (),
         extra_cuda_cflags=None, extra_ldflags: Sequence[str] = (),
         extra_include_paths: Sequence[str] = (), build_directory=None,
         verbose: bool = False, interpreter=None):
    """paddle.utils.cpp_extension.load parity."""
    inc = [f"-I{p}" for p in extra_include_paths]
    so = _compile(name, sources, [*extra_cxx_cflags, *inc, *extra_ldflags],
                  build_directory, verbose)
    lib = ctypes.CDLL(so)
    names = []
    for s in sources:
        names += [m.group(1) for m in _SIG_RE.finditer(open(s).read())]
    if not names:
        raise RuntimeError(
            'no extern "C" void functions found; custom ops must use the C ABI '
            "convention (see module docstring)")
    return _ExtensionModule(lib, names)


def setup(name=None, ext_modules=None, **kwargs):
    """Shim of the setuptools-based build: compiles immediately."""
    mods = []
    for ext in ext_modules or []:
        mods.append(load(name or "ext", ext.sources,
                         extra_cxx_cflags=ext.extra_compile_args))
    return mods
