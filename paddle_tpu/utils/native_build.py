"""On-demand builds of the csrc/ shared objects.

The .so binaries are NOT committed to version control (no way to verify a
blob matches its source); every ctypes loader calls :func:`ensure_lib`,
which (re)compiles ``csrc/<name>.cpp`` with g++ whenever the built library
is missing or older than its source, caching the result next to the source
(or under ``~/.cache/paddle_tpu`` when the tree is read-only).

Atomicity: concurrent ranks racing on first use compile into a temp file in
the destination directory and ``os.replace`` it — a loader can never CDLL a
half-written library.
"""
from __future__ import annotations

import os
import subprocess
import tempfile
from typing import Optional, Sequence

_CSRC = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", "..",
                                     "csrc"))

#: last g++ failure (stderr tail / exception), for loader error messages
LAST_BUILD_ERROR: Optional[str] = None


def _compile_to(src: str, out_path: str, extra: Sequence[str]) -> bool:
    global LAST_BUILD_ERROR
    tmp = None
    try:
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(out_path))
        os.close(fd)
        subprocess.run(["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                        "-o", tmp, src, *extra, "-lpthread"],
                       check=True, capture_output=True, text=True, timeout=300)
        os.replace(tmp, out_path)  # atomic on POSIX
        return True
    except subprocess.CalledProcessError as e:
        LAST_BUILD_ERROR = (e.stderr or e.stdout or str(e))[-2000:]
    except Exception as e:
        LAST_BUILD_ERROR = repr(e)
    if tmp is not None:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    return False


def ensure_lib(stem: str, extra_flags: Sequence[str] = ()) -> Optional[str]:
    """Return the path of an up-to-date ``lib<stem>.so`` built from
    ``csrc/<stem>.cpp``, compiling if missing/stale; None if unbuildable."""
    src = os.path.join(_CSRC, f"{stem}.cpp")
    if not os.path.exists(src):
        return None
    out = os.path.join(_CSRC, f"lib{stem}.so")
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    if _compile_to(src, out, extra_flags):
        return out
    if os.path.exists(out):
        return out  # refresh failed (no g++?): a stale lib beats none
    # tree may be read-only: build into (or reuse from) a user cache
    cache = os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu")
    try:
        os.makedirs(cache, exist_ok=True)
    except OSError:
        return None
    out = os.path.join(cache, f"lib{stem}.so")
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    if _compile_to(src, out, extra_flags):
        return out
    return out if os.path.exists(out) else None  # stale cache fallback
