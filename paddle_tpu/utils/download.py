"""paddle.utils.download (ref python/paddle/utils/download.py
get_weights_path_from_url — fetch + cache pretrained weights).

Zero-egress environment: resolves against the local cache only and raises
with placement guidance when absent (same policy as dataset/common.py).
"""
from __future__ import annotations

import hashlib
import os

__all__ = ["get_weights_path_from_url"]

WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle_tpu/hapi/weights")


def _md5check(fullname: str, md5sum: str | None) -> bool:
    if md5sum is None:
        return True
    h = hashlib.md5()
    with open(fullname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            h.update(chunk)
    return h.hexdigest() == md5sum


def get_path_from_url(url: str, root_dir: str, md5sum: str | None = None,
                      check_exist: bool = True) -> str:
    fname = os.path.join(root_dir, url.split("/")[-1])
    if os.path.exists(fname) and _md5check(fname, md5sum):
        return fname
    raise RuntimeError(
        f"weights file {fname} not cached and network egress is disabled; "
        f"place the file from {url} at that path")


def get_weights_path_from_url(url: str, md5sum: str | None = None) -> str:
    """ref download.py get_weights_path_from_url"""
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
