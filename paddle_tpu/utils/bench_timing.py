"""Trustworthy device timing on async / tunneled backends.

On the tunneled axon TPU platform ``jax.block_until_ready`` returns WITHOUT
waiting for device execution (measured 2026-07-31: 20 flash-attention
kernels "completed" in 0.026 ms total), so any wall-clock timing that closes
with it reports dispatch time, not device time.  The only trustworthy sync
point is an actual device->host transfer.

The primitives here implement **dispatch-chain differencing**: dispatch N
calls (they pipeline on-device), close with a single scalar pull, and
subtract the identically-shaped 1-call measurement so the fixed tunnel
round-trip cost cancels:

    device_time = [t(N+1 calls + pull) - t(1 call + pull)] / N

Requirement on ``fn``: repeated calls must serialize on-device — either
through a data dependency (train steps chained via donated params) or by
being independent launches on the same stream (the default for same-device
jitted calls).  Every benchmark tool in the repo times through this module;
do not hand-roll ``block_until_ready`` timing loops.
"""
from __future__ import annotations

import contextlib
import os
import sys
import time

__all__ = ["pull_scalar", "chain_seconds", "device_time_ms", "tpu_lock",
           "UnstableMeasurement", "peak_flops"]

_LOCK_PATH = "/tmp/paddle_tpu_bench.lock"

# True when the most recent tpu_lock() acquisition timed out and the
# measurement proceeded unlocked — drivers should surface this in their
# emitted artifacts (see tpu_lock docstring)
last_lock_contended = False


class UnstableMeasurement(RuntimeError):
    """The differencing signal never cleared the observed noise floor.

    Distinct from generic RuntimeError so callers can skip-and-report
    without accidentally swallowing real device failures (XlaRuntimeError
    is also a RuntimeError subclass)."""


def peak_flops(gen: str | None = None) -> float:
    """Peak bf16 FLOP/s per chip for the generation in
    ``PALLAS_AXON_TPU_GEN`` (default v5e).  Single source of truth for
    bench.py and the sweep tools' physical-sanity gates."""
    gen = gen or os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    return {"v5e": 197e12, "v5p": 459e12, "v4": 275e12,
            "v6e": 918e12}.get(gen, 197e12)


@contextlib.contextmanager
def tpu_lock(path: str = _LOCK_PATH, timeout_s: float | None = None):
    """Cross-process exclusivity for device-timing runs.

    Two benchmark processes sharing one chip contend and corrupt each
    other's numbers (observed 2026-07-31: a 1.2 ms kernel "measured" 34 ms
    while a second sweep ran).  Every benchmark driver that spawns a
    measurement child — including cheap probes — must hold this flock
    around the child's lifetime.

    ``timeout_s`` bounds the wait: on expiry the context proceeds WITHOUT
    the lock (a possibly-contended measurement beats an unboundedly hung
    driver).  The degraded state is propagated, not just printed: the
    context yields ``locked`` (False when contended) and the module-level
    ``last_lock_contended`` flag is set, so benchmark drivers can annotate
    their emitted JSON — a stderr line alone is discardable (several
    run_tpu_suite.sh stages run with 2>/dev/null).
    """
    import fcntl

    global last_lock_contended
    with open(path, "w") as f:
        if timeout_s is None:
            fcntl.flock(f, fcntl.LOCK_EX)
            locked = True
        else:
            deadline = time.monotonic() + timeout_s
            locked = False
            while True:
                try:
                    fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    locked = True
                    break
                except OSError:
                    if time.monotonic() >= deadline:
                        sys.stderr.write(
                            f"tpu_lock: gave up after {timeout_s:.0f}s; "
                            f"proceeding unlocked (numbers may be "
                            f"contended)\n")
                        break
                    time.sleep(1.0)
        last_lock_contended = not locked
        try:
            yield locked
        finally:
            if locked:
                fcntl.flock(f, fcntl.LOCK_UN)


def pull_scalar(out) -> float:
    """Force a real device->host sync by fetching one scalar of ``out``.

    Accepts any pytree of jax arrays or framework Tensors (anything whose
    leaves numpy can consume after ``jnp.asarray``).
    """
    import jax
    import jax.numpy as jnp

    leaves = [l for l in jax.tree_util.tree_leaves(out) if l is not None]
    if not leaves:
        raise ValueError(
            "pull_scalar: fn returned no array output to sync on (got an "
            "empty/None pytree) — the timing harness needs at least one "
            "device array to pull")
    leaf = leaves[0]
    value = getattr(leaf, "value", leaf)  # framework Tensor -> jax.Array
    return float(jnp.asarray(value).reshape(-1)[0].astype(jnp.float32))


def _chain_stats(fn, n: int, repeats: int) -> tuple[float, float]:
    """(min, max) wall time over ``repeats`` of: dispatch ``fn()`` ``n``
    times, then one scalar pull of the last output."""
    lo, hi = float("inf"), 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = fn()
        pull_scalar(out)
        dt = time.perf_counter() - t0
        lo, hi = min(lo, dt), max(hi, dt)
    return lo, hi


def chain_seconds(fn, n: int, repeats: int = 3) -> float:
    """min-of-``repeats`` wall time of: dispatch ``fn()`` ``n`` times, then
    one scalar pull of the last output."""
    return _chain_stats(fn, n, repeats)[0]


def device_time_ms(fn, reps: int = 10, repeats: int = 3, warmup: int = 1,
                   min_signal_s: float | None = None,
                   max_reps: int = 1024) -> float:
    """Per-call device execution time of ``fn`` in milliseconds.

    Self-calibrating against the noise it actually observes: the required
    differencing signal is ``max(4 x measured spread, 10 ms)`` (or the
    explicit ``min_signal_s``), and reps double until the signal clears it.
    On a quiet local backend sub-ms ops pass at small reps; on the jittery
    tunnel the same code demands hundreds of ms of signal — the adaptive
    floor is what keeps physically-impossible readings (observed at fixed
    small reps) out of benchmark tables.  ``UnstableMeasurement`` is raised
    at the reps cap rather than returning a sub-floor number.
    """
    out = None
    for _ in range(max(warmup, 1)):  # compile + steady-state
        out = fn()
    pull_scalar(out)
    while True:
        lo_long, hi_long = _chain_stats(fn, reps + 1, repeats)
        lo_short, hi_short = _chain_stats(fn, 1, repeats)
        diff = lo_long - lo_short
        spread = (hi_long - lo_long) + (hi_short - lo_short)
        floor = (min_signal_s if min_signal_s is not None
                 else max(4.0 * spread, 0.010))
        if diff >= floor:
            return diff / reps * 1e3
        if reps >= max_reps:
            raise UnstableMeasurement(
                f"{reps} reps stayed below the noise floor "
                f"(signal {diff * 1e3:.2f} ms < floor {floor * 1e3:.0f} ms, "
                f"spread {spread * 1e3:.0f} ms); the backend is too jittery "
                f"for this op")
        reps *= 2
