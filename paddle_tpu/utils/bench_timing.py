"""Trustworthy device timing on async / tunneled backends.

On the tunneled axon TPU platform ``jax.block_until_ready`` returns WITHOUT
waiting for device execution (measured 2026-07-31: 20 flash-attention
kernels "completed" in 0.026 ms total), so any wall-clock timing that closes
with it reports dispatch time, not device time.  The only trustworthy sync
point is an actual device->host transfer.

The primitives here implement **dispatch-chain differencing**: dispatch N
calls (they pipeline on-device), close with a single scalar pull, and
subtract the identically-shaped 1-call measurement so the fixed tunnel
round-trip cost cancels:

    device_time = [t(N+1 calls + pull) - t(1 call + pull)] / N

Requirement on ``fn``: repeated calls must serialize on-device — either
through a data dependency (train steps chained via donated params) or by
being independent launches on the same stream (the default for same-device
jitted calls).  Every benchmark tool in the repo times through this module;
do not hand-roll ``block_until_ready`` timing loops.
"""
from __future__ import annotations

import time

__all__ = ["pull_scalar", "chain_seconds", "device_time_ms"]


def pull_scalar(out) -> float:
    """Force a real device->host sync by fetching one scalar of ``out``.

    Accepts any pytree of jax arrays or framework Tensors (anything whose
    leaves numpy can consume after ``jnp.asarray``).
    """
    import jax
    import jax.numpy as jnp

    leaves = [l for l in jax.tree_util.tree_leaves(out) if l is not None]
    leaf = leaves[0]
    value = getattr(leaf, "value", leaf)  # framework Tensor -> jax.Array
    return float(jnp.asarray(value).reshape(-1)[0].astype(jnp.float32))


def chain_seconds(fn, n: int, repeats: int = 3) -> float:
    """min-of-``repeats`` wall time of: dispatch ``fn()`` ``n`` times, then
    one scalar pull of the last output."""
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = fn()
        pull_scalar(out)
        best = min(best, time.perf_counter() - t0)
    return best


def device_time_ms(fn, reps: int = 10, repeats: int = 3,
                   warmup: int = 1) -> float:
    """Per-call device execution time of ``fn`` in milliseconds.

    A non-positive difference means the signal (reps x per-call time) was
    below the tunnel jitter — one retry at double the reps, then
    ``RuntimeError``: an unstable measurement must never enter a sorted
    benchmark table looking like a near-zero winner.
    """
    out = None
    for _ in range(max(warmup, 1)):  # compile + steady-state
        out = fn()
    pull_scalar(out)
    for attempt_reps in (reps, reps * 2):
        t_long = chain_seconds(fn, attempt_reps + 1, repeats)
        t_short = chain_seconds(fn, 1, repeats)
        if t_long > t_short:
            return (t_long - t_short) / attempt_reps * 1e3
    raise RuntimeError(
        f"unstable measurement: {reps}..{reps * 2} reps of fn stayed below "
        f"the host/tunnel timing noise floor; raise reps")
