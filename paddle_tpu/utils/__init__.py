"""paddle.utils parity (subset)."""
from __future__ import annotations

import importlib
import sys

from . import cpp_extension  # noqa: F401
from . import dlpack  # noqa: F401
from . import download  # noqa: F401


def try_import(module_name, err_msg=None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"{module_name} is required but not installed")


def run_check():
    """paddle.utils.run_check parity: verifies the TPU stack end-to-end."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt

    x = pt.ones([2, 3])
    y = pt.matmul(x, pt.ones([3, 4]))
    assert y.shape == [2, 4]
    devs = jax.devices()
    print(f"paddle_tpu is installed successfully! devices={devs}")
    return True


def unique_name_generator(prefix="tmp"):
    counter = {}

    def gen(p=None):
        p = p or prefix
        counter[p] = counter.get(p, 0) + 1
        return f"{p}_{counter[p]}"

    return gen


class unique_name:
    _counter = {}

    @classmethod
    def generate(cls, prefix="tmp"):
        cls._counter[prefix] = cls._counter.get(prefix, 0) + 1
        return f"{prefix}_{cls._counter[prefix]}"


def flatten(nest):
    import jax

    leaves, _ = jax.tree_util.tree_flatten(nest)
    return leaves


def pack_sequence_as(structure, flat):
    import jax

    _, treedef = jax.tree_util.tree_flatten(structure)
    return jax.tree_util.tree_unflatten(treedef, flat)


def deprecated(update_to="", since="", reason=""):
    def decorator(fn):
        return fn

    return decorator


def require_version(min_version: str, max_version=None):
    """ref python/paddle/utils/install_check require_version — assert the
    installed framework version falls in [min_version, max_version]."""
    from ..version import full_version

    def parse(v):
        """Leading numeric part of each of the first 3 segments, zero-padded
        ('2.5.0+tpu' -> (2,5,0); '2.5' -> (2,5,0)) so local suffixes and
        length mismatches don't skew the comparison."""
        import re

        out = []
        for seg in str(v).split(".")[:3]:
            m = re.match(r"\d+", seg)
            out.append(int(m.group()) if m else 0)
        return tuple(out + [0] * (3 - len(out)))

    cur = parse(full_version)
    if parse(min_version) > cur:
        raise ValueError(
            f"paddle_tpu version {full_version} < required {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise ValueError(
            f"paddle_tpu version {full_version} > allowed {max_version}")
    return True
