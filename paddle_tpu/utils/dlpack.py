"""paddle.utils.dlpack (ref python/paddle/utils/dlpack.py to_dlpack/
from_dlpack over paddle/fluid/framework/dlpack_tensor.cc).

TPU-native: jax arrays speak dlpack natively (zero-copy on CPU; device
buffers export via the producer stream) — torch/numpy interop without a copy.
"""
from __future__ import annotations

from ..framework.core import Tensor, to_array

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor → DLPack capsule (ref dlpack.py to_dlpack)."""
    arr = to_array(x) if isinstance(x, Tensor) else x
    return arr.__dlpack__()


def from_dlpack(capsule_or_ext) -> Tensor:
    """DLPack capsule or __dlpack__-capable external tensor → Tensor
    (ref dlpack.py from_dlpack)."""
    import jax.numpy as jnp

    return Tensor(jnp.from_dlpack(capsule_or_ext))
