"""Autograd public API (ref: python/paddle/autograd/__init__.py).

backward/grad come from the tape engine; PyLayer (custom autograd,
ref python/paddle/autograd/py_layer.py + paddle/fluid/eager/pylayer/) is a
thin class over the same tape — forward runs eagerly, backward is the
user-supplied function registered as the tape node's vjp.
"""
from __future__ import annotations

from typing import Any, List

import jax.numpy as jnp
import weakref

from ..framework.core import (Tensor, TapeNode, backward, grad, is_grad_enabled, no_grad,
                              to_array)

__all__ = ["backward", "grad", "PyLayer", "PyLayerContext", "no_grad",
           "hessian", "jacobian", "saved_tensors_hooks", "set_grad_enabled"]


# --- saved-tensor pack/unpack hooks (ref autograd/saved_tensors_hooks.py:20)
_saved_hooks = []


class saved_tensors_hooks:
    """Register a (pack_hook, unpack_hook) pair applied to tensors saved for
    backward (ref autograd/saved_tensors_hooks.py:20) — e.g. offload
    activations to host numpy on save, reload on use.

    Scope note: eagerly-saved tensors means ``PyLayerContext.
    save_for_backward`` here; the implicit op residuals of the tape engine
    are captured inside jax vjp closures (XLA-managed device buffers with no
    eager alias to hook), so the reference's LoDTensor-only caveat maps to
    "PyLayer saves only"."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        _saved_hooks.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        _saved_hooks.pop()
        return False


import contextlib as _contextlib


@_contextlib.contextmanager
def set_grad_enabled(mode: bool):
    """paddle.set_grad_enabled parity: context manager flipping autograd
    recording (ref framework [core] set_grad_enabled)."""
    from ..framework.core import _grad_state

    prev = _grad_state.enabled
    _grad_state.enabled = bool(mode)
    try:
        yield
    finally:
        _grad_state.enabled = prev


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.materialize_grads = True
        self._non_diff = set()

    def save_for_backward(self, *tensors):
        if _saved_hooks:
            pack, _ = _saved_hooks[-1]
            self._packed_with = _saved_hooks[-1]
            self._saved = [pack(t) for t in tensors]
        else:
            self._packed_with = None
            self._saved = list(tensors)

    def _unpacked(self):
        if getattr(self, "_packed_with", None) is not None:
            _, unpack = self._packed_with
            return [unpack(v) for v in self._saved]
        return self._saved

    @property
    def saved_tensor(self):
        return self._unpacked()

    def saved_tensors(self):
        return self._unpacked()

    def mark_not_inplace(self, *args):
        pass

    def mark_non_differentiable(self, *args):
        self._non_diff.update(id(a) for a in args)

    def set_materialize_grads(self, value: bool):
        self.materialize_grads = value


class PyLayerMeta(type):
    def __call__(cls, *a, **k):
        raise RuntimeError("PyLayer must be used via .apply(), not instantiated")


class PyLayer:
    """Custom autograd function: subclass with static forward/backward."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outs, (tuple, list))
        out_list = list(outs) if multi else [outs]
        out_tensors = [o if isinstance(o, Tensor) else Tensor(o) for o in out_list]

        diff_inputs = [a for a in args
                       if isinstance(a, Tensor) and not a.stop_gradient]
        if is_grad_enabled() and diff_inputs:
            n_in = len(diff_inputs)

            def vjp_fn(cts):
                cts_t = cts if isinstance(cts, tuple) else (cts,)
                gin = cls.backward(ctx, *[Tensor(c) for c in cts_t])
                gin = gin if isinstance(gin, (tuple, list)) else (gin,)
                out = []
                for g in gin:
                    out.append(None if g is None else to_array(g))
                # pad/truncate to match diff inputs
                return tuple(out[:n_in]) + (None,) * (n_in - len(out))

            node = TapeNode(
                vjp_fn,
                inputs=diff_inputs,
                out_avals=[(tuple(t.shape), t.dtype) for t in out_tensors],
                name=cls.__name__,
            )
            for k_, t in enumerate(out_tensors):
                if id(t) not in ctx._non_diff:
                    t._node = node
                    t._idx = k_
                    t.stop_gradient = False
                node.out_tensors[k_] = weakref.ref(t)
        if multi:
            return tuple(out_tensors)
        return out_tensors[0]


LegacyPyLayer = PyLayer


def jacobian(ys, xs, batch_axis=None):
    """paddle.incubate.autograd.jacobian parity via jax.jacrev on the traced fn."""
    import jax

    from ..framework.core import to_array

    if not isinstance(xs, (list, tuple)):
        xs = [xs]
    raise NotImplementedError(
        "Use paddle_tpu.incubate.autograd.Jacobian with an explicit function; "
        "tape-based jacobian of already-computed outputs is not supported.")


def hessian(func, xs, batch_axis=None):
    raise NotImplementedError(
        "Use paddle_tpu.incubate.autograd.Hessian with an explicit function.")
