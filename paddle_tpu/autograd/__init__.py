"""Autograd public API (ref: python/paddle/autograd/__init__.py).

backward/grad come from the tape engine; PyLayer (custom autograd,
ref python/paddle/autograd/py_layer.py + paddle/fluid/eager/pylayer/) is a
thin class over the same tape — forward runs eagerly, backward is the
user-supplied function registered as the tape node's vjp.
"""
from __future__ import annotations

from typing import Any, List

import jax.numpy as jnp
import weakref

from ..framework.core import (Tensor, TapeNode, backward, grad, is_grad_enabled, no_grad,
                              to_array)

__all__ = ["backward", "grad", "PyLayer", "PyLayerContext", "no_grad", "hessian", "jacobian"]


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.materialize_grads = True
        self._non_diff = set()

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved

    def mark_not_inplace(self, *args):
        pass

    def mark_non_differentiable(self, *args):
        self._non_diff.update(id(a) for a in args)

    def set_materialize_grads(self, value: bool):
        self.materialize_grads = value


class PyLayerMeta(type):
    def __call__(cls, *a, **k):
        raise RuntimeError("PyLayer must be used via .apply(), not instantiated")


class PyLayer:
    """Custom autograd function: subclass with static forward/backward."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outs, (tuple, list))
        out_list = list(outs) if multi else [outs]
        out_tensors = [o if isinstance(o, Tensor) else Tensor(o) for o in out_list]

        diff_inputs = [a for a in args
                       if isinstance(a, Tensor) and not a.stop_gradient]
        if is_grad_enabled() and diff_inputs:
            n_in = len(diff_inputs)

            def vjp_fn(cts):
                cts_t = cts if isinstance(cts, tuple) else (cts,)
                gin = cls.backward(ctx, *[Tensor(c) for c in cts_t])
                gin = gin if isinstance(gin, (tuple, list)) else (gin,)
                out = []
                for g in gin:
                    out.append(None if g is None else to_array(g))
                # pad/truncate to match diff inputs
                return tuple(out[:n_in]) + (None,) * (n_in - len(out))

            node = TapeNode(
                vjp_fn,
                inputs=diff_inputs,
                out_avals=[(tuple(t.shape), t.dtype) for t in out_tensors],
                name=cls.__name__,
            )
            for k_, t in enumerate(out_tensors):
                if id(t) not in ctx._non_diff:
                    t._node = node
                    t._idx = k_
                    t.stop_gradient = False
                node.out_tensors[k_] = weakref.ref(t)
        if multi:
            return tuple(out_tensors)
        return out_tensors[0]


LegacyPyLayer = PyLayer


def jacobian(ys, xs, batch_axis=None):
    """paddle.incubate.autograd.jacobian parity via jax.jacrev on the traced fn."""
    import jax

    from ..framework.core import to_array

    if not isinstance(xs, (list, tuple)):
        xs = [xs]
    raise NotImplementedError(
        "Use paddle_tpu.incubate.autograd.Jacobian with an explicit function; "
        "tape-based jacobian of already-computed outputs is not supported.")


def hessian(func, xs, batch_axis=None):
    raise NotImplementedError(
        "Use paddle_tpu.incubate.autograd.Hessian with an explicit function.")
