"""paddle.signal parity (ref: python/paddle/signal.py — stft/istft/frame)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .framework.core import Tensor, to_array
from .framework.dispatch import apply_op


def frame(x, frame_length, hop_length, axis=-1, name=None):
    def f(v):
        n = (v.shape[axis] - frame_length) // hop_length + 1
        idx = jnp.arange(n)[:, None] * hop_length + jnp.arange(frame_length)[None, :]
        vm = jnp.moveaxis(v, axis, -1)
        out = vm[..., idx]  # (..., n, frame_length)
        if axis in (-1, v.ndim - 1):
            return jnp.swapaxes(out, -1, -2)  # paddle: (..., frame_length, n)
        return out

    return apply_op(f, x)


def overlap_add(x, hop_length, axis=-1, name=None):
    def f(v):
        # v: (..., frame_length, n)
        vm = v if axis in (-1, v.ndim - 1) else jnp.moveaxis(v, axis, -1)
        fl, n = vm.shape[-2], vm.shape[-1]
        out_len = (n - 1) * hop_length + fl
        out = jnp.zeros(vm.shape[:-2] + (out_len,), v.dtype)
        for i in range(n):
            out = out.at[..., i * hop_length:i * hop_length + fl].add(vm[..., :, i])
        return out

    return apply_op(f, x)


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win = to_array(window) if window is not None else jnp.ones(win_length)
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        win = jnp.pad(win, (pad, n_fft - win_length - pad))

    def f(v):
        if center:
            pads = [(0, 0)] * (v.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            v = jnp.pad(v, pads, mode=pad_mode)
        n = (v.shape[-1] - n_fft) // hop_length + 1
        idx = jnp.arange(n)[:, None] * hop_length + jnp.arange(n_fft)[None, :]
        frames = v[..., idx] * win  # (..., n, n_fft)
        if onesided:
            spec = jnp.fft.rfft(frames, axis=-1)
        else:
            spec = jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(n_fft)
        return jnp.swapaxes(spec, -1, -2)  # (..., freq, n_frames)

    return apply_op(f, x)


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win = to_array(window) if window is not None else jnp.ones(win_length)
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        win = jnp.pad(win, (pad, n_fft - win_length - pad))

    def f(v):
        spec = jnp.swapaxes(v, -1, -2)  # (..., n_frames, freq)
        if normalized:
            spec = spec * jnp.sqrt(n_fft)
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, axis=-1).real
        frames = frames * win
        n = frames.shape[-2]
        out_len = (n - 1) * hop_length + n_fft
        out = jnp.zeros(frames.shape[:-2] + (out_len,), frames.dtype)
        wsum = jnp.zeros(out_len, frames.dtype)
        for i in range(n):
            out = out.at[..., i * hop_length:i * hop_length + n_fft].add(frames[..., i, :])
            wsum = wsum.at[i * hop_length:i * hop_length + n_fft].add(win * win)
        out = out / jnp.maximum(wsum, 1e-11)
        if center:
            out = out[..., n_fft // 2:-(n_fft // 2)]
        if length is not None:
            out = out[..., :length]
        return out

    return apply_op(f, x)
