"""Model zoo (flagships for the BASELINE.json configs: Llama for the 8B/70B
pretraining recipes, GPT/ERNIE-style encoder for NLP finetune, plus
paddle_tpu.vision models for the conv path)."""
from .llama import (LlamaConfig, LlamaForCausalLM, LlamaModel, llama3_8b_config,
                    llama3_70b_config, llama_tiny_config)
from .gpt import GPTConfig, GPTForCausalLM, gpt2_small_config, gpt_tiny_config
from .ernie import ErnieConfig, ErnieForMaskedLM, ErnieForQuestionAnswering, \
    ErnieForSequenceClassification, ErnieForTokenClassification, ErnieModel, \
    ernie_tiny_config

__all__ = [n for n in dir() if not n.startswith("_")]
