"""GPT-2-style decoder (the auto_parallel test fixture family —
ref python/paddle/fluid/tests/unittests/auto_parallel_gpt_model.py)."""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..framework.dispatch import apply_op
from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.layer_base import Layer
from .generation import GenerationMixin
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.norm import LayerNorm
from ..tensor.manipulation import reshape


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-5
    dtype: str = "float32"


def gpt2_small_config(**kw):
    return GPTConfig(**kw)


def gpt_tiny_config(**kw):
    return GPTConfig(**{**dict(vocab_size=512, hidden_size=128, num_hidden_layers=2,
                               num_attention_heads=4, intermediate_size=512,
                               max_position_embeddings=256,
                               hidden_dropout_prob=0.0,
                               attention_probs_dropout_prob=0.0), **kw})


class GPTBlock(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h = cfg.hidden_size
        init = Normal(0.0, 0.02)
        self.ln_1 = LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.c_attn = Linear(h, 3 * h, weight_attr=init)
        self.c_proj = Linear(h, h, weight_attr=init)
        self.ln_2 = LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.c_fc = Linear(h, cfg.intermediate_size, weight_attr=init)
        self.c_out = Linear(cfg.intermediate_size, h, weight_attr=init)
        self.drop = Dropout(cfg.hidden_dropout_prob)
        self.n_head = cfg.num_attention_heads
        self.c_attn.weight.pspec = P(None, "tensor")
        self.c_proj.weight.pspec = P("tensor", None)
        self.c_fc.weight.pspec = P(None, "tensor")
        self.c_out.weight.pspec = P("tensor", None)

    def forward(self, x):
        B, S, H = x.shape[0], x.shape[1], x.shape[2]
        qkv = self.c_attn(self.ln_1(x))
        qkv = reshape(qkv, [B, S, 3, self.n_head, H // self.n_head])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                              training=self.training)
        attn = reshape(attn, [B, S, H])
        x = x + self.drop(self.c_proj(attn))
        x = x + self.drop(self.c_out(F.gelu(self.c_fc(self.ln_2(x)), approximate=True)))
        return x


class GPTModel(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.wte = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = Embedding(cfg.max_position_embeddings, cfg.hidden_size)
        self.drop = Dropout(cfg.hidden_dropout_prob)
        self.h = LayerList([GPTBlock(cfg) for _ in range(cfg.num_hidden_layers)])
        self.ln_f = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)

    def forward(self, input_ids):
        S = input_ids.shape[1]
        import paddle_tpu as paddle

        pos = paddle.arange(S, dtype="int64")
        x = self.drop(self.wte(input_ids) + self.wpe(pos))
        for block in self.h:
            x = block(x)
        return self.ln_f(x)


class GPTForCausalLM(Layer, GenerationMixin):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.transformer = GPTModel(cfg)

    def forward(self, input_ids):
        h = self.transformer(input_ids)
        return apply_op(lambda v, w: jnp.matmul(v, w.T), h, self.transformer.wte.weight)

    def loss_fn(self, logits, labels):
        return F.cross_entropy(logits, labels, reduction="mean")
