"""GPT-2-style decoder (the auto_parallel test fixture family —
ref python/paddle/fluid/tests/unittests/auto_parallel_gpt_model.py)."""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..framework.dispatch import apply_op
from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.layer_base import Layer
from .generation import GenerationMixin
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.container import LayerList
from ..nn.layer.norm import LayerNorm
from ..tensor.manipulation import reshape


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-5
    dtype: str = "float32"
    # LoRA flag: rank > 0 wraps the block projections (nn/lora.py) at
    # construction — GPT's adapters ride the training path + merge_lora
    # export; the pooled multi-adapter serving path is Llama's
    lora_rank: int = 0
    lora_alpha: float = None
    lora_targets: tuple = None  # default: GPT_LORA_TARGETS


# fused qkv + attn out + both MLP Linears — every projection in a GPTBlock
GPT_LORA_TARGETS = ("c_attn", "c_proj", "c_fc", "c_out")


def gpt2_small_config(**kw):
    return GPTConfig(**kw)


def gpt_tiny_config(**kw):
    return GPTConfig(**{**dict(vocab_size=512, hidden_size=128, num_hidden_layers=2,
                               num_attention_heads=4, intermediate_size=512,
                               max_position_embeddings=256,
                               hidden_dropout_prob=0.0,
                               attention_probs_dropout_prob=0.0), **kw})


class GPTBlock(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        h = cfg.hidden_size
        init = Normal(0.0, 0.02)
        self.ln_1 = LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.c_attn = Linear(h, 3 * h, weight_attr=init)
        self.c_proj = Linear(h, h, weight_attr=init)
        self.ln_2 = LayerNorm(h, epsilon=cfg.layer_norm_eps)
        self.c_fc = Linear(h, cfg.intermediate_size, weight_attr=init)
        self.c_out = Linear(cfg.intermediate_size, h, weight_attr=init)
        self.drop = Dropout(cfg.hidden_dropout_prob)
        self.n_head = cfg.num_attention_heads
        self.c_attn.weight.pspec = P(None, "tensor")
        self.c_proj.weight.pspec = P("tensor", None)
        self.c_fc.weight.pspec = P(None, "tensor")
        self.c_out.weight.pspec = P("tensor", None)

    def forward(self, x):
        B, S, H = x.shape[0], x.shape[1], x.shape[2]
        qkv = self.c_attn(self.ln_1(x))
        qkv = reshape(qkv, [B, S, 3, self.n_head, H // self.n_head])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                              training=self.training)
        attn = reshape(attn, [B, S, H])
        x = x + self.drop(self.c_proj(attn))
        x = x + self.drop(self.c_out(F.gelu(self.c_fc(self.ln_2(x)), approximate=True)))
        return x

    def prefill(self, x, ck, cv):
        """Whole-prompt pass filling cache positions [0, S) in one causal
        attention (the Llama prefill design). Attention goes through the
        SAME scaled_dot_product_attention path as forward() — flash kernel
        on TPU, jnp fallback elsewhere — only the cache writes are new."""
        B, S, H = x.shape[0], x.shape[1], x.shape[2]
        nh = self.n_head
        hd = H // nh
        qkv = reshape(self.c_attn(self.ln_1(x)), [B, S, 3, nh, hd])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]

        def fill(ckv, cvv, kv_, vv_):
            ckv = jax.lax.dynamic_update_slice(ckv, kv_.astype(ckv.dtype),
                                               (0, 0, 0, 0))
            cvv = jax.lax.dynamic_update_slice(cvv, vv_.astype(cvv.dtype),
                                               (0, 0, 0, 0))
            return ckv, cvv

        ck, cv = apply_op(fill, ck, cv, k, v, op_name="gpt_cache_fill")
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                             training=False)
        out = reshape(out, [B, S, H])
        x = x + self.c_proj(out)
        x = x + self.c_out(F.gelu(self.c_fc(self.ln_2(x)), approximate=True))
        return x, ck, cv

    def decode(self, x, ck, cv, pos):
        """Single-token decode with fixed-size KV caches (B, L, nh, hd) —
        same design as LlamaAttention.decode: write at ``pos`` via
        dynamic_update_slice, attend over positions ≤ pos, static shapes so
        the whole generate loop compiles once."""
        B, H = x.shape[0], x.shape[2]
        nh = self.n_head
        hd = H // nh
        qkv = self.c_attn(self.ln_1(x))

        def attn_step(qkvv, ckv, cvv):
            q, k, v = jnp.split(qkvv.reshape(B, 1, 3 * nh, hd), 3, axis=2)
            ckv = jax.lax.dynamic_update_slice(ckv, k.astype(ckv.dtype),
                                               (0, pos, 0, 0))
            cvv = jax.lax.dynamic_update_slice(cvv, v.astype(cvv.dtype),
                                               (0, pos, 0, 0))
            L = ckv.shape[1]
            scores = jnp.einsum("bshd,bthd->bhst", q, ckv).astype(
                jnp.float32) / math.sqrt(hd)
            mask = (jnp.arange(L) <= pos)[None, None, None, :]
            scores = jnp.where(mask, scores, -1e30)
            p = jax.nn.softmax(scores, -1).astype(q.dtype)
            out = jnp.einsum("bhst,bthd->bshd", p, cvv)
            return out.reshape(B, 1, H), ckv, cvv

        out, ck, cv = apply_op(attn_step, qkv, ck, cv,
                               op_name="gpt_decode_attention")
        x = x + self.c_proj(out)
        x = x + self.c_out(F.gelu(self.c_fc(self.ln_2(x)), approximate=True))
        return x, ck, cv


class GPTModel(Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.wte = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.wpe = Embedding(cfg.max_position_embeddings, cfg.hidden_size)
        self.drop = Dropout(cfg.hidden_dropout_prob)
        self.h = LayerList([GPTBlock(cfg) for _ in range(cfg.num_hidden_layers)])
        self.ln_f = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)

    def forward(self, input_ids):
        S = input_ids.shape[1]
        import paddle_tpu as paddle

        pos = paddle.arange(S, dtype="int64")
        x = self.drop(self.wte(input_ids) + self.wpe(pos))
        for block in self.h:
            x = block(x)
        return self.ln_f(x)

    def prefill(self, input_ids, caches):
        """Whole-prompt pass filling the decode caches; returns (normed
        hidden for all positions, new caches)."""
        import paddle_tpu as paddle

        S = input_ids.shape[1]
        pos = paddle.arange(S, dtype="int64")
        x = self.wte(input_ids) + self.wpe(pos)
        new = []
        for block, (ck, cv) in zip(self.h, caches):
            x, ck, cv = block.prefill(x, ck, cv)
            new.append((ck, cv))
        return self.ln_f(x), new

    def decode_step(self, token, caches, pos):
        """token (B,1) at absolute position ``pos``; returns hidden (B,1,H)
        + updated caches (list of (ck, cv) per block)."""
        x = self.wte(token) + apply_op(
            lambda w: jax.lax.dynamic_slice_in_dim(w, pos, 1, 0)[None],
            self.wpe.weight, op_name="wpe_at")
        new = []
        for block, (ck, cv) in zip(self.h, caches):
            x, ck, cv = block.decode(x, ck, cv, pos)
            new.append((ck, cv))
        return self.ln_f(x), new


class GPTForCausalLM(Layer, GenerationMixin):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.transformer = GPTModel(cfg)
        if cfg.lora_rank:
            self.attach_lora(cfg.lora_rank, alpha=cfg.lora_alpha,
                             targets=cfg.lora_targets)

    def attach_lora(self, rank, alpha=None, targets=None):
        """Wrap the block projections with trainable LoRA factors; the
        fused c_attn wrap adapts q/k/v through one (h, 3h) residual."""
        from ..nn.lora import attach_lora

        return attach_lora(self, rank, alpha=alpha,
                           targets=targets or GPT_LORA_TARGETS)

    def merge_lora(self, targets=None):
        """Fold adapter deltas back into the base weights (dense export)."""
        from ..nn.lora import merge_lora

        return merge_lora(self, targets=targets or GPT_LORA_TARGETS)

    def forward(self, input_ids):
        h = self.transformer(input_ids)
        return apply_op(lambda v, w: jnp.matmul(v, w.T), h, self.transformer.wte.weight)

    def loss_fn(self, logits, labels):
        return F.cross_entropy(logits, labels, reduction="mean")

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0, seed: int = 0,
                 eos_token_id=None, num_beams: int = 1,
                 length_penalty: float = 0.0):
        """Cached O(L) decode (overrides the cache-less GenerationMixin
        fallback): fixed KV caches per block + one compiled scan — the same
        design as Llama's generate. ``num_beams > 1`` switches to the
        compiled beam search."""
        from ..framework.core import Tensor
        from ..framework.dtype import convert_dtype
        from ..jit import functional_call
        from .generation import compiled_cached_generate

        cfg = self.cfg
        nh = cfg.num_attention_heads
        hd = cfg.hidden_size // nh
        n_layers = cfg.num_hidden_layers
        cdtype = convert_dtype(getattr(cfg, "dtype", "float32"))
        model = self

        def make_caches(B, L):
            flat = []
            for _ in range(n_layers):
                flat += [jnp.zeros((B, L, nh, hd), cdtype),
                         jnp.zeros((B, L, nh, hd), cdtype)]
            return flat

        def run_one(p, tok, flat, pos):
            caches = [(Tensor(flat[2 * i]), Tensor(flat[2 * i + 1]))
                      for i in range(n_layers)]

            def call():
                h, new = model.transformer.decode_step(Tensor(tok), caches,
                                                       pos)
                logits = apply_op(lambda v, w: jnp.matmul(v, w.T), h,
                                  model.transformer.wte.weight)
                return logits, new

            logits, new = functional_call(model, p, call_fn=call)
            out = []
            for ck, cv in new:
                out += [ck.value, cv.value]
            return logits.value[:, 0], out

        def prefill_fn(p, prompt, flat):
            caches = [(Tensor(flat[2 * i]), Tensor(flat[2 * i + 1]))
                      for i in range(n_layers)]

            def call():
                h, new = model.transformer.prefill(Tensor(prompt), caches)
                logits = apply_op(lambda v, w: jnp.matmul(v, w.T), h[:, -1:],
                                  model.transformer.wte.weight)
                return logits, new

            logits, new = functional_call(model, p, call_fn=call)
            out = []
            for ck, cv in new:
                out += [ck.value, cv.value]
            return logits.value[:, 0], out

        if num_beams > 1:
            if temperature or top_k:
                import warnings

                warnings.warn(
                    "num_beams > 1 uses deterministic beam search; "
                    "temperature/top_k/seed are ignored", UserWarning)
            from .generation import compiled_beam_search

            return compiled_beam_search(
                self, input_ids, num_beams=num_beams,
                max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
                length_penalty=length_penalty, make_caches=make_caches,
                run_one=run_one, prefill=prefill_fn,
                max_positions=cfg.max_position_embeddings)
        return compiled_cached_generate(
            self, input_ids, max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p, seed=seed,
            eos_token_id=eos_token_id, make_caches=make_caches,
            run_one=run_one, prefill=prefill_fn,
            max_positions=cfg.max_position_embeddings)
