"""Llama model family — the flagship for the BASELINE.json pretraining
configs (Llama-3 8B DP-only; 8B full recipe ≥40% MFU; 70B 4D hybrid).

TPU-first design decisions:
- bf16 parameters/activations, fp32 softmax + norms (master weights live in
  the optimizer, ref AdamW multi_precision).
- GQA attention through the Pallas flash kernel (ops/flash_attention.py);
  ring attention over the 'context' mesh axis for long sequences
  (parallel/ring_attention.py) when config.context_parallel.
- TP via GSPMD PartitionSpecs on weights (mp_layers pattern): qkv/gate/up
  column-sharded, o/down row-sharded over 'tensor'; embeddings vocab-sharded.
- Sequence-parallel residual stream: activations carry P('data', 'sep')
  constraints between blocks when the mesh has a 'sep' axis (ref absent —
  SURVEY §5.7 new design).

The reference has no Llama in-tree (it lives in PaddleNLP, which builds on
the surveyed primitives: fleet mp_layers + fused_multi_transformer); this
implementation targets the same recipe surface.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from ..framework.core import Tensor
from ..framework.dispatch import apply_op
from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.layer_base import Layer
from ..nn.layer.common import Embedding, Linear
from ..nn.layer.container import LayerList
from ..parallel.api import shard_constraint
from ..tensor.manipulation import concat, reshape


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 128256
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-5
    rope_theta: float = 500000.0
    tie_word_embeddings: bool = False
    dtype: str = "bfloat16"
    # parallelism knobs
    context_parallel: bool = False  # ring attention over 'context' axis
    sequence_parallel: bool = False  # shard activations over 'sep'
    # with sequence_parallel: attention via Ulysses head<->seq all_to_all
    # on the 'sep' axis instead of GSPMD's gather (SURVEY §5.7 optional leg)
    ulysses_parallel: bool = False
    use_flash_attention: bool = True
    # fuse lm_head matmul + CE when forward() is given labels: chunked
    # logsumexp, never materializes [B,S,V] logits (ops/fused_ce.py)
    fused_lm_head_ce: bool = True
    # tokens per fused-CE chunk: bigger chunks beat scan overhead. v5e
    # bracketed A/B on the 509M bench step (2026-08-01): 16384 -> 0.690 /
    # 0.6815 MFU vs 8192 -> 0.6752 / 0.675 — adopted. Transient f32 [c, V]
    # logits = chunk*vocab*4 B; at vocab >~100k (llama3) consider 8192 via
    # PT_CE_CHUNK unless the lm-head/CE is vocab-sharded over 'tensor'.
    ce_chunk_size: int = 16384
    recompute: bool = False
    # Mixtral-style MoE FFN (0 = dense). Experts are SwiGLU of the dense
    # MLP's shape, stacked (E, d, d_ff) and sharded over the 'expert' mesh
    # axis; routing = GShard top-k with capacity buckets + load-balance aux
    # loss folded into the LM loss (ref incubate moe_layer.py integrated at
    # model level; the reference has no model-family MoE transformer)
    moe_num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_every: int = 1  # every Nth decoder layer gets the MoE FFN
    moe_aux_coeff: float = 0.01
    # training-side LoRA flag: rank > 0 wraps the projection Linears with
    # trainable A/B factors at construction (nn/lora.py). The SERVING
    # multi-adapter path is orthogonal — it threads pooled factors through
    # the paged programs per request and wants a CLEAN base model.
    lora_rank: int = 0
    lora_alpha: Optional[float] = None
    lora_targets: Optional[tuple] = None  # default: all seven projections


# attribute names attach_lora/merge_lora wrap when cfg.lora_targets is None
LLAMA_LORA_TARGETS = ("q_proj", "k_proj", "v_proj", "o_proj",
                      "gate_proj", "up_proj", "down_proj")


def llama3_8b_config(**kw) -> LlamaConfig:
    return LlamaConfig(**{**dict(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8), **kw})


def llama3_70b_config(**kw) -> LlamaConfig:
    return LlamaConfig(**{**dict(
        vocab_size=128256, hidden_size=8192, intermediate_size=28672,
        num_hidden_layers=80, num_attention_heads=64, num_key_value_heads=8), **kw})


def llama_tiny_config(**kw) -> LlamaConfig:
    return LlamaConfig(**{**dict(
        vocab_size=1024, hidden_size=256, intermediate_size=704,
        num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=4,
        max_position_embeddings=512, dtype="float32"), **kw})


# --------------------------------------------------------------------------- #
# RoPE
# --------------------------------------------------------------------------- #


def _rope_tables(head_dim: int, max_pos: int, theta: float):
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_pos, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # (S, D/2)
    return jnp.cos(freqs), jnp.sin(freqs)


def _rope_rotate(x, c, s):
    """Rotate pairs (x[..., :D/2], x[..., D/2:]) by pre-gathered c/s rows."""
    d2 = x.shape[-1] // 2
    xf1 = x[..., :d2].astype(jnp.float32)
    xf2 = x[..., d2:].astype(jnp.float32)
    out = jnp.concatenate([xf1 * c - xf2 * s, xf2 * c + xf1 * s], axis=-1)
    return out.astype(x.dtype)


def _apply_rope_rows(x, cos, sin, pos):
    """x: (B, 1, H, D), pos: int32 [B] — per-row rope rotation (continuous
    batching: each row sits at its own position)."""
    c = jnp.take(cos, pos, axis=0)[:, None, None, :]
    s = jnp.take(sin, pos, axis=0)[:, None, None, :]
    return _rope_rotate(x, c, s)


def _apply_rope_window(x, cos, sin, pos):
    """x: (B, W, H, D) at positions ``pos[:, None] + arange(W)`` with
    per-row int32 ``pos`` (speculative verify window: every slot's window
    starts at its own depth). Edge-clamped like :func:`_apply_rope_chunk`:
    rows past the table are masked window surplus the harvest discards."""
    W = x.shape[1]
    idx = jnp.clip(pos[:, None] + jnp.arange(W)[None, :], 0,
                   cos.shape[0] - 1)                     # (B, W)
    c = jnp.take(cos, idx, axis=0)[:, :, None, :]
    s = jnp.take(sin, idx, axis=0)[:, :, None, :]
    return _rope_rotate(x, c, s)


def _apply_rope_chunk(x, cos, sin, start):
    """x: (B, C, H, D) at positions ``start + arange(C)`` with traced
    ``start`` (chunked prefill). Per-row gather with edge-clamp instead of
    a dynamic_slice: a padded final chunk may overrun the rope table, and
    dynamic_slice would CLAMP the start down, mis-rotating the real
    positions — clamped rows here are only ever discarded padding."""
    S = x.shape[1]
    idx = jnp.clip(start + jnp.arange(S), 0, cos.shape[0] - 1)
    c = jnp.take(cos, idx, axis=0)[None, :, None, :]
    s = jnp.take(sin, idx, axis=0)[None, :, None, :]
    return _rope_rotate(x, c, s)


def _apply_rope(x, cos, sin, pos_offset=0):
    """x: (B, S, H, D); rotate pairs (x[..., :D/2], x[..., D/2:])."""
    S = x.shape[1]
    c = jax.lax.dynamic_slice_in_dim(cos, pos_offset, S, 0)[None, :, None, :]
    s = jax.lax.dynamic_slice_in_dim(sin, pos_offset, S, 0)[None, :, None, :]
    return _rope_rotate(x, c, s)


def _apply_rope_bhsd(x, cos, sin, pos_offset=0):
    """x: (B, H, S, D) — the kernel-native head-major layout."""
    S = x.shape[2]
    c = jax.lax.dynamic_slice_in_dim(cos, pos_offset, S, 0)[None, None, :, :]
    s = jax.lax.dynamic_slice_in_dim(sin, pos_offset, S, 0)[None, None, :, :]
    return _rope_rotate(x, c, s)


# --------------------------------------------------------------------------- #
# Context-parallel attention dispatch
# --------------------------------------------------------------------------- #


def _attn_island(axis, local, qr, kr, vv, head_divisible=False):
    """Shared scaffolding for attention shard_map islands.

    The sequence-axis collectives (``ppermute`` for the ring,
    ``all_to_all`` for Ulysses) need a *bound* mesh axis name. Inside an
    outer shard_map (manual-SPMD callers) the direct ``local`` call
    succeeds. Under GSPMD jit (ParallelEngine) no axis is bound, so when
    the active mesh carries ``axis`` we open a shard_map island: batch
    over 'data', sequence over ``axis``, heads over 'tensor' when present
    (CP×TP / SP×TP composition falls out of the head sharding). Returns
    None when the axis exists nowhere — the caller falls back to plain
    attention (single-device parity runs).

    ``head_divisible``: Ulysses additionally needs local head counts
    divisible by the axis size; an explicit user request that can't be
    honored warns instead of silently degrading.
    """
    try:
        # explicit binding probe: axis_index raises NameError iff `axis` is
        # not bound here. Probing with the tiny op (instead of running
        # `local` and catching ITS NameError) keeps a genuine NameError bug
        # inside the ring/Ulysses kernels loud instead of silently
        # rerouting to a different attention path (ADVICE r4).
        jax.lax.axis_index(axis)
        bound = True
    except NameError:
        bound = False
    if bound:
        return local(qr, kr, vv)  # already inside a shard_map binding axis
    from ..parallel.api import current_mesh, in_spmd_region

    mesh = current_mesh()
    if (mesh is None or axis not in mesh.shape or mesh.shape[axis] <= 1
            or not in_spmd_region()):
        return None
    tp = "tensor" if ("tensor" in mesh.shape and mesh.shape["tensor"] > 1) \
        else None
    if head_divisible:
        n = mesh.shape[axis]
        tpn = mesh.shape[tp] if tp else 1
        h, hkv = qr.shape[2], kr.shape[2]
        if h % tpn or hkv % tpn or (h // tpn) % n or (hkv // tpn) % n:
            import warnings

            warnings.warn(
                f"ulysses_parallel requested but head counts {h}/{hkv} are "
                f"not divisible by the '{axis}' axis ({n}"
                f"{f' x tensor {tpn}' if tp else ''}); falling back to "
                f"GSPMD attention", UserWarning)
            return None
    dp = "data" if "data" in mesh.shape else None
    spec = P(dp, axis, tp, None)
    from ..ops.flash_attention import _interpret

    # the pallas HLO interpreter's internal dynamic_slice doesn't propagate
    # varying-mesh-axes types; compiled runs keep the default check
    kw = {"check_vma": False} if _interpret() else {}
    return jax.shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, **kw)(qr, kr, vv)


def _ring_dispatch(qr, kr, vv, rep, use_flash, causal):
    """Ring attention over the 'context' axis (SURVEY §5.7 new design —
    the reference has no context parallelism at all, grep-verified)."""

    def local(a, b, c):
        from ..ops.flash_attention import _use_pallas
        from ..parallel.ring_attention import ring_attention_bshd
        from ..parallel.ring_flash_attention import ring_flash_attention_bshd

        if use_flash and _use_pallas():
            # Pallas blockwise kernels per ring hop, GQA-native
            return ring_flash_attention_bshd(a, b, c, "context", causal=causal)
        kx = jnp.repeat(b, rep, axis=2) if rep > 1 else b
        vx = jnp.repeat(c, rep, axis=2) if rep > 1 else c
        return ring_attention_bshd(a, kx, vx, "context", causal=causal)

    return _attn_island("context", local, qr, kr, vv)


def _ulysses_dispatch(qr, kr, vv, use_flash, causal):
    """Ulysses sequence parallelism at the model level (SURVEY §5.7
    optional leg; ref absent): all_to_all swaps the sharded dim seq→heads,
    full-sequence attention runs on the local head slice, and a second
    all_to_all swaps back. GQA needs no handling here — the flash kernel
    and the dense reference both route shared KV heads internally."""

    def attn_fn(a, b, c):
        from ..ops.flash_attention import _use_pallas, flash_attention_bshd

        if use_flash and _use_pallas():
            return flash_attention_bshd(a, b, c, causal=causal)
        from ..ops.flash_attention import _ref_bhsd

        out = _ref_bhsd(jnp.swapaxes(a, 1, 2), jnp.swapaxes(b, 1, 2),
                        jnp.swapaxes(c, 1, 2), causal,
                        1.0 / math.sqrt(a.shape[-1]))
        return jnp.swapaxes(out, 1, 2)

    def local(a, b, c):
        from ..parallel.ring_attention import ulysses_attention_bshd

        return ulysses_attention_bshd(a, b, c, "sep", causal=causal,
                                      attn_fn=attn_fn)

    return _attn_island("sep", local, qr, kr, vv, head_divisible=True)


# --------------------------------------------------------------------------- #
# Modules
# --------------------------------------------------------------------------- #


class LlamaRMSNorm(Layer):
    def __init__(self, hidden_size, eps):
        super().__init__()
        from ..nn.initializer import Constant

        self.weight = self.create_parameter([hidden_size],
                                            default_initializer=Constant(1.0))
        self.weight.pspec = P()
        self._eps = eps

    def forward(self, x):
        from ..ops.fused_norm import fused_rms_norm

        return apply_op(lambda v, w: fused_rms_norm(v, w, self._eps), x, self.weight,
                        op_name="rms_norm")


class LlamaAttention(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.num_heads = cfg.num_attention_heads
        self.num_kv_heads = cfg.num_key_value_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        h = cfg.hidden_size
        init = Normal(0.0, 0.02)
        self.q_proj = Linear(h, self.num_heads * self.head_dim, bias_attr=False,
                             weight_attr=init)
        self.k_proj = Linear(h, self.num_kv_heads * self.head_dim, bias_attr=False,
                             weight_attr=init)
        self.v_proj = Linear(h, self.num_kv_heads * self.head_dim, bias_attr=False,
                             weight_attr=init)
        self.o_proj = Linear(self.num_heads * self.head_dim, h, bias_attr=False,
                             weight_attr=init)
        # TP shardings (mp_layers pattern: column for qkv, row for o)
        self.q_proj.weight.pspec = P(None, "tensor")
        self.k_proj.weight.pspec = P(None, "tensor")
        self.v_proj.weight.pspec = P(None, "tensor")
        self.o_proj.weight.pspec = P("tensor", None)

    def _qkv(self, x, B, S, lora=None):
        """q/k/v projections. The int8 decode path can fuse the three into
        ONE concatenated matmul (quantize_int8 with PT_W8_FUSED_QKV=1 —
        single weight stream + kernel launch per step; see the measured
        A/B in BASELINE.md round 4). ``lora``: per-layer dict of gathered
        per-row (A, B, scale) factors keyed "q"/"k"/"v" (serving
        multi-adapter path) — the delta is additive AFTER the base
        projection, so it composes with both the fp and fused-int8
        branches."""
        if getattr(self, "_w8_split", None):
            from ..ops.int8 import w8_matmul

            nq, nk, nv = self._w8_split

            def qkv8(v, wq, s):
                o = w8_matmul(v, wq, s)
                return o[..., :nq], o[..., nq:nq + nk], o[..., nq + nk:]

            q, k, v = apply_op(qkv8, x, self.qkv_fused.weight_q,
                               self.qkv_fused.weight_scale,
                               op_name="w8_qkv")
        elif lora is not None:
            # base matmul + gathered delta fused into ONE op per projection
            # (a single Pallas program per row under use_pallas())
            from ..nn.lora import lora_matmul

            q = lora_matmul(x, self.q_proj.weight, lora.get("q"))
            k = lora_matmul(x, self.k_proj.weight, lora.get("k"))
            v = lora_matmul(x, self.v_proj.weight, lora.get("v"))
        else:
            q, k, v = self.q_proj(x), self.k_proj(x), self.v_proj(x)
        if lora is not None and getattr(self, "_w8_split", None):
            from ..nn.lora import bgmv

            if "q" in lora:
                q = q + bgmv(x, lora["q"])
            if "k" in lora:
                k = k + bgmv(x, lora["k"])
            if "v" in lora:
                v = v + bgmv(x, lora["v"])
        return (reshape(q, [B, S, self.num_heads, self.head_dim]),
                reshape(k, [B, S, self.num_kv_heads, self.head_dim]),
                reshape(v, [B, S, self.num_kv_heads, self.head_dim]))

    def _o_lora(self, out, lora):
        """Output projection plus the gathered per-row "o" delta, fused."""
        if lora is not None:
            from ..nn.lora import lora_matmul

            return lora_matmul(out, self.o_proj.weight, lora.get("o"))
        return self.o_proj(out)

    def forward(self, x, cos, sin, cache=None, pos_offset=0):
        B, S = x.shape[0], x.shape[1]
        q, k, v = self._qkv(x, B, S)

        def attn(qv, kv, vv, cv, sv, *cache_vals):
            qv = checkpoint_name(qv, "qkv")
            kv = checkpoint_name(kv, "qkv")
            vv = checkpoint_name(vv, "qkv")
            if (self.cfg.use_flash_attention and not cache_vals
                    and not self.cfg.context_parallel
                    and not (self.cfg.sequence_parallel
                             and self.cfg.ulysses_parallel)):
                # BHSD-NATIVE training path: swap to head-major BEFORE rope
                # so the layout change fuses into the rope elementwise (and
                # the inverse transposes fold into the o-proj/vjp dots) —
                # at S=16k the standalone (B,S,H,D)<->(B,H,S,D) copies
                # around the custom call were ~33% of the step (r5 per-op
                # profile, tools/profile_step.py)
                from ..ops.flash_attention import flash_attention

                qh = _apply_rope_bhsd(jnp.swapaxes(qv, 1, 2), cv, sv,
                                      pos_offset)
                kh = _apply_rope_bhsd(jnp.swapaxes(kv, 1, 2), cv, sv,
                                      pos_offset)
                out = flash_attention(qh, kh, jnp.swapaxes(vv, 1, 2),
                                      causal=True)
                return jnp.swapaxes(out, 1, 2)
            qr = _apply_rope(qv, cv, sv, pos_offset)
            kr = _apply_rope(kv, cv, sv, pos_offset)
            if cache_vals:
                ck, cvv = cache_vals
                kr = jnp.concatenate([ck, kr], axis=1)
                vv = jnp.concatenate([cvv, vv], axis=1)
            causal = cache_vals == ()
            rep = self.num_heads // self.num_kv_heads
            from ..ops.flash_attention import flash_attention_bshd

            if self.cfg.context_parallel and not cache_vals:
                ring_out = _ring_dispatch(qr, kr, vv, rep,
                                          self.cfg.use_flash_attention, causal)
                if ring_out is not None:
                    return ring_out
            if self.cfg.sequence_parallel and self.cfg.ulysses_parallel \
                    and not cache_vals:
                uly_out = _ulysses_dispatch(
                    qr, kr, vv, self.cfg.use_flash_attention, causal)
                if uly_out is not None:
                    return uly_out
            if self.cfg.use_flash_attention:
                # GQA handled inside the kernel (no KV repeat)
                return flash_attention_bshd(qr, kr, vv, causal=causal)
            if rep > 1:
                kr = jnp.repeat(kr, rep, axis=2)
                vv = jnp.repeat(vv, rep, axis=2)
            d = qr.shape[-1]
            logits = jnp.einsum("bshd,bthd->bhst", qr, kr).astype(jnp.float32) \
                / math.sqrt(d)
            if causal:
                mask = jnp.tril(jnp.ones((S, kr.shape[1]), bool), k=kr.shape[1] - S)
                logits = jnp.where(mask, logits, -1e30)
            p = jax.nn.softmax(logits, -1).astype(qr.dtype)
            return jnp.einsum("bhst,bthd->bshd", p, vv)

        args = [q, k, v, Tensor(cos), Tensor(sin)]
        if cache is not None:
            args += [cache[0], cache[1]]
        # remat-policy anchor (engine save_attn/offload_attn policies): the
        # flash output is the one S²-cost intermediate worth pinning — named
        # inside the op so eager decode pays no extra dispatch
        out = apply_op(lambda *a: checkpoint_name(attn(*a), "attn_out"),
                       *args, op_name="flash_attention")
        out = reshape(out, [B, S, self.num_heads * self.head_dim])
        out = self.o_proj(out)
        if self.cfg.sequence_parallel:
            out = shard_constraint(out, P("data", "sep", None))
        elif self.cfg.context_parallel:
            out = shard_constraint(out, P("data", "context", None))
        return out

    def prefill(self, x, cos, sin, ck, cv):
        """Prompt pass that fills the fixed decode caches at positions
        [0, S): ONE causal attention over the whole prompt (flash kernel
        when enabled) instead of S single-token decode steps — prompt
        processing at training-forward speed."""
        B, S = x.shape[0], x.shape[1]
        q, k, v = self._qkv(x, B, S)

        def step(qv, kv, vv, ckv, cvv, cosv, sinv):
            qr = _apply_rope(qv, cosv, sinv, 0)
            kr = _apply_rope(kv, cosv, sinv, 0)
            ckv = jax.lax.dynamic_update_slice(ckv, kr.astype(ckv.dtype),
                                               (0, 0, 0, 0))
            cvv = jax.lax.dynamic_update_slice(cvv, vv.astype(cvv.dtype),
                                               (0, 0, 0, 0))
            rep = self.num_heads // self.num_kv_heads
            if self.cfg.use_flash_attention:
                from ..ops.flash_attention import flash_attention_bshd

                out = flash_attention_bshd(qr, kr, vv, causal=True)
            else:
                kx = jnp.repeat(kr, rep, axis=2) if rep > 1 else kr
                vx = jnp.repeat(vv, rep, axis=2) if rep > 1 else vv
                d = qr.shape[-1]
                logits = jnp.einsum("bshd,bthd->bhst", qr, kx).astype(
                    jnp.float32) / math.sqrt(d)
                mask = jnp.tril(jnp.ones((S, S), bool))
                logits = jnp.where(mask, logits, -1e30)
                p = jax.nn.softmax(logits, -1).astype(qr.dtype)
                out = jnp.einsum("bhst,bthd->bshd", p, vx)
            return out, ckv, cvv

        out, ck, cv = apply_op(step, q, k, v, ck, cv, Tensor(cos), Tensor(sin),
                               op_name="prefill_attention")
        out = reshape(out, [B, S, self.num_heads * self.head_dim])
        return self.o_proj(out), ck, cv

    def decode(self, x, cos, sin, ck, cv, pos):
        """Single-token decode with a fixed-size KV cache: write the new
        K/V at ``pos`` via dynamic_update_slice (static shapes, so the whole
        generate loop compiles once) and attend over positions ≤ pos.
        ck/cv: Tensors (B, L, KV, D); pos: traced int32 scalar, or an int32
        [B] VECTOR of per-row positions (continuous-batching serving: every
        slot sits at its own depth — rope rows are gathered and cache writes
        scattered per row)."""
        B = x.shape[0]
        H, KV, D = self.num_heads, self.num_kv_heads, self.head_dim
        q, k, v = self._qkv(x, B, 1)

        def step(qv, kv, vv, ckv, cvv, cosv, sinv):
            vector_pos = jnp.ndim(pos) == 1
            if vector_pos:
                qr = _apply_rope_rows(qv, cosv, sinv, pos)
                kr = _apply_rope_rows(kv, cosv, sinv, pos)
                rows = jnp.arange(B)
                ckv = ckv.at[rows, pos].set(kr[:, 0].astype(ckv.dtype))
                cvv = cvv.at[rows, pos].set(vv[:, 0].astype(cvv.dtype))
            else:
                qr = _apply_rope(qv, cosv, sinv, pos)
                kr = _apply_rope(kv, cosv, sinv, pos)
                ckv = jax.lax.dynamic_update_slice(ckv, kr.astype(ckv.dtype),
                                                   (0, pos, 0, 0))
                cvv = jax.lax.dynamic_update_slice(cvv, vv.astype(cvv.dtype),
                                                   (0, pos, 0, 0))
            rep = H // KV
            L = ckv.shape[1]
            # GQA-native: group q heads by kv head — no L-sized cache copies
            qg = qr.reshape(B, 1, KV, rep, D)
            scores = jnp.einsum("bsgrd,btgd->bgrst", qg, ckv).astype(
                jnp.float32) / math.sqrt(D)
            if vector_pos:
                mask = (jnp.arange(L)[None, :] <=
                        pos[:, None])[:, None, None, None, :]
            else:
                mask = (jnp.arange(L) <= pos)[None, None, None, None, :]
            scores = jnp.where(mask, scores, -1e30)
            p = jax.nn.softmax(scores, -1).astype(qr.dtype)
            out = jnp.einsum("bgrst,btgd->bsgrd", p, cvv)
            return out.reshape(B, 1, H, D), ckv, cvv

        out, ck, cv = apply_op(step, q, k, v, ck, cv, Tensor(cos), Tensor(sin),
                               op_name="decode_attention")
        out = reshape(out, [B, 1, H * D])
        return self.o_proj(out), ck, cv

    def paged_decode(self, x, cos, sin, pool, block_tables, pos,
                     lora=None):
        """Single-token decode against the PAGED pool: K/V of the new token
        scatter through the block table at ``pos``; attention gathers
        context by table (ops/paged_attention.py). ``pool``: per-layer
        tuple of Tensors — ``(kp, vp)`` f32/bf16 pools
        (num_blocks, bs, KV, D), or ``(kq, ks, vq, vs)`` int8 pools + f32
        per-block-per-head scales (kv_quant="int8": dequant is fused into
        the attention, the pool is never materialized in full precision);
        block_tables: traced int32 (B, M); pos: traced int32 [B].
        Numerically mirrors the dense vector-pos ``decode`` so paged/dense
        greedy outputs agree token-exactly."""
        B = x.shape[0]
        H, D = self.num_heads, self.head_dim
        q, k, v = self._qkv(x, B, 1, lora=lora)

        if len(pool) == 4:
            def step(qv, kv, vv, kqv, ksv, vqv, vsv, cosv, sinv):
                from ..ops.paged_attention import (paged_decode_attention_q,
                                                   write_decode_kv_q)

                qr = _apply_rope_rows(qv, cosv, sinv, pos)
                kr = _apply_rope_rows(kv, cosv, sinv, pos)
                kqv, ksv, vqv, vsv = write_decode_kv_q(
                    kqv, ksv, vqv, vsv, kr[:, 0], vv[:, 0], block_tables, pos)
                out = paged_decode_attention_q(qr, kqv, ksv, vqv, vsv,
                                               block_tables, pos)
                return out, kqv, ksv, vqv, vsv
        else:
            def step(qv, kv, vv, kpv, vpv, cosv, sinv):
                from ..ops.paged_attention import (paged_decode_attention,
                                                   write_decode_kv)

                qr = _apply_rope_rows(qv, cosv, sinv, pos)
                kr = _apply_rope_rows(kv, cosv, sinv, pos)
                kpv, vpv = write_decode_kv(kpv, vpv, kr[:, 0], vv[:, 0],
                                           block_tables, pos)
                out = paged_decode_attention(qr, kpv, vpv, block_tables, pos)
                return out, kpv, vpv

        out, *pool = apply_op(step, q, k, v, *pool, Tensor(cos), Tensor(sin),
                              op_name="paged_decode_attention")
        out = reshape(out, [B, 1, H * D])
        return self._o_lora(out, lora), tuple(pool)

    def paged_verify_attn(self, x, cos, sin, pool, block_tables, pos,
                          lora=None):
        """Multi-token speculative VERIFY window against the paged pool:
        K/V for all W = k+1 window tokens scatter through the block table
        at ``pos..pos+k``; attention gathers context by table with the
        in-window causal mask (query j sees positions ≤ pos+j). x:
        (B, W, hidden); ``pool`` as in :meth:`paged_decode`; block_tables:
        traced int32 (B, M); pos: traced int32 [B]. At W = 1 this is
        numerically the paged ``decode`` — which is what makes greedy
        speculative output token-exact vs the dense server."""
        B, W = x.shape[0], x.shape[1]
        H, D = self.num_heads, self.head_dim
        q, k, v = self._qkv(x, B, W, lora=lora)

        if len(pool) == 4:
            def step(qv, kv, vv, kqv, ksv, vqv, vsv, cosv, sinv):
                from ..ops.paged_attention import (paged_verify_attention_q,
                                                   write_window_kv_q)

                qr = _apply_rope_window(qv, cosv, sinv, pos)
                kr = _apply_rope_window(kv, cosv, sinv, pos)
                kqv, ksv, vqv, vsv = write_window_kv_q(
                    kqv, ksv, vqv, vsv, kr, vv, block_tables, pos)
                out = paged_verify_attention_q(qr, kqv, ksv, vqv, vsv,
                                               block_tables, pos)
                return out, kqv, ksv, vqv, vsv
        else:
            def step(qv, kv, vv, kpv, vpv, cosv, sinv):
                from ..ops.paged_attention import (paged_verify_attention,
                                                   write_window_kv)

                qr = _apply_rope_window(qv, cosv, sinv, pos)
                kr = _apply_rope_window(kv, cosv, sinv, pos)
                kpv, vpv = write_window_kv(kpv, vpv, kr, vv, block_tables,
                                           pos)
                out = paged_verify_attention(qr, kpv, vpv, block_tables, pos)
                return out, kpv, vpv

        out, *pool = apply_op(step, q, k, v, *pool, Tensor(cos), Tensor(sin),
                              op_name="paged_verify_attention")
        out = reshape(out, [B, W, H * D])
        return self._o_lora(out, lora), tuple(pool)

    def paged_prefill_chunk(self, x, cos, sin, pool, block_table, start,
                            lora=None):
        """One fixed-size prefill CHUNK through the paged pool: queries sit
        at positions ``start + arange(C)`` (``start`` traced, block-aligned,
        C a multiple of the block size), their K/V scatter into consecutive
        table entries, and attention runs against ALL paged context written
        so far (earlier chunks + shared prefix blocks) with a causal mask.
        x: (1, C, hidden); ``pool`` as in :meth:`paged_decode`;
        block_table: traced int32 (M,)."""
        B, S = x.shape[0], x.shape[1]
        H, D = self.num_heads, self.head_dim
        q, k, v = self._qkv(x, B, S, lora=lora)

        if len(pool) == 4:
            def step(qv, kv, vv, kqv, ksv, vqv, vsv, cosv, sinv):
                from ..ops.paged_attention import (paged_prefill_attention_q,
                                                   write_chunk_kv_q)

                qr = _apply_rope_chunk(qv, cosv, sinv, start)
                kr = _apply_rope_chunk(kv, cosv, sinv, start)
                kqv, ksv, vqv, vsv = write_chunk_kv_q(
                    kqv, ksv, vqv, vsv, kr[0], vv[0], block_table, start)
                out = paged_prefill_attention_q(qr, kqv, ksv, vqv, vsv,
                                                block_table, start)
                return out, kqv, ksv, vqv, vsv
        else:
            def step(qv, kv, vv, kpv, vpv, cosv, sinv):
                from ..ops.paged_attention import (paged_prefill_attention,
                                                   write_chunk_kv)

                qr = _apply_rope_chunk(qv, cosv, sinv, start)
                kr = _apply_rope_chunk(kv, cosv, sinv, start)
                kpv, vpv = write_chunk_kv(kpv, vpv, kr[0], vv[0], block_table,
                                          start)
                out = paged_prefill_attention(qr, kpv, vpv, block_table, start)
                return out, kpv, vpv

        out, *pool = apply_op(step, q, k, v, *pool, Tensor(cos), Tensor(sin),
                              op_name="paged_prefill_attention")
        out = reshape(out, [B, S, H * D])
        return self._o_lora(out, lora), tuple(pool)


class LlamaMLP(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        init = Normal(0.0, 0.02)
        self.gate_proj = Linear(cfg.hidden_size, cfg.intermediate_size, bias_attr=False,
                                weight_attr=init)
        self.up_proj = Linear(cfg.hidden_size, cfg.intermediate_size, bias_attr=False,
                              weight_attr=init)
        self.down_proj = Linear(cfg.intermediate_size, cfg.hidden_size, bias_attr=False,
                                weight_attr=init)
        self.gate_proj.weight.pspec = P(None, "tensor")
        self.up_proj.weight.pspec = P(None, "tensor")
        self.down_proj.weight.pspec = P("tensor", None)
        self._sp = cfg.sequence_parallel
        self._cp = cfg.context_parallel

    def forward(self, x, lora=None):
        from ..nn.quant import Int8Linear

        if isinstance(self.gate_proj, Int8Linear):  # weight-only decode mode
            if lora is not None:
                raise NotImplementedError(
                    "pooled LoRA deltas on a weight-only int8 MLP are not "
                    "supported — serve LoRA over fp base weights (int8 KV "
                    "quant is fine)")
            from ..ops.int8 import w8_matmul

            def mlp8(v, wgq, sg, wuq, su, wdq, sd):
                h = jax.nn.silu(w8_matmul(v, wgq, sg)) * w8_matmul(v, wuq, su)
                return checkpoint_name(w8_matmul(h, wdq, sd), "mlp_out")

            out = apply_op(mlp8, x,
                           self.gate_proj.weight_q, self.gate_proj.weight_scale,
                           self.up_proj.weight_q, self.up_proj.weight_scale,
                           self.down_proj.weight_q, self.down_proj.weight_scale,
                           op_name="w8_mlp")
        elif lora is not None and any(k in lora for k in ("gate", "up", "down")):
            # decomposed SwiGLU with each base matmul + gathered per-row
            # delta fused into one op (one Pallas program per row under
            # use_pallas()); XLA re-fuses the chain inside the jitted
            # serving program
            from ..nn.lora import lora_matmul

            g = lora_matmul(x, self.gate_proj.weight, lora.get("gate"))
            u = lora_matmul(x, self.up_proj.weight, lora.get("up"))
            h = apply_op(lambda a, b: jax.nn.silu(a) * b, g, u,
                         op_name="swiglu")
            out = lora_matmul(h, self.down_proj.weight, lora.get("down"))
        elif not isinstance(self.gate_proj, Linear):
            # training-side LoRALinear wrap (attach_lora): go through the
            # layer calls so each projection applies its own A/B residual
            h = apply_op(lambda a, b: jax.nn.silu(a) * b,
                         self.gate_proj(x), self.up_proj(x), op_name="swiglu")
            out = apply_op(lambda v: checkpoint_name(v, "mlp_out"),
                           self.down_proj(h), op_name="mlp_out")
        else:
            def mlp(v, wg, wu, wd):
                out = jnp.matmul(jax.nn.silu(jnp.matmul(v, wg)) * jnp.matmul(v, wu), wd)
                return checkpoint_name(out, "mlp_out")

            out = apply_op(mlp, x, self.gate_proj.weight, self.up_proj.weight,
                           self.down_proj.weight, op_name="linear")
        if self._sp:
            out = shard_constraint(out, P("data", "sep", None))
        elif self._cp:
            out = shard_constraint(out, P("data", "context", None))
        return out


class LlamaMoEMLP(Layer):
    """MoE FFN slot-in for LlamaMLP: top-k routed SwiGLU experts over the
    'expert' mesh axis (SURVEY §2.3 EP at model level — parity test
    `tests/test_moe_llama.py`)."""

    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        from ..incubate.distributed.models.moe import MoELayer

        self.moe = MoELayer(d_model=cfg.hidden_size,
                            num_experts=cfg.moe_num_experts,
                            d_hidden=cfg.intermediate_size,
                            top_k=cfg.moe_top_k,
                            capacity_factor=cfg.moe_capacity_factor,
                            gated_experts=True)
        self._sp = cfg.sequence_parallel
        self._cp = cfg.context_parallel

    @property
    def aux_loss(self):
        return self.moe.gate.loss

    def forward(self, x, lora=None):
        if lora is not None:
            raise NotImplementedError(
                "pooled LoRA deltas are not supported on MoE FFN layers")
        out = self.moe(x)
        out = apply_op(lambda v: checkpoint_name(v, "mlp_out"), out,
                       op_name="moe_out")
        if self._sp:
            out = shard_constraint(out, P("data", "sep", None))
        elif self._cp:
            out = shard_constraint(out, P("data", "context", None))
        return out


class LlamaDecoderLayer(Layer):
    def __init__(self, cfg: LlamaConfig, layer_idx: int = 0):
        super().__init__()
        self.input_layernorm = LlamaRMSNorm(cfg.hidden_size, cfg.rms_norm_eps)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = LlamaRMSNorm(cfg.hidden_size, cfg.rms_norm_eps)
        use_moe = (cfg.moe_num_experts > 0
                   and layer_idx % max(cfg.moe_every, 1) == 0)
        self.mlp = LlamaMoEMLP(cfg) if use_moe else LlamaMLP(cfg)
        self._recompute = cfg.recompute

    def forward(self, x, cos, sin, cache=None, pos_offset=0):
        h = x + self.self_attn(self.input_layernorm(x), cos, sin, cache, pos_offset)
        out = h + self.mlp(self.post_attention_layernorm(h))
        return out

    def decode(self, x, cos, sin, ck, cv, pos):
        a, ck, cv = self.self_attn.decode(self.input_layernorm(x), cos, sin,
                                          ck, cv, pos)
        h = x + a
        out = h + self.mlp(self.post_attention_layernorm(h))
        return out, ck, cv

    def prefill(self, x, cos, sin, ck, cv):
        a, ck, cv = self.self_attn.prefill(self.input_layernorm(x), cos, sin,
                                           ck, cv)
        h = x + a
        out = h + self.mlp(self.post_attention_layernorm(h))
        return out, ck, cv

    def paged_decode(self, x, cos, sin, pool, block_tables, pos, lora=None):
        a, pool = self.self_attn.paged_decode(self.input_layernorm(x), cos,
                                              sin, pool, block_tables, pos,
                                              lora=lora)
        h = x + a
        out = h + self.mlp(self.post_attention_layernorm(h), lora=lora)
        return out, pool

    def paged_verify(self, x, cos, sin, pool, block_tables, pos, lora=None):
        a, pool = self.self_attn.paged_verify_attn(
            self.input_layernorm(x), cos, sin, pool, block_tables, pos,
            lora=lora)
        h = x + a
        out = h + self.mlp(self.post_attention_layernorm(h), lora=lora)
        return out, pool

    def paged_prefill_chunk(self, x, cos, sin, pool, block_table, start,
                            lora=None):
        a, pool = self.self_attn.paged_prefill_chunk(
            self.input_layernorm(x), cos, sin, pool, block_table, start,
            lora=lora)
        h = x + a
        out = h + self.mlp(self.post_attention_layernorm(h), lora=lora)
        return out, pool


class LlamaModel(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        from ..framework.dtype import convert_dtype

        if cfg.moe_num_experts > 0 and cfg.recompute:
            # the eager recompute wrapper (fleet/recompute PyLayer) replays
            # the forward under no_grad, so the gate.loss side-channel the
            # aux loss reads would be DETACHED — the router would silently
            # never learn. The compiled path is fine: use
            # ParallelEngine(remat=True), whose jax.checkpoint replays
            # differentiably.
            raise ValueError(
                "moe_num_experts > 0 with cfg.recompute=True detaches the "
                "load-balance aux loss in eager training; use "
                "ParallelEngine(remat=True) instead of cfg.recompute")
        self.embed_tokens = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.embed_tokens.weight.pspec = P("tensor", None)
        self.layers = LayerList([LlamaDecoderLayer(cfg, layer_idx=i)
                                 for i in range(cfg.num_hidden_layers)])
        self.norm = LlamaRMSNorm(cfg.hidden_size, cfg.rms_norm_eps)
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        cos, sin = _rope_tables(head_dim, cfg.max_position_embeddings, cfg.rope_theta)
        self._cos = cos
        self._sin = sin
        if cfg.dtype != "float32":
            self._convert_dtype(convert_dtype(cfg.dtype))

    def forward(self, input_ids, caches=None, pos_offset=0):
        x = self.embed_tokens(input_ids)
        if self.cfg.sequence_parallel:
            x = shard_constraint(x, P("data", "sep", None))
        elif self.cfg.context_parallel:
            x = shard_constraint(x, P("data", "context", None))
        for i, layer in enumerate(self.layers):
            cache = caches[i] if caches is not None else None
            if self._should_recompute():
                from ..distributed.fleet.recompute import recompute

                x = recompute(lambda v, l=layer: l(v, self._cos, self._sin, cache,
                                                   pos_offset), x)
            else:
                x = layer(x, self._cos, self._sin, cache, pos_offset)
        return self.norm(x)

    def decode_step(self, token, caches, pos):
        """token: Tensor (B, 1) int; caches: list of (ck, cv) Tensors per
        layer; pos: traced int32 scalar. Returns (normed hidden, new caches)."""
        x = self.embed_tokens(token)
        new = []
        for layer, (ck, cv) in zip(self.layers, caches):
            x, ck, cv = layer.decode(x, self._cos, self._sin, ck, cv, pos)
            new.append((ck, cv))
        return self.norm(x), new

    def prefill(self, input_ids, caches):
        """Fill the decode caches from the whole prompt in one forward;
        returns (normed hidden for ALL prompt positions, new caches)."""
        x = self.embed_tokens(input_ids)
        new = []
        for layer, (ck, cv) in zip(self.layers, caches):
            x, ck, cv = layer.prefill(x, self._cos, self._sin, ck, cv)
            new.append((ck, cv))
        return self.norm(x), new

    def paged_decode_step(self, token, pools, block_tables, pos, lora=None):
        """Paged continuous-batching decode: like :meth:`decode_step` but
        K/V read/write goes through per-row block tables into the shared
        block pool. token: Tensor (B, 1); pools: list of per-layer pool
        tuples — ``(kp, vp)`` Tensors (num_blocks, bs, KV, D), or
        ``(kq, ks, vq, vs)`` for the int8 pool (kv_quant="int8");
        block_tables: traced int32 (B, M); pos: traced int32 [B]; ``lora``:
        None or a per-layer list of gathered per-row adapter factors
        (``inference.lora.AdapterPool.gather_rows``) — all static shapes,
        so the multi-adapter program is the single-adapter program."""
        x = self.embed_tokens(token)
        new = []
        for i, (layer, pool) in enumerate(zip(self.layers, pools)):
            x, pool = layer.paged_decode(x, self._cos, self._sin, pool,
                                         block_tables, pos,
                                         lora=None if lora is None else lora[i])
            new.append(pool)
        return self.norm(x), new

    def paged_verify_step(self, tokens, pools, block_tables, pos, lora=None):
        """Speculative verify: score a WINDOW of W = k+1 tokens per row in
        one program — :meth:`paged_decode_step` generalized from 1 to W
        positions (W = 1 is plain decode). tokens: Tensor (B, W) = current
        token followed by the k drafted tokens, at positions
        ``pos[b] + arange(W)``; pools/block_tables/pos as in
        :meth:`paged_decode_step`. Returns (normed hidden (B, W, hidden),
        new pools) — the caller projects to logits for all W positions and
        runs rejection sampling."""
        x = self.embed_tokens(tokens)
        new = []
        for i, (layer, pool) in enumerate(zip(self.layers, pools)):
            x, pool = layer.paged_verify(x, self._cos, self._sin, pool,
                                         block_tables, pos,
                                         lora=None if lora is None else lora[i])
            new.append(pool)
        return self.norm(x), new

    def paged_prefill_chunk(self, input_ids, pools, block_table, start,
                            lora=None):
        """Stream ONE prompt chunk into the paged pool (chunked prefill:
        the same compiled program serves every chunk of every prompt
        length — no per-bucket compile family). input_ids: Tensor (1, C);
        start: traced int32 block-aligned chunk origin. Returns (normed
        hidden for the chunk, new pools)."""
        x = self.embed_tokens(input_ids)
        new = []
        for i, (layer, pool) in enumerate(zip(self.layers, pools)):
            x, pool = layer.paged_prefill_chunk(
                x, self._cos, self._sin, pool, block_table, start,
                lora=None if lora is None else lora[i])
            new.append(pool)
        return self.norm(x), new

    def _should_recompute(self):
        from ..framework.core import is_grad_enabled

        return self.cfg.recompute and self.training and is_grad_enabled()


class LlamaForCausalLM(Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__()
        self.cfg = cfg
        self.model = LlamaModel(cfg)
        if not cfg.tie_word_embeddings:
            init = Normal(0.0, 0.02)
            self.lm_head = Linear(cfg.hidden_size, cfg.vocab_size, bias_attr=False,
                                  weight_attr=init)
            self.lm_head.weight.pspec = P(None, "tensor")
            if cfg.dtype != "float32":
                from ..framework.dtype import convert_dtype

                self.lm_head._convert_dtype(convert_dtype(cfg.dtype))
        if cfg.lora_rank:
            self.attach_lora(cfg.lora_rank, alpha=cfg.lora_alpha,
                             targets=cfg.lora_targets)

    def attach_lora(self, rank, alpha=None, targets=None):
        """Wrap the projection Linears with trainable LoRA factors
        (nn/lora.py); ``targets`` defaults to all of
        :data:`LLAMA_LORA_TARGETS`. Base weights freeze; only A/B train."""
        from ..nn.lora import attach_lora

        return attach_lora(self, rank, alpha=alpha,
                           targets=targets or LLAMA_LORA_TARGETS)

    def merge_lora(self, targets=None):
        """Fold trained adapter deltas into the base weights and restore
        plain Linears — the dense-equivalent export the serving exactness
        tests compare against."""
        from ..nn.lora import merge_lora

        return merge_lora(self, targets=targets or LLAMA_LORA_TARGETS)

    def _moe_aux(self):
        """Sum of the MoE gates' load-balance losses from the last forward
        (None for dense configs)."""
        total = None
        for layer in self.model.layers:
            aux = getattr(layer.mlp, "aux_loss", None)
            if aux is not None:
                total = aux if total is None else total + aux
        return total

    def forward(self, input_ids, labels=None):
        h = self.model(input_ids)
        if labels is not None and self.cfg.fused_lm_head_ce:
            from ..ops.fused_ce import fused_linear_cross_entropy

            tied = self.cfg.tie_word_embeddings
            w = self.model.embed_tokens.weight if tied else self.lm_head.weight
            from ..ops.fused_ce import capped_chunk_size

            chunk = capped_chunk_size(self.cfg.ce_chunk_size,
                                      input_ids.shape[1])
            loss = apply_op(
                lambda hv, wv, lv: fused_linear_cross_entropy(
                    hv, wv, lv, chunk_size=chunk, transpose_weight=tied),
                h, w, labels, op_name="fused_linear_cross_entropy")
            aux = self._moe_aux()
            if aux is not None:
                loss = loss + self.cfg.moe_aux_coeff * aux
            return loss
        if self.cfg.tie_word_embeddings:
            logits = apply_op(lambda v, w: jnp.matmul(v, w.T), h,
                              self.model.embed_tokens.weight)
        else:
            logits = self.lm_head(h)
        if labels is None:
            return logits
        loss = self.loss_fn(logits, labels)
        aux = self._moe_aux()
        if aux is not None:
            loss = loss + self.cfg.moe_aux_coeff * aux
        return loss

    def loss_fn(self, logits, labels):
        """Next-token CE with fp32 softmax (ParallelCrossEntropy math).

        MoE configs: the gates' load-balance aux loss (recorded by the
        forward that produced ``logits``) is folded in here too, so
        ``ParallelEngine(loss_fn=model.loss_fn)`` trains the router. A
        fully external loss_fn must add ``cfg.moe_aux_coeff *
        model._moe_aux()`` itself or the routing degenerates."""
        loss = F.cross_entropy(logits, labels, reduction="mean")
        aux = self._moe_aux()
        if aux is not None:
            loss = loss + self.cfg.moe_aux_coeff * aux
        return loss

    def quantize_int8(self):
        """Convert every projection (q/k/v/o, gate/up/down, lm_head) to
        weight-only int8 for decode (ref fused_multi_transformer_int8 /
        weight-only PTQ; TPU rationale in ops/int8.py: decode tokens/s is
        HBM-bound on parameter bytes, int8 halves them). Embedding stays in
        the model dtype (it is gathered, not matmul'd). In-place; returns
        self. Use for inference only — int8 weights do not train."""
        import os

        from ..nn.quant import Int8Linear
        from ..ops.int8 import quantize_per_channel

        fuse_qkv = os.environ.get("PT_W8_FUSED_QKV") == "1"
        for layer in self.model.layers:
            att, mlp = layer.self_attn, layer.mlp
            if isinstance(mlp, LlamaMoEMLP):
                # MoE experts stay in the model dtype: the stacked einsum
                # path has no per-expert int8 kernel yet (routing keeps the
                # active weight bytes at K/E of the dense equivalent anyway)
                mlp = None
            if fuse_qkv:
                # one [K, Nq+Nk+Nv] int8 weight (per-channel scales are
                # column-independent, so fused == separate numerically);
                # the bf16 projections are dropped from the module tree so
                # the decode weight stream isn't paid twice
                wcat = jnp.concatenate(
                    [att.q_proj.weight.value, att.k_proj.weight.value,
                     att.v_proj.weight.value], axis=1)
                w_q, sc = quantize_per_channel(wcat)
                att._w8_split = (int(att.q_proj.weight.shape[1]),
                                 int(att.k_proj.weight.shape[1]),
                                 int(att.v_proj.weight.shape[1]))
                att.qkv_fused = Int8Linear(w_q, sc)
                att.q_proj = att.k_proj = att.v_proj = None
                att.o_proj = Int8Linear.from_linear(att.o_proj)
            else:
                for name in ("q_proj", "k_proj", "v_proj", "o_proj"):
                    setattr(att, name,
                            Int8Linear.from_linear(getattr(att, name)))
            if mlp is not None:
                for name in ("gate_proj", "up_proj", "down_proj"):
                    setattr(mlp, name,
                            Int8Linear.from_linear(getattr(mlp, name)))
        if not self.cfg.tie_word_embeddings:
            self.lm_head = Int8Linear.from_linear(self.lm_head)
        self._gen_cache = {}  # old compiled loops close over bf16 params
        return self

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0, seed: int = 0,
                 eos_token_id: Optional[int] = None, num_beams: int = 1,
                 length_penalty: float = 0.0):
        """Autoregressive generation with a compiled single-token decode loop
        (PaddleNLP `model.generate` surface; greedy when temperature == 0).

        TPU-native design: fixed-size KV caches (B, P+N, KV, D) updated via
        dynamic_update_slice, one lax.scan over P+N-1 steps covering prefill
        and decode uniformly — the whole loop is ONE compiled program, no
        per-step dispatch and no dynamic shapes. Returns (B, P+N) int32 of
        prompt + generated tokens.
        """
        from ..framework.dtype import convert_dtype
        from ..jit import functional_call
        from .generation import compiled_cached_generate

        cfg = self.cfg
        kv = cfg.num_key_value_heads
        d = cfg.hidden_size // cfg.num_attention_heads
        cdtype = convert_dtype(cfg.dtype)
        model = self

        def make_caches(B, L):
            flat = []
            for _ in range(cfg.num_hidden_layers):
                flat += [jnp.zeros((B, L, kv, d), cdtype),
                         jnp.zeros((B, L, kv, d), cdtype)]
            return flat

        def head(h):
            if cfg.tie_word_embeddings:
                return apply_op(lambda v, w: jnp.matmul(v, w.T), h,
                                model.model.embed_tokens.weight)
            return model.lm_head(h)

        def run_one(p, tok, flat_caches, pos):
            caches = [(Tensor(flat_caches[2 * i]), Tensor(flat_caches[2 * i + 1]))
                      for i in range(cfg.num_hidden_layers)]

            def call():
                h, new = model.model.decode_step(Tensor(tok), caches, pos)
                return head(h), new

            logits, new = functional_call(model, p, call_fn=lambda: call())
            flat = []
            for ck, cv in new:
                flat += [ck.value, cv.value]
            return logits.value[:, 0], flat

        def prefill_fn(p, prompt, flat_caches):
            caches = [(Tensor(flat_caches[2 * i]), Tensor(flat_caches[2 * i + 1]))
                      for i in range(cfg.num_hidden_layers)]

            def call():
                h, new = model.model.prefill(Tensor(prompt), caches)
                return head(h[:, -1:]), new  # logits only for the last token

            logits, new = functional_call(model, p, call_fn=call)
            flat = []
            for ck, cv in new:
                flat += [ck.value, cv.value]
            return logits.value[:, 0], flat

        if num_beams > 1:
            if temperature or top_k:
                import warnings

                warnings.warn(
                    "num_beams > 1 uses deterministic beam search; "
                    "temperature/top_k/seed are ignored", UserWarning)
            from .generation import compiled_beam_search

            return compiled_beam_search(
                self, input_ids, num_beams=num_beams,
                max_new_tokens=max_new_tokens, eos_token_id=eos_token_id,
                length_penalty=length_penalty, make_caches=make_caches,
                run_one=run_one, prefill=prefill_fn,
                max_positions=cfg.max_position_embeddings)
        return compiled_cached_generate(
            self, input_ids, max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p, seed=seed,
            eos_token_id=eos_token_id, make_caches=make_caches,
            run_one=run_one, prefill=prefill_fn,
            max_positions=cfg.max_position_embeddings)


def llama_pretrain_loss(model: LlamaForCausalLM, input_ids, labels):
    return model(input_ids, labels)
