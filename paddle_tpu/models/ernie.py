"""ERNIE/BERT-style encoder (BASELINE config 2: ERNIE-3.0 base finetune).

Built on the nn.TransformerEncoder stack (ref python/paddle/nn/layer/
transformer.py) — the same composition PaddleNLP's ErnieModel uses.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ..nn import functional as F
from ..nn.initializer import Normal
from ..nn.layer_base import Layer
from ..nn.layer.common import Dropout, Embedding, Linear
from ..nn.layer.norm import LayerNorm
from ..nn.layer.transformer import TransformerEncoder, TransformerEncoderLayer


@dataclasses.dataclass
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 2048
    type_vocab_size: int = 4
    layer_norm_eps: float = 1e-12


def ernie_tiny_config(**kw):
    return ErnieConfig(**{**dict(vocab_size=1024, hidden_size=128, num_hidden_layers=2,
                                 num_attention_heads=4, intermediate_size=512,
                                 hidden_dropout_prob=0.0,
                                 attention_probs_dropout_prob=0.0), **kw})


class ErnieEmbeddings(Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size)
        self.position_embeddings = Embedding(cfg.max_position_embeddings, cfg.hidden_size)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size, cfg.hidden_size)
        self.layer_norm = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        import paddle_tpu as paddle

        S = input_ids.shape[1]
        pos = paddle.arange(S, dtype="int64")
        emb = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            emb = emb + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(emb))


class ErnieModel(Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.cfg = cfg
        self.embeddings = ErnieEmbeddings(cfg)
        enc_layer = TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_attention_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation=cfg.hidden_act,
            attn_dropout=cfg.attention_probs_dropout_prob, normalize_before=False)
        self.encoder = TransformerEncoder(enc_layer, cfg.num_hidden_layers)
        self.pooler = Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        x = self.encoder(x, attention_mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class ErnieForSequenceClassification(Layer):
    def __init__(self, cfg: ErnieConfig, num_classes=2, dropout=None):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.dropout = Dropout(dropout if dropout is not None
                               else cfg.hidden_dropout_prob)
        self.classifier = Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None):
        _, pooled = self.ernie(input_ids, token_type_ids)
        return self.classifier(self.dropout(pooled))

    def loss_fn(self, logits, labels):
        return F.cross_entropy(logits, labels, reduction="mean")


class ErnieForTokenClassification(Layer):
    """Per-token head (NER etc.; ref PaddleNLP ErnieForTokenClassification)."""

    def __init__(self, cfg: ErnieConfig, num_classes=2, dropout=None):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.dropout = Dropout(dropout if dropout is not None
                               else cfg.hidden_dropout_prob)
        self.classifier = Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None):
        seq, _ = self.ernie(input_ids, token_type_ids)
        return self.classifier(self.dropout(seq))

    def loss_fn(self, logits, labels):
        import paddle_tpu as paddle

        return F.cross_entropy(paddle.reshape(logits, [-1, logits.shape[-1]]),
                               paddle.reshape(labels, [-1]), reduction="mean")


class ErnieForQuestionAnswering(Layer):
    """Span head: start/end logits (ref ErnieForQuestionAnswering)."""

    def __init__(self, cfg: ErnieConfig, dropout=None):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.dropout = Dropout(dropout if dropout is not None
                               else cfg.hidden_dropout_prob)
        self.classifier = Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None):
        seq, _ = self.ernie(input_ids, token_type_ids)
        logits = self.classifier(self.dropout(seq))
        import paddle_tpu as paddle

        start, end = paddle.split(logits, 2, axis=-1)
        return paddle.squeeze(start, -1), paddle.squeeze(end, -1)

    def loss_fn(self, start_logits, end_logits, start_pos, end_pos):
        l1 = F.cross_entropy(start_logits, start_pos, reduction="mean")
        l2 = F.cross_entropy(end_logits, end_pos, reduction="mean")
        return (l1 + l2) / 2


class ErnieLMHead(Layer):
    """Masked-LM transform + decoder tied to the word embedding."""

    def __init__(self, cfg: ErnieConfig, embedding_weight):
        super().__init__()
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size)
        self.norm = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self._act = getattr(F, cfg.hidden_act)
        self._embed = embedding_weight  # tied (not a new parameter)
        self.bias = self.create_parameter([cfg.vocab_size], is_bias=True)

    def forward(self, x):
        from ..framework.dispatch import apply_op

        h = self.norm(self._act(self.transform(x)))
        return apply_op(lambda v, w, b: jnp.matmul(v, w.T) + b,
                        h, self._embed, self.bias, op_name="ernie_lm_logits")


class ErnieForMaskedLM(Layer):
    """ref ErnieForMaskedLM / ErnieForPretraining's MLM half."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.lm_head = ErnieLMHead(
            cfg, self.ernie.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None):
        seq, _ = self.ernie(input_ids, token_type_ids)
        return self.lm_head(seq)

    def loss_fn(self, logits, labels, ignore_index=-100):
        import paddle_tpu as paddle

        return F.cross_entropy(paddle.reshape(logits, [-1, logits.shape[-1]]),
                               paddle.reshape(labels, [-1]),
                               ignore_index=ignore_index, reduction="mean")
