"""Generic autoregressive generation for causal LMs (PaddleNLP
`model.generate` surface).

Model-agnostic strategy: keep a fixed (B, L) token buffer and, per step,
re-run the FULL causal forward on the buffer, reading logits at the current
position — causal masking guarantees positions ≤ t ignore the padding
beyond t, so no KV-cache plumbing is needed. The loop is one lax.scan, so
the whole generation compiles once; cost is O(L) forwards of length L
(fine for short-to-medium generations; models with a cached decode path,
e.g. Llama, override generate with the O(L) cached version).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def next_token(logits, rng, temperature: float, top_k: int):
    """Sample/argmax one token per row from (B, V) logits. Shared by every
    generate implementation so sampling semantics can't drift."""
    if temperature and temperature > 0:
        rng, sub = jax.random.split(rng)
        lg = logits.astype(jnp.float32) / temperature
        if top_k and top_k > 0:
            kth = jnp.sort(lg, axis=-1)[:, -top_k][:, None]
            lg = jnp.where(lg < kth, -1e30, lg)
        return jax.random.categorical(sub, lg, axis=-1).astype(jnp.int32), rng
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), rng


def advance_tokens(toks, done, nxt, t, prompt_len: int, total_len: int,
                   eos_token_id: Optional[int]):
    """Write the step-t output token into the buffer: within the prompt the
    'next' token is the given one (teacher forcing); after eos, keep
    emitting eos."""
    given = t + 1 < prompt_len
    at = jnp.minimum(t + 1, total_len - 1)
    cur = jax.lax.dynamic_slice_in_dim(toks, at, 1, 1)[:, 0]
    nxt = jnp.where(given, cur, nxt)
    if eos_token_id is not None:
        nxt = jnp.where(done, eos_token_id, nxt)
        done = done | ((nxt == eos_token_id) & jnp.logical_not(given))
    toks = jax.lax.dynamic_update_slice(toks, nxt[:, None], (0, at))
    return toks, done


class GenerationMixin:
    """Mixin for Layer models whose forward(input_ids) returns logits
    (B, S, V) with causal semantics."""

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 eos_token_id: Optional[int] = None):
        import numpy as _np

        from ..framework.core import Tensor, to_array
        from ..jit import functional_call, state_values

        ids = _np.asarray(to_array(input_ids))
        B, P = ids.shape
        L = P + max_new_tokens
        max_pos = getattr(getattr(self, "cfg", None), "max_position_embeddings",
                          None)
        if max_pos is not None and L > max_pos:
            raise ValueError(f"prompt+new tokens {L} exceeds "
                             f"max_position_embeddings {max_pos}")
        params = state_values(self)
        model = self

        def logits_at(p, toks, t):
            out = functional_call(model, p, Tensor(toks))
            out = out[0] if isinstance(out, (list, tuple)) else out
            row = jax.lax.dynamic_slice_in_dim(out.value, t, 1, 1)
            return row[:, 0]

        def gen_fn(p, prompt, rng):
            toks = jnp.concatenate(
                [prompt, jnp.zeros((B, max_new_tokens), jnp.int32)], axis=1)
            done = jnp.zeros((B,), bool)

            def body(carry, t):
                toks, done, rng = carry
                logits = logits_at(p, toks, t)
                nxt, rng = next_token(logits, rng, temperature, top_k)
                toks, done = advance_tokens(toks, done, nxt, t, P, L,
                                            eos_token_id)
                return (toks, done, rng), None

            # no KV cache here, every step re-reads the full buffer — so the
            # prompt needs no warm-up iterations; start at the last prompt
            # position instead of 0
            (toks, _, _), _ = jax.lax.scan(body, (toks, done, rng),
                                           jnp.arange(P - 1, L - 1))
            return toks

        key = (B, P, max_new_tokens, float(temperature or 0.0),
               int(top_k or 0), eos_token_id)
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        if key not in cache:
            cache[key] = jax.jit(gen_fn)
        was_training = getattr(self, "training", False)
        self.eval()  # dropout etc. must be off — a traced dropout key would
        try:         # leak into the global RNG state
            out = cache[key](params, jnp.asarray(ids, jnp.int32),
                             jax.random.PRNGKey(seed))
        finally:
            if was_training:
                self.train()
        from ..framework.core import Tensor as T

        return T(out)


def compiled_cached_generate(model, input_ids, *, max_new_tokens, temperature,
                             top_k, seed, eos_token_id, make_caches, run_one,
                             prefill=None, max_positions=None, extra_key=()):
    """Shared prefill+decode loop for models WITH a cached decode_step
    (Llama, GPT): fixed-size KV caches, one lax.scan over the decode steps,
    the whole generation compiled once per static config.

    make_caches(B, L) -> flat list of cache arrays.
    run_one(params, tok[B,1], flat_caches, pos) -> ((B,V) logits, flat).
    prefill(params, prompt[B,P], flat_caches) -> ((B,V) logits at P-1, flat):
    optional whole-prompt pass (flash attention) that fills cache positions
    [0, P) in ONE forward; without it the prompt is teacher-forced through
    P-1 single-token decode steps.
    Mirrors the reference's fused decode loop (fused_multi_transformer) as a
    single compiled scan instead of a per-step CUDA op."""
    import numpy as _np

    from ..framework.core import Tensor, to_array
    from ..jit import state_values

    ids = _np.asarray(to_array(input_ids))
    B, P = ids.shape  # noqa: N806
    L = P + max_new_tokens
    if max_positions is not None and L > max_positions:
        raise ValueError(f"prompt+new tokens {L} exceeds "
                         f"max_position_embeddings {max_positions}")
    params = state_values(model)

    def gen_fn(p, prompt, rng):
        caches = make_caches(B, L)
        toks = jnp.concatenate(
            [prompt, jnp.zeros((B, max_new_tokens), jnp.int32)], axis=1)
        done = jnp.zeros((B,), bool)
        start = 0
        # prefill needs a real prompt AND at least one token to emit — with
        # max_new_tokens == 0 the sampled token would overwrite toks[:, P-1]
        if prefill is not None and P > 1 and max_new_tokens > 0:
            logits, caches = prefill(p, prompt, caches)
            nxt, rng = next_token(logits, rng, temperature, top_k)
            toks, done = advance_tokens(toks, done, nxt, P - 1, P, L,
                                        eos_token_id)
            start = P  # positions [0, P) are in the caches already

        def body(carry, t):
            toks, caches, done, rng = carry
            tok = jax.lax.dynamic_slice_in_dim(toks, t, 1, 1)
            logits, caches = run_one(p, tok, caches, t)
            nxt, rng = next_token(logits, rng, temperature, top_k)
            toks, done = advance_tokens(toks, done, nxt, t, P, L,
                                        eos_token_id)
            return (toks, caches, done, rng), None

        (toks, _, _, _), _ = jax.lax.scan(
            body, (toks, caches, done, rng), jnp.arange(start, L - 1))
        return toks

    key = (B, P, max_new_tokens, float(temperature or 0.0), int(top_k or 0),
           eos_token_id, prefill is not None, tuple(extra_key))
    cache = getattr(model, "_gen_cache", None)
    if cache is None:
        cache = model._gen_cache = {}
    if key not in cache:
        cache[key] = jax.jit(gen_fn)
    was_training = getattr(model, "training", False)
    model.eval()  # stochastic layers must be off under the trace
    try:
        out = cache[key](params, jnp.asarray(ids, jnp.int32),
                         jax.random.PRNGKey(seed))
    finally:
        if was_training:
            model.train()
    return Tensor(out)
