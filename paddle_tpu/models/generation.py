"""Generic autoregressive generation for causal LMs (PaddleNLP
`model.generate` surface).

Model-agnostic strategy: keep a fixed (B, L) token buffer and, per step,
re-run the FULL causal forward on the buffer, reading logits at the current
position — causal masking guarantees positions ≤ t ignore the padding
beyond t, so no KV-cache plumbing is needed. The loop is one lax.scan, so
the whole generation compiles once; cost is O(L) forwards of length L
(fine for short-to-medium generations; models with a cached decode path,
e.g. Llama, override generate with the O(L) cached version).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def next_token(logits, rng, temperature: float, top_k: int,
               top_p: float = 0.0):
    """Sample/argmax one token per row from (B, V) logits. Shared by every
    generate implementation so sampling semantics can't drift. ``top_p``
    applies nucleus filtering (keep the smallest prefix of the sorted
    distribution whose mass reaches p) after top_k."""
    if temperature and temperature > 0:
        rng, sub = jax.random.split(rng)
        lg = logits.astype(jnp.float32) / temperature
        if top_k and top_k > 0:
            kth = jnp.sort(lg, axis=-1)[:, -top_k][:, None]
            lg = jnp.where(lg < kth, -1e30, lg)
        if top_p and 0 < top_p < 1:
            srt = jnp.sort(lg, axis=-1)[:, ::-1]  # descending
            probs = jax.nn.softmax(srt, axis=-1)
            cdf = jnp.cumsum(probs, axis=-1)
            # keep tokens while the mass BEFORE them is < p (always >= 1)
            keep = jnp.concatenate(
                [jnp.ones((lg.shape[0], 1), bool), cdf[:, :-1] < top_p],
                axis=-1)
            cutoff = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1)[:, None]
            lg = jnp.where(lg < cutoff, -1e30, lg)
        return jax.random.categorical(sub, lg, axis=-1).astype(jnp.int32), rng
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), rng


def filtered_logits_rows(logits, temps, top_ks, top_ps):
    """Per-row temperature-scaled, top-k/top-p-filtered logits — the
    filtering core shared by :func:`sample_token_rows` (decode tick) and
    the speculative verify's target distribution
    (``inference/speculative.py``), factored out so the two can never
    drift. Filtered-out entries are ``-1e30``; rows with temp 0 are
    meaningful only through their argmax (callers keep a greedy branch)."""
    lg = logits.astype(jnp.float32) / jnp.maximum(temps, 1e-6)[:, None]
    V = lg.shape[-1]
    srt = jnp.sort(lg, axis=-1)[:, ::-1]            # descending
    kidx = jnp.clip(top_ks - 1, 0, V - 1)
    kth = jnp.take_along_axis(srt, kidx[:, None], axis=-1)
    lg = jnp.where((top_ks > 0)[:, None] & (lg < kth), -1e30, lg)
    # nucleus over the top-k-FILTERED logits (next_token ordering)
    srt = jnp.sort(lg, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(srt, axis=-1)
    cdf = jnp.cumsum(probs, axis=-1)
    keep = jnp.concatenate(
        [jnp.ones((lg.shape[0], 1), bool), cdf[:, :-1] < top_ps[:, None]],
        axis=-1)
    cutoff = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1)[:, None]
    nucleus = ((top_ps > 0) & (top_ps < 1))[:, None]
    return jnp.where(nucleus & (lg < cutoff), -1e30, lg)


def filtered_probs_rows(logits, temps, top_ks, top_ps):
    """Softmax of :func:`filtered_logits_rows` — the exact distribution a
    sampled row draws from, as probabilities. This is the ``p`` of
    speculative rejection sampling: accepting against it makes the
    speculative output distribution provably equal to the dense tick's."""
    return jax.nn.softmax(filtered_logits_rows(logits, temps, top_ks,
                                               top_ps), axis=-1)


def sample_token_rows(logits, key, temps, top_ks, top_ps):
    """Per-ROW ``next_token`` for the serving decode tick: row ``i`` uses
    ``temps[i]`` (0 → greedy argmax), ``top_ks[i]`` (0 → off) and
    ``top_ps[i]`` (0 → off) — the same filtering math as :func:`next_token`
    (top-k cutoff at the k-th largest, then nucleus over the filtered
    distribution), vectorized so one compiled tick can mix greedy and
    sampled slots. ``logits``: (B, V); temps/top_ps float32 [B], top_ks
    int32 [B]; ``key`` is consumed directly (the server folds a fresh key
    per tick)."""
    lg = filtered_logits_rows(logits, temps, top_ks, top_ps)
    sampled = jax.random.categorical(key, lg, axis=-1).astype(jnp.int32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jnp.where(temps > 0, sampled, greedy)


def advance_tokens(toks, done, nxt, t, prompt_len: int, total_len: int,
                   eos_token_id: Optional[int]):
    """Write the step-t output token into the buffer: within the prompt the
    'next' token is the given one (teacher forcing); after eos, keep
    emitting eos."""
    given = t + 1 < prompt_len
    at = jnp.minimum(t + 1, total_len - 1)
    cur = jax.lax.dynamic_slice_in_dim(toks, at, 1, 1)[:, 0]
    nxt = jnp.where(given, cur, nxt)
    if eos_token_id is not None:
        nxt = jnp.where(done, eos_token_id, nxt)
        done = done | ((nxt == eos_token_id) & jnp.logical_not(given))
    toks = jax.lax.dynamic_update_slice(toks, nxt[:, None], (0, at))
    return toks, done


class GenerationMixin:
    """Mixin for Layer models whose forward(input_ids) returns logits
    (B, S, V) with causal semantics."""

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0, seed: int = 0,
                 eos_token_id: Optional[int] = None):
        import numpy as _np

        from ..framework.core import Tensor, to_array
        from ..jit import functional_call, state_values

        ids = _np.asarray(to_array(input_ids))
        B, P = ids.shape
        L = P + max_new_tokens
        max_pos = getattr(getattr(self, "cfg", None), "max_position_embeddings",
                          None)
        if max_pos is not None and L > max_pos:
            raise ValueError(f"prompt+new tokens {L} exceeds "
                             f"max_position_embeddings {max_pos}")
        params = state_values(self)
        model = self

        def logits_at(p, toks, t):
            out = functional_call(model, p, Tensor(toks))
            out = out[0] if isinstance(out, (list, tuple)) else out
            row = jax.lax.dynamic_slice_in_dim(out.value, t, 1, 1)
            return row[:, 0]

        def gen_fn(p, prompt, rng):
            toks = jnp.concatenate(
                [prompt, jnp.zeros((B, max_new_tokens), jnp.int32)], axis=1)
            done = jnp.zeros((B,), bool)

            def body(carry, t):
                toks, done, rng = carry
                logits = logits_at(p, toks, t)
                nxt, rng = next_token(logits, rng, temperature, top_k, top_p)
                toks, done = advance_tokens(toks, done, nxt, t, P, L,
                                            eos_token_id)
                return (toks, done, rng), None

            # no KV cache here, every step re-reads the full buffer — so the
            # prompt needs no warm-up iterations; start at the last prompt
            # position instead of 0
            (toks, _, _), _ = jax.lax.scan(body, (toks, done, rng),
                                           jnp.arange(P - 1, L - 1))
            return toks

        key = (B, P, max_new_tokens, float(temperature or 0.0),
               int(top_k or 0), eos_token_id)
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        if key not in cache:
            cache[key] = jax.jit(gen_fn)
        was_training = getattr(self, "training", False)
        self.eval()  # dropout etc. must be off — a traced dropout key would
        try:         # leak into the global RNG state
            out = cache[key](params, jnp.asarray(ids, jnp.int32),
                             jax.random.PRNGKey(seed))
        finally:
            if was_training:
                self.train()
        from ..framework.core import Tensor as T

        return T(out)


def compiled_cached_generate(model, input_ids, *, max_new_tokens, temperature,
                             top_k, seed, eos_token_id, make_caches, run_one,
                             prefill=None, max_positions=None, extra_key=(),
                             top_p: float = 0.0):
    """Shared prefill+decode loop for models WITH a cached decode_step
    (Llama, GPT): fixed-size KV caches, one lax.scan over the decode steps,
    the whole generation compiled once per static config.

    make_caches(B, L) -> flat list of cache arrays.
    run_one(params, tok[B,1], flat_caches, pos) -> ((B,V) logits, flat).
    prefill(params, prompt[B,P], flat_caches) -> ((B,V) logits at P-1, flat):
    optional whole-prompt pass (flash attention) that fills cache positions
    [0, P) in ONE forward; without it the prompt is teacher-forced through
    P-1 single-token decode steps.
    Mirrors the reference's fused decode loop (fused_multi_transformer) as a
    single compiled scan instead of a per-step CUDA op."""
    import numpy as _np

    from ..framework.core import Tensor, to_array
    from ..jit import state_values

    ids = _np.asarray(to_array(input_ids))
    B, P = ids.shape  # noqa: N806
    L = P + max_new_tokens
    if max_positions is not None and L > max_positions:
        raise ValueError(f"prompt+new tokens {L} exceeds "
                         f"max_position_embeddings {max_positions}")
    params = state_values(model)

    def gen_fn(p, prompt, rng):
        caches = make_caches(B, L)
        toks = jnp.concatenate(
            [prompt, jnp.zeros((B, max_new_tokens), jnp.int32)], axis=1)
        done = jnp.zeros((B,), bool)
        start = 0
        # prefill needs a real prompt AND at least one token to emit — with
        # max_new_tokens == 0 the sampled token would overwrite toks[:, P-1]
        if prefill is not None and P > 1 and max_new_tokens > 0:
            logits, caches = prefill(p, prompt, caches)
            nxt, rng = next_token(logits, rng, temperature, top_k, top_p)
            toks, done = advance_tokens(toks, done, nxt, P - 1, P, L,
                                        eos_token_id)
            start = P  # positions [0, P) are in the caches already

        def body(carry, t):
            toks, caches, done, rng = carry
            tok = jax.lax.dynamic_slice_in_dim(toks, t, 1, 1)
            logits, caches = run_one(p, tok, caches, t)
            nxt, rng = next_token(logits, rng, temperature, top_k, top_p)
            toks, done = advance_tokens(toks, done, nxt, t, P, L,
                                        eos_token_id)
            return (toks, caches, done, rng), None

        (toks, _, _, _), _ = jax.lax.scan(
            body, (toks, caches, done, rng), jnp.arange(start, L - 1))
        return toks

    key = (B, P, max_new_tokens, float(temperature or 0.0), int(top_k or 0),
           float(top_p or 0.0), eos_token_id, prefill is not None,
           tuple(extra_key))
    cache = getattr(model, "_gen_cache", None)
    if cache is None:
        cache = model._gen_cache = {}
    if key not in cache:
        cache[key] = jax.jit(gen_fn)
    was_training = getattr(model, "training", False)
    model.eval()  # stochastic layers must be off under the trace
    try:
        out = cache[key](params, jnp.asarray(ids, jnp.int32),
                         jax.random.PRNGKey(seed))
    finally:
        if was_training:
            model.train()
    return Tensor(out)


def compiled_beam_search(model, input_ids, *, num_beams, max_new_tokens,
                         eos_token_id, length_penalty, make_caches, run_one,
                         prefill=None, max_positions=None):
    """Compiled beam search over the cached decode step (PaddleNLP
    ``generate(decode_strategy="beam_search")`` parity, built the TPU way:
    the whole search is ONE lax.scan — per step the (B·K) decode batch
    produces logprobs, joint top-k over K·V picks the next beams, KV caches
    are gathered along the beam dim, and the token/parent trail is
    backtraced at the end with the gather_tree primitive).

    Finished beams (emitted EOS) are frozen: they re-emit EOS with no score
    change and keep competing in the top-k, the standard is-done handling.
    ``length_penalty`` alpha: final score = cum_logprob / (len ** alpha).
    """
    import numpy as _np

    from ..framework.core import Tensor, to_array
    from ..jit import state_values

    ids = _np.asarray(to_array(input_ids))
    B, P = ids.shape  # noqa: N806
    K = int(num_beams)
    T = max_new_tokens
    L = P + T
    if max_positions is not None and L > max_positions:
        raise ValueError(f"prompt+new tokens {L} exceeds "
                         f"max_position_embeddings {max_positions}")
    if T < 1 or K < 1:
        raise ValueError(
            f"beam search needs max_new_tokens >= 1 and num_beams >= 1 "
            f"(got {T}, {K})")
    eos = -1 if eos_token_id is None else int(eos_token_id)
    params = state_values(model)

    def expand(x):  # (B, ...) -> (B*K, ...) beam-major per batch row
        return jnp.repeat(x, K, axis=0)

    def gen_fn(p, prompt):
        neg = jnp.float32(-1e30)
        # run the prompt at batch B (all beams share it), then replicate the
        # caches/logits K-fold — prefilling (B*K) identical rows would do K
        # times redundant compute
        caches = make_caches(B, L)
        if prefill is not None and P > 1:
            logits, caches = prefill(p, prompt, caches)
        else:
            def tf_body(caches, t):
                tok = jax.lax.dynamic_slice_in_dim(prompt, t, 1, 1)
                logits, caches = run_one(p, tok, caches, t)
                return caches, logits

            caches, all_lg = jax.lax.scan(tf_body, caches, jnp.arange(P))
            logits = all_lg[-1]
        caches = [jnp.repeat(c, K, axis=0) for c in caches]
        start = P

        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)  # (B, V)
        V = logp.shape[-1]
        # first expansion: every beam starts from the single prompt state,
        # so one top-k over V per row seeds the K beams (no duplicates)
        cum, tok0 = jax.lax.top_k(logp, K)             # (B, K)
        flat_idx = tok0  # tokens directly (single source beam)
        tok0 = flat_idx.astype(jnp.int32)
        done = (tok0 == eos) if eos >= 0 else jnp.zeros((B, K), bool)
        gen_len = jnp.ones((B, K), jnp.int32)
        # parents for step 0 all come from beam 0; caches identical per row
        step_tokens0 = tok0                             # (B, K)
        step_parents0 = jnp.zeros((B, K), jnp.int32)
        cur = tok0.reshape(B * K)

        def body(carry, t):
            cur, cum, done, gen_len, caches = carry
            logits, caches = run_one(p, cur[:, None], caches, t)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            logp = logp.reshape(B, K, V)
            # frozen finished beams: only EOS continues, score unchanged
            if eos >= 0:
                frozen = jnp.full((V,), neg).at[eos].set(0.0)
                logp = jnp.where(done[..., None], frozen[None, None, :], logp)
            total = cum[..., None] + logp               # (B, K, V)
            cum2, flat = jax.lax.top_k(total.reshape(B, K * V), K)
            parent = (flat // V).astype(jnp.int32)      # (B, K)
            tok = (flat % V).astype(jnp.int32)
            bi = jnp.arange(B)[:, None]
            done2 = done[bi, parent]
            gen2 = jnp.where(done2, gen_len[bi, parent],
                             gen_len[bi, parent] + 1)
            if eos >= 0:
                done2 = done2 | (tok == eos)
            # reindex KV caches along the beam dim
            src = (jnp.arange(B)[:, None] * K + parent).reshape(B * K)
            caches = [c[src] for c in caches]
            return ((tok.reshape(B * K), cum2, done2, gen2, caches),
                    (tok, parent))

        (cur, cum, done, gen_len, caches), (tks, prs) = jax.lax.scan(
            body, (cur, cum, done, gen_len, caches),
            jnp.arange(start, start + T - 1))
        # trail: (T, B, K) including the first expansion
        all_toks = jnp.concatenate([step_tokens0[None], tks], axis=0)
        all_parents = jnp.concatenate([step_parents0[None], prs], axis=0)
        from ..nn.functional.extras import gather_tree

        traced = gather_tree(Tensor(all_toks), Tensor(all_parents)).value
        # pick the best beam per row by length-normalized score
        # (PaddleNLP/HF convention: normalize by the FULL hypothesis length,
        # prompt included)
        full_len = (gen_len + P).astype(jnp.float32)
        norm = cum / jnp.power(full_len, jnp.float32(length_penalty))
        best = jnp.argmax(norm, axis=-1)                # (B,)
        seq = traced[:, jnp.arange(B), best].T          # (B, T)
        return jnp.concatenate([prompt, seq.astype(jnp.int32)], axis=1)

    key = ("beam", B, P, T, K, eos, float(length_penalty),
           prefill is not None)
    cache = getattr(model, "_gen_cache", None)
    if cache is None:
        cache = model._gen_cache = {}
    if key not in cache:
        cache[key] = jax.jit(gen_fn)
    was_training = getattr(model, "training", False)
    model.eval()
    try:
        out = cache[key](params, jnp.asarray(ids, jnp.int32))
    finally:
        if was_training:
            model.train()
    return Tensor(out)
