"""Dtype system.

Maps the reference's phi::DataType (ref: paddle/phi/common/data_type.h) onto
jnp dtypes. On TPU, bfloat16 is the preferred half-precision type.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype objects (exported at paddle_tpu top level).
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_STR2DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
    # paddle aliases
    "fp16": float16,
    "bf16": bfloat16,
    "fp32": float32,
    "fp64": float64,
}

_FLOATING = {float16, bfloat16, float32, float64}
_INTEGER = {uint8, int8, int16, int32, int64}


def convert_dtype(dtype):
    """Normalize str/np/jnp dtype to a jnp dtype object."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _STR2DTYPE:
            raise ValueError(f"Unknown dtype string: {dtype!r}")
        return _STR2DTYPE[dtype]
    return jnp.dtype(dtype).type


def dtype_name(dtype) -> str:
    return np.dtype(dtype).name


def is_floating_point(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.integer)


def is_complex(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating)


_default_dtype = float32


def set_default_dtype(d):
    """paddle.set_default_dtype parity."""
    global _default_dtype
    d = convert_dtype(d)
    if d not in _FLOATING:
        raise TypeError(f"Default dtype must be floating, got {d}")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype
