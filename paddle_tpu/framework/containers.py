"""Auxiliary tensor container types.

Ref: paddle/phi/core/selected_rows.h (SelectedRows — sparse row-slice
gradients for embeddings) and the fluid TensorArray / LoDTensorArray
(paddle/phi/core/tensor_array.h) used by static control flow
(array_write/array_read around While ops).

TPU-native: TensorArray is a host-side list in eager mode; inside jit, the
idiomatic equivalent is lax.scan's stacked outputs, so ``stack()`` is the
bridge. SelectedRows keeps (rows, values) and densifies via a scatter-add,
which XLA turns into an efficient one-hot matmul/scatter on the MXU.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax.numpy as jnp

from .core import Tensor, to_array


class TensorArray:
    """Dynamic array of same-rank tensors (write/read/stack)."""

    def __init__(self, values: Optional[Sequence] = None):
        self._items: List[Optional[Tensor]] = list(values) if values else []

    def append(self, x) -> "TensorArray":
        self._items.append(x if isinstance(x, Tensor) else Tensor(to_array(x)))
        return self

    def write(self, index: int, x):
        index = int(index)
        if index >= len(self._items):
            self._items.extend([None] * (index + 1 - len(self._items)))
        self._items[index] = x if isinstance(x, Tensor) else Tensor(to_array(x))

    def read(self, index: int) -> Tensor:
        item = self._items[int(index)]
        if item is None:
            raise IndexError(f"TensorArray slot {index} was never written")
        return item

    def stack(self, axis: int = 0) -> Tensor:
        from ..tensor.manipulation import stack

        holes = [i for i, t in enumerate(self._items) if t is None]
        if holes:
            raise IndexError(
                f"TensorArray.stack: slots {holes} were never written "
                "(write() every index, or append() densely)")
        return stack(list(self._items), axis=axis)

    def __len__(self):
        return len(self._items)

    def __iter__(self):
        return iter(self._items)

    def __getitem__(self, i):
        return self.read(i)


class SelectedRows:
    """Row-sparse tensor: ``value[i]`` is the slice for row id ``rows[i]``.

    The reference uses this as the gradient type of large embedding tables
    (phi/core/selected_rows.h); optimizers apply sparse updates. Here the
    dense bridge is a segment-sum scatter, which is what a TPU optimizer
    update wants anyway.
    """

    def __init__(self, rows, value, height: int):
        self.rows = jnp.asarray(to_array(rows)).astype(jnp.int32)
        self.value = to_array(value)
        self.height = int(height)

    @property
    def shape(self):
        return (self.height,) + tuple(self.value.shape[1:])

    def to_dense(self) -> Tensor:
        dense = jnp.zeros((self.height,) + tuple(self.value.shape[1:]),
                          self.value.dtype)
        return Tensor(dense.at[self.rows].add(self.value))

    def merge(self) -> "SelectedRows":
        """Merge duplicate row ids by summing their slices."""
        uniq, inv = jnp.unique(self.rows, return_inverse=True,
                               size=self.rows.shape[0], fill_value=self.height)
        merged = jnp.zeros((uniq.shape[0],) + tuple(self.value.shape[1:]),
                           self.value.dtype).at[inv].add(self.value)
        keep = uniq < self.height
        return SelectedRows(jnp.where(keep, uniq, 0), merged * keep[(...,) + (None,) * (self.value.ndim - 1)], self.height)

    def __repr__(self):
        return f"SelectedRows(height={self.height}, nnz_rows={self.rows.shape[0]})"
