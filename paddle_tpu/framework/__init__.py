"""Framework core: Tensor, autograd tape, dtypes, flags, RNG."""
from .core import (EagerParamBase, Parameter, Tensor, backward, enable_grad, grad,
                   is_grad_enabled, no_grad, to_array)
from .containers import SelectedRows, TensorArray
from .dispatch import apply_op, defop
from .dtype import (bfloat16, bool_, complex64, complex128, convert_dtype, float16, float32,
                    float64, get_default_dtype, int8, int16, int32, int64, set_default_dtype,
                    uint8)
from .flags import GLOBAL_FLAGS, get_flags, set_flags
from .monitor import monitor_add, monitor_get, stat_registry
from .random import Generator, default_generator, get_rng_state, seed, set_rng_state

__all__ = [
    "Tensor", "Parameter", "EagerParamBase", "backward", "grad", "no_grad", "enable_grad",
    "is_grad_enabled", "apply_op", "defop", "convert_dtype", "set_default_dtype",
    "get_default_dtype", "set_flags", "get_flags", "GLOBAL_FLAGS", "seed", "Generator",
    "get_rng_state", "set_rng_state", "default_generator", "to_array",
]
