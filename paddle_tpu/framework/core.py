"""Core Tensor type and eager autograd engine.

TPU-native redesign of the reference's eager stack:

- ``Tensor`` wraps an immutable ``jax.Array`` (replacing phi::DenseTensor +
  AllocatorFacade — XLA owns memory on TPU; ref paddle/phi/core/dense_tensor.h:38,
  paddle/fluid/memory/allocation/allocator_facade.h).
- Eager autograd is a *tape* of ``jax.vjp`` closures instead of generated
  GradNode classes (ref paddle/fluid/eager/grad_node_info.h:168 and the
  queue-based engine in paddle/fluid/eager/backward.cc:105).  Because the tape
  is recorded sequentially, node-id order IS a topological order, so
  ``backward`` is a reverse sweep with cotangent accumulation — no in-degree
  map needed (ref backward.cc:216 builds one because its graph is not a tape).
- The jit path bypasses the tape entirely: pure functions + ``jax.grad``.

Everything here is eager-mode UX; under ``paddle_tpu.jit.to_static`` the same
ops trace into one jaxpr and XLA compiles/fuses them (the analogue of the
reference's InterpreterCore + CINN, which has no runtime equivalent on TPU).
"""
from __future__ import annotations

import contextlib
import threading
import weakref
from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .dtype import convert_dtype, get_default_dtype, is_floating_point

# --------------------------------------------------------------------------- #
# Grad-mode state
# --------------------------------------------------------------------------- #


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True
        self.tape_counter = 0


_grad_state = _GradState()


def is_grad_enabled() -> bool:
    return _grad_state.enabled


@contextlib.contextmanager
def no_grad_ctx():
    prev = _grad_state.enabled
    _grad_state.enabled = False
    try:
        yield
    finally:
        _grad_state.enabled = prev


class no_grad:
    """paddle.no_grad parity: usable as context manager and decorator."""

    def __enter__(self):
        self._prev = _grad_state.enabled
        _grad_state.enabled = False
        return self

    def __exit__(self, *exc):
        _grad_state.enabled = self._prev
        return False

    def __call__(self, fn):
        def wrapper(*args, **kwargs):
            with no_grad_ctx():
                return fn(*args, **kwargs)

        return wrapper


@contextlib.contextmanager
def enable_grad():
    prev = _grad_state.enabled
    _grad_state.enabled = True
    try:
        yield
    finally:
        _grad_state.enabled = prev


# --------------------------------------------------------------------------- #
# Tape
# --------------------------------------------------------------------------- #


class TapeNode:
    """One recorded op: holds the vjp closure and links to differentiable inputs.

    Analogue of a generated GradNode (ref grad_node_info.h:168) — but generic:
    jax.vjp supplies the gradient rule for any traced computation, so there is
    no per-op codegen (ref eager_gen.py:192).
    """

    __slots__ = (
        "id",
        "vjp_fn",
        "fwd_fn",
        "inputs",
        "n_out",
        "out_ct",
        "out_avals",
        "out_tensors",
        "name",
        "__weakref__",
    )

    def __init__(self, vjp_fn, inputs, out_avals, name="", fwd_fn=None):
        _grad_state.tape_counter += 1
        self.id = _grad_state.tape_counter
        self.vjp_fn = vjp_fn
        # forward closure over the differentiable inputs: re-linearized by
        # backward(create_graph=True) so second-order grads see the primal
        # dependency (the vjp residuals alone are constants)
        self.fwd_fn = fwd_fn
        self.inputs: Tuple["Tensor", ...] = tuple(inputs)
        self.n_out = len(out_avals)
        self.out_avals = out_avals  # list of (shape, dtype)
        self.out_ct: List[Optional[jax.Array]] = [None] * self.n_out
        self.out_tensors: List[Optional[weakref.ref]] = [None] * self.n_out
        self.name = name

    def add_ct(self, idx: int, ct) -> None:
        if self.out_ct[idx] is None:
            self.out_ct[idx] = ct
        else:
            self.out_ct[idx] = self.out_ct[idx] + ct


# --------------------------------------------------------------------------- #
# Tensor
# --------------------------------------------------------------------------- #

TensorLike = Union["Tensor", jax.Array, np.ndarray, int, float, bool, list, tuple]


class Tensor:
    """Eager tensor: a jax.Array plus autograd metadata.

    API modelled on paddle.Tensor (ref python/paddle/fluid/dygraph/ math-op
    patches + pybind/eager.cc:1148), storage is always a device-resident
    jax.Array.
    """

    __slots__ = (
        "_value",
        "stop_gradient",
        "_grad",
        "_node",
        "_idx",
        "_retain_grads",
        "_backward_hooks",
        "name",
        "persistable",
        "__weakref__",
    )

    def __init__(self, value, stop_gradient: bool = True, name: str = ""):
        if isinstance(value, Tensor):
            value = value._value
        elif not isinstance(value, jax.Array):
            value = jnp.asarray(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self._grad: Optional[Tensor] = None
        self._node: Optional[TapeNode] = None
        self._idx: int = 0
        self._retain_grads = False
        self._backward_hooks: List[Callable] = []
        self.name = name
        self.persistable = False

    # -- basic properties ---------------------------------------------------
    @property
    def value(self):
        return self._value

    @property
    def shape(self) -> List[int]:
        return list(self._value.shape)

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def ndim(self) -> int:
        return self._value.ndim

    @property
    def size(self) -> int:
        return int(self._value.size)

    @property
    def place(self) -> str:
        try:
            dev = list(self._value.devices())[0]
            return str(dev)
        except Exception:
            return "cpu"

    @property
    def is_leaf(self) -> bool:
        return self._node is None

    def numel(self) -> int:
        return int(self._value.size)

    def dim(self) -> int:
        return self._value.ndim

    # -- conversion ---------------------------------------------------------
    # These ARE the sanctioned device->host boundary: the user asked for a
    # host value by name. Library code must not call them on hot paths —
    # graftlint GL001 polices that; here the sync is the contract.
    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)  # graftlint: noqa[host-sync]

    def item(self, *args):
        if args:
            return self.numpy().item(*args)  # graftlint: noqa[host-sync]
        return self.numpy().item()  # graftlint: noqa[host-sync]

    def tolist(self):
        return self.numpy().tolist()  # graftlint: noqa[host-sync]

    def __array__(self, dtype=None):
        a = np.asarray(self._value)  # graftlint: noqa[host-sync]
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(self.item())  # graftlint: noqa[host-sync]

    def __int__(self):
        return int(self.item())  # graftlint: noqa[host-sync]

    def __bool__(self):
        return bool(self.item())  # graftlint: noqa[host-sync]

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __hash__(self):
        return id(self)

    # -- autograd -----------------------------------------------------------
    @property
    def grad(self) -> Optional["Tensor"]:
        return self._grad

    @grad.setter
    def grad(self, g):
        self._grad = g if (g is None or isinstance(g, Tensor)) else Tensor(g)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def retain_grads(self):
        self._retain_grads = True

    def register_hook(self, hook: Callable):
        """Run ``hook(grad)`` on this tensor's gradient during backward
        (ref eager grad hooks; returns a removable handle)."""
        self._backward_hooks.append(hook)

        class _Handle:
            def __init__(self, hooks, h):
                self._hooks, self._h = hooks, h

            def remove(self):
                try:
                    self._hooks.remove(self._h)
                except ValueError:
                    pass

        return _Handle(self._backward_hooks, hook)

    def backward(self, grad_tensor: Optional["Tensor"] = None, retain_graph: bool = False):
        """Reverse-mode sweep from this tensor (ref eager/backward.cc:105)."""
        backward([self], [grad_tensor] if grad_tensor is not None else None, retain_graph)

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True)
        return t

    def detach_(self) -> "Tensor":
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        from .dispatch import apply_op

        return apply_op(lambda x: x + 0, self)

    # -- mutation (in-place, breaks tape links deliberately) ---------------
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        value = jnp.asarray(value)
        if tuple(value.shape) != tuple(self._value.shape):
            raise ValueError(
                f"set_value shape mismatch: {value.shape} vs {self._value.shape}")
        self._value = value.astype(self._value.dtype)

    def copy_(self, other, blocking=True):
        self.set_value(other)
        return self

    def _update_value(self, value):
        """Internal: replace storage (optimizer updates)."""
        self._value = value

    # -- dtype / device -----------------------------------------------------
    def astype(self, dtype) -> "Tensor":
        from .dispatch import apply_op

        d = convert_dtype(dtype)
        return apply_op(lambda x: x.astype(d), self)

    def cast(self, dtype) -> "Tensor":
        return self.astype(dtype)

    def to(self, *args, **kwargs) -> "Tensor":
        # Accepts dtype or device strings; device moves are XLA-managed.
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a in ("cpu", "gpu", "tpu", "xpu") or a is None:
                continue
            try:
                return self.astype(a)
            except (ValueError, TypeError):
                continue
        return self

    def cpu(self) -> "Tensor":
        # explicit device move requested by the caller
        return Tensor(np.asarray(self._value),  # graftlint: noqa[host-sync]
                      stop_gradient=self.stop_gradient)

    def cuda(self, *a, **k) -> "Tensor":
        return self

    def pin_memory(self) -> "Tensor":
        return self

    # -- indexing -----------------------------------------------------------
    def __getitem__(self, idx) -> "Tensor":
        from .dispatch import apply_op

        idx = _normalize_index(idx)
        return apply_op(lambda x: x[idx], self)

    def __setitem__(self, idx, val):
        idx = _normalize_index(idx)
        if isinstance(val, Tensor):
            val = val._value
        self._value = self._value.at[idx].set(val)

    def __repr__(self):
        grad_flag = "" if self.stop_gradient else ", stop_gradient=False"
        return (
            f"Tensor(shape={self.shape}, dtype={np.dtype(self.dtype).name}"
            f"{grad_flag},\n       "
            f"{np.asarray(self._value)!r})"  # graftlint: noqa[host-sync]
        )

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]


def _normalize_index(idx):
    def conv(i):
        if isinstance(i, Tensor):
            return i._value
        return i

    if isinstance(idx, tuple):
        return tuple(conv(i) for i in idx)
    return conv(idx)


class Parameter(Tensor):
    """Trainable tensor (ref python/paddle/fluid/framework.py Parameter).

    ``pspec`` carries the GSPMD PartitionSpec for this parameter — the TPU
    analogue of TensorDistAttr (ref paddle/fluid/distributed/auto_parallel/
    dist_attr.h); consumed by the parallel engine when building sharded
    train steps.
    """

    __slots__ = ("trainable", "optimize_attr", "regularizer", "is_distributed", "pspec")

    def __init__(self, value, trainable: bool = True, name: str = ""):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self.pspec = None
        self.persistable = True


class EagerParamBase(Parameter):
    """Alias matching the reference's eager parameter class name."""


# Pytree registration: lets jitted functions take/return Tensors transparently.
jax.tree_util.register_pytree_node(
    Tensor,
    lambda t: ((t._value,), t.stop_gradient),
    lambda aux, children: Tensor(children[0], stop_gradient=aux),
)
jax.tree_util.register_pytree_node(
    Parameter,
    lambda t: ((t._value,), t.trainable),
    lambda aux, children: Parameter(children[0], trainable=aux),
)


# --------------------------------------------------------------------------- #
# Backward engine
# --------------------------------------------------------------------------- #


def _requires_grad(t: Any) -> bool:
    return isinstance(t, Tensor) and not t.stop_gradient


def backward(tensors: Sequence[Tensor], grad_tensors=None, retain_graph: bool = False,
             create_graph: bool = False):
    """paddle.autograd.backward parity (ref eager/backward.cc:383).

    Tape order is topological, so we sweep nodes by descending id.
    ``create_graph=True`` records the backward computation itself on the
    tape (cotangents flow as taped Tensors and every vjp is re-linearized
    through dispatch), enabling double backward / ``paddle.grad`` chains.
    """
    tensors = list(tensors)
    retain_graph = retain_graph or create_graph  # grad graph re-enters nodes
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    roots: List[TapeNode] = []
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient and t._node is None:
            raise RuntimeError("backward() on a tensor that does not require grad")
        if g is None:
            if t.size != 1 and not is_floating_point(t.dtype):
                raise RuntimeError("backward() root must be scalar or have grad_tensor")
            g_val = jnp.ones_like(t._value)
        else:
            g_val = g._value if isinstance(g, Tensor) else jnp.asarray(g)
        if create_graph:
            g_val = g if isinstance(g, Tensor) else Tensor(g_val)
        if t._node is not None:
            t._node.add_ct(t._idx, g_val)
            roots.append(t._node)
        if t._retain_grads or t._node is None:
            _accum_grad(t, g_val)

    # Collect reachable nodes.
    seen = {}
    stack = list(roots)
    while stack:
        n = stack.pop()
        if n.id in seen:
            continue
        seen[n.id] = n
        for inp in n.inputs:
            if inp._node is not None:
                stack.append(inp._node)

    for nid in sorted(seen.keys(), reverse=True):
        node = seen[nid]
        cts = []
        pending = False
        for i in range(node.n_out):
            ct = node.out_ct[i]
            if ct is None:
                shape, dtype = node.out_avals[i]
                ct = jnp.zeros(shape, dtype)
                if create_graph:
                    ct = Tensor(ct)
            else:
                pending = True
            # apply hooks registered on the output tensor
            ref = node.out_tensors[i]
            out_t = ref() if ref is not None else None
            if out_t is not None:
                for hook in out_t._backward_hooks:
                    res = hook(ct if isinstance(ct, Tensor) else Tensor(ct))
                    if res is not None:
                        ct = _hook_result(res, create_graph)
                if out_t._retain_grads and node.out_ct[i] is not None:
                    _accum_grad(out_t, ct)
            cts.append(ct)
        if not pending:
            continue
        if create_graph:
            if node.fwd_fn is None:
                raise RuntimeError(
                    f"create_graph=True through op '{node.name}': recorded "
                    "without a re-linearizable forward (PyLayer/custom "
                    "autograd) — double backward is not supported across it")
            in_cts = _relinearized_vjp(node, cts)
        else:
            raw_cts = [c._value if isinstance(c, Tensor) else c for c in cts]
            in_cts = node.vjp_fn(tuple(raw_cts) if node.n_out > 1
                                 else raw_cts[0])
        for inp, ict in zip(node.inputs, in_cts):
            if ict is None:
                continue
            if inp._node is not None:
                inp._node.add_ct(inp._idx, ict)
            if inp._node is None or inp._retain_grads:
                for hook in inp._backward_hooks:
                    res = hook(ict if isinstance(ict, Tensor) else Tensor(ict))
                    if res is not None:
                        ict = _hook_result(res, create_graph)
                _accum_grad(inp, ict)
        node.out_ct = [None] * node.n_out
        if not retain_graph:
            node.vjp_fn = _used_vjp
            node.fwd_fn = None  # release the captured forward inputs too


def _hook_result(res, create_graph: bool):
    if create_graph and isinstance(res, Tensor):
        return res
    return res._value if isinstance(res, Tensor) else jnp.asarray(res)


def _relinearized_vjp(node: "TapeNode", cts):
    """create_graph path: apply the node's vjp as a DISPATCHED op over
    (cotangents, primal inputs) — jax.vjp is recomputed from the forward
    closure so the primal dependency is differentiable (second order)."""
    from .dispatch import apply_op

    n_out = node.n_out
    fwd = node.fwd_fn

    def vjp_op(*a):
        c = a[:n_out]
        dvals = a[n_out:]
        _, vjp = jax.vjp(fwd, *dvals)
        res = vjp(tuple(c) if n_out > 1 else c[0])
        return tuple(res) if len(res) > 1 else res[0]

    ct_ts = [c if isinstance(c, Tensor) else Tensor(c) for c in cts]
    out = apply_op(vjp_op, *ct_ts, *node.inputs,
                   op_name=f"grad_{node.name}")
    return list(out) if isinstance(out, (tuple, list)) else [out]


def _used_vjp(*_):
    raise RuntimeError(
        "Trying to backward through the graph a second time; "
        "pass retain_graph=True to backward() to allow this.")


def _accum_grad(t: Tensor, g) -> None:
    if t.stop_gradient and not t._retain_grads:
        return
    if isinstance(g, Tensor):
        # create_graph path: keep the accumulated grad on the tape
        t._grad = g if t._grad is None else t._grad + g
        return
    if t._grad is None:
        t._grad = Tensor(g)
    else:
        t._grad = Tensor(t._grad._value + g)


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
):
    """paddle.grad parity (ref eager GeneralGrad, general_grad.h).

    Computes grads of ``outputs`` wrt ``inputs`` without touching ``.grad``
    slots of other leaves.
    """
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    saved = [(t, t._grad, t._retain_grads) for t in inputs]
    for t in inputs:
        t._grad = None
        t._retain_grads = True
    try:
        backward(list(outputs), grad_outputs,
                 retain_graph=bool(retain_graph) or create_graph,
                 create_graph=create_graph)
        results = []
        for t in inputs:
            if t._grad is None and not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears unused; "
                    "set allow_unused=True to return None for it.")
            results.append(t._grad)
    finally:
        # restore .grad slots to pre-call values on BOTH paths — an
        # exception must not clobber the caller's accumulated grads
        # (results hold their own references, unaffected by the restore)
        for t, g, r in saved:
            t._retain_grads = r
            t._grad = g
    return results


# --------------------------------------------------------------------------- #
# Helpers for converting arbitrary input to raw arrays
# --------------------------------------------------------------------------- #


def to_array(x):
    if isinstance(x, Tensor):
        return x._value
    if isinstance(x, jax.Array):
        return x
    return jnp.asarray(x)


def to_tensor_out(val) -> Tensor:
    return Tensor(val)
