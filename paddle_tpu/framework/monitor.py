"""Runtime stat counters (ref paddle/fluid/platform/monitor.cc StatRegistry:
named int64 counters the runtime bumps and monitoring code scrapes)."""
from __future__ import annotations

import threading
from typing import Dict


class _StatRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._stats: Dict[str, int] = {}

    def add(self, name: str, delta: int = 1) -> int:
        with self._lock:
            self._stats[name] = self._stats.get(name, 0) + int(delta)
            return self._stats[name]

    def set(self, name: str, value: int) -> None:
        with self._lock:
            self._stats[name] = int(value)

    def get(self, name: str) -> int:
        with self._lock:
            return self._stats.get(name, 0)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)

    def reset(self, name: str = None) -> None:
        with self._lock:
            if name is None:
                self._stats.clear()
            else:
                self._stats.pop(name, None)


_registry = _StatRegistry()


def stat_registry() -> _StatRegistry:
    return _registry


def monitor_add(name: str, delta: int = 1) -> int:
    return _registry.add(name, delta)


def monitor_get(name: str) -> int:
    return _registry.get(name)
