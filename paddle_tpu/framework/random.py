"""Global RNG state.

JAX PRNG is explicit/functional; eager mode keeps a global splitting key so the
paddle-style stateful API (`paddle.seed`, implicit randomness in dropout etc.)
works (ref: python/paddle/fluid/framework.py default_main_program random seed,
paddle.seed). Distributed per-mode seeds (TP-aware RNG) live in
paddle_tpu.distributed.fleet.meta_parallel.random (ref:
python/paddle/distributed/fleet/meta_parallel/parallel_layers/random.py:35
RNGStatesTracker).
"""
from __future__ import annotations

import threading

import jax
import numpy as np


class Generator:
    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._seed = int(seed)
        # key is created LAZILY: jax.random.key initializes the XLA backend,
        # which must not happen at paddle_tpu import time — a launched pod
        # job needs jax.distributed.initialize to run first
        self._key = None

    def manual_seed(self, seed: int):
        with self._lock:
            self._seed = int(seed)
            self._key = jax.random.key(int(seed))
        return self

    def initial_seed(self) -> int:
        return self._seed

    def _ensure_key(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)

    def next_key(self):
        with self._lock:
            self._ensure_key()
            self._key, sub = jax.random.split(self._key)
            return sub

    def get_state(self):
        with self._lock:
            self._ensure_key()
            return jax.random.key_data(self._key)

    def set_state(self, state):
        with self._lock:
            self._key = jax.random.wrap_key_data(np.asarray(state))


_default_generator = Generator(0)


def default_generator() -> Generator:
    return _default_generator


def seed(s: int):
    """paddle.seed parity."""
    _default_generator.manual_seed(s)
    # legacy consumers (user code, datasets) still read the global numpy
    # stream; seeding it here is the API's contract
    np.random.seed(int(s) % (2 ** 32))  # graftlint: noqa[np-random]
    return _default_generator


def derived_rng(*entropy) -> np.random.Generator:
    """Seeded LOCAL numpy generator for host-side library randomness
    (init heuristics, negative sampling, graph subsampling).

    Derives from the framework seed plus caller-supplied entropy (ints or
    strings — strings are hashed stably), so the stream is reproducible
    after ``paddle.seed`` yet immune to — and invisible to — every other
    ``np.random`` consumer. For FRESH draws per call, mix in
    ``next_key()``'s key data as entropy. This is the sanctioned
    replacement for ``np.random.RandomState``/``default_rng`` in library
    modules (graftlint GL003)."""
    import zlib

    ints = [_default_generator.initial_seed() & 0xFFFFFFFF]
    for e in entropy:
        if isinstance(e, (bool, str, bytes)):
            b = e if isinstance(e, bytes) else str(e).encode()
            ints.append(zlib.crc32(b))
        elif isinstance(e, (int, np.integer)):
            ints.append(int(e) & 0xFFFFFFFFFFFFFFFF)
        else:
            raise TypeError(
                f"derived_rng entropy must be int/str/bytes, got "
                f"{type(e).__name__}")
    return np.random.default_rng(ints)  # graftlint: noqa[np-random]


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)


def next_key():
    return _default_generator.next_key()
