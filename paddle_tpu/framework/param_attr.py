"""ParamAttr (ref: python/paddle/fluid/param_attr.py)."""
from __future__ import annotations


class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0, regularizer=None,
                 trainable=True, do_model_average=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(arg):
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if callable(arg):
            return ParamAttr(initializer=arg)
        if arg is False:
            return False
        return ParamAttr()


class WeightNormParamAttr(ParamAttr):
    """ref python/paddle/fluid/param_attr.py WeightNormParamAttr — weight-norm
    reparameterization metadata; applied by nn.utils.weight_norm."""

    def __init__(self, dim=None, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        super().__init__(name=name, initializer=initializer,
                         learning_rate=learning_rate, regularizer=regularizer,
                         trainable=trainable, do_model_average=do_model_average,
                         need_clip=need_clip)
        self.dim = dim
