"""paddle.save / paddle.load parity (ref: python/paddle/framework/io.py:637,879).

Pickle-based object serialization handling Tensor / state_dict / nested
containers. Sharded & async checkpointing lives in
paddle_tpu.distributed.checkpoint (orbax-backed).
"""
from __future__ import annotations

import os
import pickle
from typing import Any

import numpy as np

from .core import Parameter, Tensor


class _TensorPayload:
    def __init__(self, array: np.ndarray, trainable: bool, name: str = "", is_param=False):
        self.array = array
        self.trainable = trainable
        self.name = name
        self.is_param = is_param


def _pack(obj: Any) -> Any:
    if isinstance(obj, Parameter):
        return _TensorPayload(np.asarray(obj.value), obj.trainable, obj.name, True)
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj.value), not obj.stop_gradient, obj.name, False)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj: Any, return_numpy=False) -> Any:
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        if obj.is_param:
            p = Parameter(obj.array, trainable=obj.trainable, name=obj.name)
            return p
        return Tensor(obj.array, stop_gradient=not obj.trainable, name=obj.name)
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj: Any, path: str, protocol: int = 4, **configs) -> None:
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path: str, return_numpy: bool = False, **configs) -> Any:
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
