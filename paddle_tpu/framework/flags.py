"""Global flag registry.

TPU-native analogue of the reference's three-tier flag system
(ref: paddle/phi/core/flags.cc — 89 PADDLE_DEFINE_EXPORTED_* gflags,
surfaced to Python via paddle.set_flags/get_flags in
python/paddle/fluid/framework.py:7629). We keep a single typed registry
with env-var overrides (``FLAGS_<name>``) instead of C++ gflags.
"""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass
class _Flag:
    name: str
    default: Any
    help: str
    parser: Callable[[str], Any]
    value: Any = None


def _parse_bool(s: str) -> bool:
    return str(s).lower() in ("1", "true", "yes", "on")


class FlagRegistry:
    def __init__(self) -> None:
        self._flags: Dict[str, _Flag] = {}
        self._lock = threading.Lock()

    def define(self, name: str, default: Any, help: str = "") -> None:
        if isinstance(default, bool):
            parser: Callable[[str], Any] = _parse_bool
        elif isinstance(default, int):
            parser = int
        elif isinstance(default, float):
            parser = float
        else:
            parser = str
        with self._lock:
            if name in self._flags:
                return
            flag = _Flag(name=name, default=default, help=help, parser=parser)
            env = os.environ.get(f"FLAGS_{name}")
            flag.value = parser(env) if env is not None else default
            self._flags[name] = flag

    def set(self, name: str, value: Any) -> None:
        with self._lock:
            if name not in self._flags:
                raise KeyError(f"Unknown flag: {name!r}")
            f = self._flags[name]
            f.value = f.parser(value) if isinstance(value, str) else value

    def get(self, name: str) -> Any:
        with self._lock:
            if name not in self._flags:
                raise KeyError(f"Unknown flag: {name!r}")
            return self._flags[name].value

    def has(self, name: str) -> bool:
        return name in self._flags

    def all(self) -> Dict[str, Any]:
        with self._lock:
            return {k: f.value for k, f in self._flags.items()}


GLOBAL_FLAGS = FlagRegistry()

# Core flags (subset mirroring the reference's most-used ones).
GLOBAL_FLAGS.define("check_nan_inf", False, "Scan op outputs for NaN/Inf (ref FLAGS_check_nan_inf)")
GLOBAL_FLAGS.define("deterministic", False, "Force deterministic execution")
GLOBAL_FLAGS.define("default_dtype", "float32", "Default floating dtype")
GLOBAL_FLAGS.define("eager_delete_tensor_gb", 0.0, "Compat no-op: XLA manages memory")
GLOBAL_FLAGS.define("use_pallas_kernels", True, "Use Pallas kernels for hot ops when on TPU")
GLOBAL_FLAGS.define("log_level", "WARNING", "Python logging level for paddle_tpu")
GLOBAL_FLAGS.define("profiler_trace_dir", "", "Directory for profiler trace dumps")


def set_flags(flags: Dict[str, Any]) -> None:
    """paddle.set_flags parity (ref python/paddle/fluid/framework.py:7629)."""
    for k, v in flags.items():
        name = k[6:] if k.startswith("FLAGS_") else k
        GLOBAL_FLAGS.set(name, v)


def get_flags(flags) -> Dict[str, Any]:
    """paddle.get_flags parity."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for k in flags:
        name = k[6:] if k.startswith("FLAGS_") else k
        out[k] = GLOBAL_FLAGS.get(name)
    return out
