"""Eager op dispatch: run a pure jax function over Tensor args, recording
the tape when gradients are required.

This replaces the reference's entire per-op generated dispatch chain
(ref paddle/fluid/eager/auto_code_generator/generator/eager_gen.py:192
FORWARD_FUNCTION_TEMPLATE + phi KernelFactory selection,
paddle/phi/core/kernel_factory.cc:140): on TPU there is exactly one
"kernel" per op — the jax/XLA lowering — and the grad rule comes from
jax.vjp instead of a hand-registered GradNode.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import weakref

from .core import Tensor, TapeNode, is_grad_enabled, to_array
from .dtype import is_floating_point
from .flags import GLOBAL_FLAGS

_static_graph = None  # lazily bound paddle_tpu.static.graph module


def _check_nan_inf(name, arrays):
    import numpy as np

    for a in arrays:
        if is_floating_point(a.dtype):
            x = np.asarray(a)
            if not np.isfinite(x).all():
                raise FloatingPointError(
                    f"NaN/Inf detected in output of op {name!r} "
                    f"(FLAGS_check_nan_inf=1; ref nan_inf_utils_detail.cc)")


def apply_op(fn: Callable, *args, n_outputs: Optional[int] = None, op_name: str = "", **kwargs):
    """Apply ``fn(*raw_arrays, **kwargs)``; record tape node if needed.

    Positional args may be Tensors, jax arrays, or python scalars; kwargs are
    static. Returns Tensor (or tuple of Tensors when fn returns a sequence).

    Static-graph build: when a paddle_tpu.static program is being built and a
    static Variable is among the inputs, the op is RECORDED into the current
    Program instead of executed (the analogue of LayerHelper.append_op in
    every reference tensor function, ref python/paddle/tensor/*).
    """
    global _static_graph
    if _static_graph is None:
        from ..static import graph as _sg

        _static_graph = _sg
    if _static_graph.static_build_active() and any(
            isinstance(a, _static_graph.Variable) for a in args):
        return _static_graph.record_op(fn, args, kwargs,
                                       op_name or getattr(fn, "__name__", "op"))

    raw = [to_array(a) if isinstance(a, Tensor) else a for a in args]

    # AMP O1/O2 autocast at dispatch time (ref eager_gen.py:415 AMP_LOGIC_TEMPLATE;
    # lists in paddle_tpu.amp). Cast fp inputs to the amp dtype for white-listed
    # ops, to fp32 for black-listed ones when inputs are low-precision.
    try:
        from ..amp import amp_dtype, amp_state, should_cast_to_low_precision

        if amp_state().level != "O0":
            name = op_name or getattr(fn, "__name__", "")
            if should_cast_to_low_precision(name):
                tgt = amp_dtype()
                raw = [a.astype(tgt)
                       if hasattr(a, "dtype") and is_floating_point(a.dtype) and
                       a.dtype != tgt else a for a in raw]
    except ImportError:
        pass

    diff_idx = [
        i
        for i, a in enumerate(args)
        if isinstance(a, Tensor)
        and not a.stop_gradient
        and is_floating_point(a.dtype)
    ]
    record = is_grad_enabled() and len(diff_idx) > 0

    if record:
        def f(*dvals):
            full = list(raw)
            for i, v in zip(diff_idx, dvals):
                full[i] = v
            return fn(*full, **kwargs)

        out, vjp_fn = jax.vjp(f, *(raw[i] for i in diff_idx))
    else:
        out = fn(*raw, **kwargs)

    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]

    if GLOBAL_FLAGS.get("check_nan_inf"):
        _check_nan_inf(op_name or getattr(fn, "__name__", "op"), outs)

    out_tensors = [Tensor(o, stop_gradient=not record) for o in outs]
    if record:
        node = TapeNode(
            vjp_fn,
            inputs=[args[i] for i in diff_idx],
            out_avals=[(o.shape, o.dtype) for o in outs],
            name=op_name or getattr(fn, "__name__", "op"),
        )
        for k, t in enumerate(out_tensors):
            t._node = node
            t._idx = k
            node.out_tensors[k] = weakref.ref(t)
    if multi:
        return tuple(out_tensors)
    return out_tensors[0]


def defop(fn: Callable, op_name: str = ""):
    """Lift a pure jax function into an eager op over Tensors."""

    def op(*args, **kwargs):
        return apply_op(fn, *args, op_name=op_name, **kwargs)

    op.__name__ = op_name or getattr(fn, "__name__", "op")
    return op
