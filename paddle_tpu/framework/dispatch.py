"""Eager op dispatch: run a pure jax function over Tensor args, recording
the tape when gradients are required.

This replaces the reference's entire per-op generated dispatch chain
(ref paddle/fluid/eager/auto_code_generator/generator/eager_gen.py:192
FORWARD_FUNCTION_TEMPLATE + phi KernelFactory selection,
paddle/phi/core/kernel_factory.cc:140): on TPU there is exactly one
"kernel" per op — the jax/XLA lowering — and the grad rule comes from
jax.vjp instead of a hand-registered GradNode.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import functools
import weakref

import jax
import jax.numpy as jnp

from .core import Tensor, TapeNode, is_grad_enabled, to_array
from .dtype import is_floating_point
from .flags import GLOBAL_FLAGS

_static_graph = None  # lazily bound paddle_tpu.static.graph module

# --------------------------------------------------------------------------- #
# cached eager autograd: jax.vjp re-traces the op's Python body on EVERY
# eager call (the dominant cost of eager training loops). For closure-free
# op functions the traced (out, vjp) pair is compiled once per
# (fn, arg-structure, kwargs) — jax.vjp's VJP closure is a pytree, so a
# jitted wrapper can return it, and a shared jitted applier replays the
# backward without retracing. Functions with closures are excluded: they may
# capture per-call state (dropout keys, loop indices), which a cached trace
# would freeze.
# --------------------------------------------------------------------------- #

_FWD_JIT_CACHE: dict = {}
_FWD_JIT_CACHE_MAX = 1024
_BWD_JIT = None


def _hashable(x) -> bool:
    try:
        hash(x)
        return True
    except TypeError:
        return False


# module-global entry points whose values change per call (PRNG draws, flag
# reads): a cached trace would freeze their first value forever. Functions
# whose code references any of these names are never cached.
_IMPURE_NAMES = frozenset({"next_key", "default_generator", "get_rng_state",
                           "GLOBAL_FLAGS", "get_flags"})


def _cached_fwd(fn, n_args, diff_idx, arr_pos, statics, kwargs):
    # key on the CODE object: closure-free functions defined per call (the
    # common `def f(...)` inside a layer's forward) share code but not
    # identity — keying on the object would compile a fresh executable every
    # call. Same code + same defaults + no closure ⇒ same behavior, PROVIDED
    # the body doesn't read per-call mutable globals (checked via co_names).
    code = fn.__code__
    if _IMPURE_NAMES & set(code.co_names):
        return None
    defaults = getattr(fn, "__defaults__", None)
    if defaults is not None and not all(_hashable(d) for d in defaults):
        return None
    kwdefaults = getattr(fn, "__kwdefaults__", None)
    kwdefaults = tuple(sorted(kwdefaults.items())) if kwdefaults else None
    if kwdefaults is not None and not _hashable(kwdefaults):
        return None
    key = (code, defaults, kwdefaults, n_args, diff_idx, arr_pos, statics,
           tuple(sorted(kwargs.items())))
    entry = _FWD_JIT_CACHE.get(key)
    if entry is None:
        if len(_FWD_JIT_CACHE) >= _FWD_JIT_CACHE_MAX:
            # evict one (FIFO) — clearing everything would trigger a full
            # recompilation storm for every hot op
            _FWD_JIT_CACHE.pop(next(iter(_FWD_JIT_CACHE)))

        def wrapper(*arrs):
            full = [None] * n_args
            for p, a in zip(arr_pos, arrs):
                full[p] = a
            for p, v in statics:
                full[p] = v

            def f_diff(*dvals):
                ff = list(full)
                for i, v in zip(diff_idx, dvals):
                    ff[i] = v
                return fn(*ff, **dict(kwargs))

            return jax.vjp(f_diff, *(full[i] for i in diff_idx))

        entry = jax.jit(wrapper)
        _FWD_JIT_CACHE[key] = entry
    return entry


def _bwd_apply(vjp_fn, cts):
    """Replay a cached VJP under a shared jit so backward doesn't retrace."""
    global _BWD_JIT
    if _BWD_JIT is None:
        _BWD_JIT = jax.jit(lambda v, c: v(c))
    return _BWD_JIT(vjp_fn, cts)


def _check_nan_inf(name, arrays):
    import numpy as np

    for a in arrays:
        if is_floating_point(a.dtype):
            x = np.asarray(a)
            if not np.isfinite(x).all():
                raise FloatingPointError(
                    f"NaN/Inf detected in output of op {name!r} "
                    f"(FLAGS_check_nan_inf=1; ref nan_inf_utils_detail.cc)")


def apply_op(fn: Callable, *args, n_outputs: Optional[int] = None, op_name: str = "", **kwargs):
    """Apply ``fn(*raw_arrays, **kwargs)``; record tape node if needed.

    Positional args may be Tensors, jax arrays, or python scalars; kwargs are
    static. Returns Tensor (or tuple of Tensors when fn returns a sequence).

    Static-graph build: when a paddle_tpu.static program is being built and a
    static Variable is among the inputs, the op is RECORDED into the current
    Program instead of executed (the analogue of LayerHelper.append_op in
    every reference tensor function, ref python/paddle/tensor/*).
    """
    global _static_graph
    if _static_graph is None:
        from ..static import graph as _sg

        _static_graph = _sg
    if _static_graph.static_build_active() and any(
            isinstance(a, _static_graph.Variable) for a in args):
        return _static_graph.record_op(fn, args, kwargs,
                                       op_name or getattr(fn, "__name__", "op"))

    raw = [to_array(a) if isinstance(a, Tensor) else a for a in args]

    # AMP O1/O2 autocast at dispatch time (ref eager_gen.py:415 AMP_LOGIC_TEMPLATE;
    # lists in paddle_tpu.amp). Cast fp inputs to the amp dtype for white-listed
    # ops, to fp32 for black-listed ones when inputs are low-precision.
    try:
        from ..amp import amp_dtype, amp_state, should_cast_to_low_precision

        if amp_state().level != "O0":
            name = op_name or getattr(fn, "__name__", "")
            if should_cast_to_low_precision(name):
                tgt = amp_dtype()
                raw = [a.astype(tgt)
                       if hasattr(a, "dtype") and is_floating_point(a.dtype) and
                       a.dtype != tgt else a for a in raw]
    except ImportError:
        pass

    diff_idx = [
        i
        for i, a in enumerate(args)
        if isinstance(a, Tensor)
        and not a.stop_gradient
        and is_floating_point(a.dtype)
    ]
    record = is_grad_enabled() and len(diff_idx) > 0

    if record:
        cached = None
        if getattr(fn, "__closure__", True) is None:
            arr_pos, statics = [], []
            for i, a in enumerate(raw):
                if hasattr(a, "shape") and hasattr(a, "dtype"):
                    arr_pos.append(i)
                elif _hashable(a):
                    statics.append((i, a))
                else:
                    arr_pos = None
                    break
            if arr_pos is not None and all(_hashable(v) for v in kwargs.values()):
                cached = _cached_fwd(fn, len(raw), tuple(diff_idx),
                                     tuple(arr_pos), tuple(statics), kwargs)
        # diff positions are overwritten by dvals at call time — capture
        # None there so the closure doesn't pin those arrays
        def _fwd(*dvals,
                 _raw=tuple(None if i in diff_idx else v
                            for i, v in enumerate(raw)),
                 _di=tuple(diff_idx), _fn=fn, _kw=kwargs):
            full = list(_raw)
            for i, v in zip(_di, dvals):
                full[i] = v
            return _fn(*full, **_kw)

        if cached is not None:
            out, raw_vjp = cached(*(raw[i] for i in arr_pos))
            vjp_fn = functools.partial(_bwd_apply, raw_vjp)
        else:
            f = _fwd
            out, vjp_fn = jax.vjp(f, *(raw[i] for i in diff_idx))
    else:
        out = fn(*raw, **kwargs)

    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]

    if GLOBAL_FLAGS.get("check_nan_inf"):
        _check_nan_inf(op_name or getattr(fn, "__name__", "op"), outs)

    out_tensors = [Tensor(o, stop_gradient=not record) for o in outs]
    if record:
        node = TapeNode(
            vjp_fn,
            inputs=[args[i] for i in diff_idx],
            out_avals=[(o.shape, o.dtype) for o in outs],
            name=op_name or getattr(fn, "__name__", "op"),
            fwd_fn=_fwd,
        )
        for k, t in enumerate(out_tensors):
            t._node = node
            t._idx = k
            node.out_tensors[k] = weakref.ref(t)
    if multi:
        return tuple(out_tensors)
    return out_tensors[0]


def defop(fn: Callable, op_name: str = ""):
    """Lift a pure jax function into an eager op over Tensors."""

    def op(*args, **kwargs):
        return apply_op(fn, *args, op_name=op_name, **kwargs)

    op.__name__ = op_name or getattr(fn, "__name__", "op")
    return op
